"""Shared stdlib HTTP-server plumbing for the JSON endpoints.

One lifecycle implementation for the three servers (streaming/serve.py,
modelimport/gateway.py, ui/server.py): ThreadingHTTPServer on a daemon
thread, port-0 resolution, shutdown/close, and JSON response writing.
"""
from __future__ import annotations

import email.message
import io
import json
import math
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

#: sane default socket timeout for every outbound call: a caller passing
#: timeout=None gets THIS, never an infinite wait — no urlopen in the repo
#: may hang its caller forever (resilience-PR audit)
DEFAULT_TIMEOUT_S = 5.0


def _sanitize_nonfinite(obj, default=None):
    """Deep-copy `obj` with non-finite floats replaced by None. Objects the
    json encoder would hand to `default` (numpy scalars, exceptions, ...) are
    converted HERE too, so a default that yields a non-finite float (e.g.
    np.float32('nan').item()) is sanitized instead of re-raising on the
    second serialization pass."""
    if isinstance(obj, float):     # incl. np.float64 (a float subclass)
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _sanitize_nonfinite(v, default) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize_nonfinite(v, default) for v in obj]
    if default is not None and not isinstance(obj, (str, int, bool,
                                                    type(None))):
        converted = default(obj)
        if converted is not obj:   # guard: a no-op default must not recurse
            return _sanitize_nonfinite(converted, default)
    return obj


def json_default(obj):
    """`default=` for payloads that may carry numpy values: anything
    .tolist()-able (numpy scalars AND arrays) becomes plain Python numbers/
    lists — dumps_safe then null-s non-finite ones — and everything else
    falls back to str so a response is never dropped mid-write."""
    tolist = getattr(obj, "tolist", None)
    if callable(tolist):
        try:
            return tolist()
        except (TypeError, ValueError):
            pass
    return str(obj)


def dumps_safe(obj, default=None) -> str:
    """json.dumps that never emits bare NaN/Infinity (which JSON.parse and
    every strict decoder reject): the fast path serializes with
    allow_nan=False, and only a payload that actually contains a non-finite
    float pays the sanitizing second pass (non-finite -> null). `default`
    passes through to json.dumps (log sinks use default=str; numpy-bearing
    payloads use default=json_default)."""
    try:
        return json.dumps(obj, allow_nan=False, default=default)
    except ValueError:
        return json.dumps(_sanitize_nonfinite(obj, default), allow_nan=False,
                          default=default)


def dumps_http(obj) -> str:
    """THE serializer for HTTP payloads that may carry stats/metrics values:
    dumps_safe with the numpy-aware default pre-applied, so call sites can't
    forget the `default=json_default` half of the pairing (forgetting it
    means a numpy scalar raises TypeError mid-response — the exact bug class
    GL002 exists to prevent)."""
    return dumps_safe(obj, default=json_default)


def send_json(handler: BaseHTTPRequestHandler, status: int, obj,
              headers=None, default=None) -> None:
    payload = dumps_safe(obj, default=default).encode()
    handler.send_response(status)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(payload)))
    for k, v in (headers or {}).items():
        handler.send_header(k, str(v))
    handler.end_headers()
    handler.wfile.write(payload)


def send_text(handler: BaseHTTPRequestHandler, status: int, text,
              content_type="text/plain; charset=utf-8", headers=None) -> None:
    """Plain-text response (Prometheus exposition, trace exports)."""
    payload = text if isinstance(text, bytes) else str(text).encode()
    handler.send_response(status)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(payload)))
    for k, v in (headers or {}).items():
        handler.send_header(k, str(v))
    handler.end_headers()
    handler.wfile.write(payload)


def _client_headers(headers):
    """Outbound header dict with the current trace context injected. This is
    THE propagation choke point (graftlint GL008 keeps raw urllib out of the
    rest of the tree): every post_json/get_json call made inside a Tracer
    span carries a W3C `traceparent` header, so the receiving server's span
    joins the caller's trace."""
    hdrs = dict(headers or {})
    from ..telemetry.propagation import inject
    return inject(hdrs)


def _decode_response(data):
    if not data:
        return None
    try:
        return json.loads(data)
    except ValueError:
        # a 2xx ack with a non-JSON body ("ok") is still a success
        return data.decode(errors="replace")


# ---- resilience seams -------------------------------------------------------
# This module is THE outbound choke point (graftlint GL008), which makes it
# the one place where (a) thread-propagated Deadlines clamp every socket
# timeout, (b) RetryPolicy/CircuitBreaker compose around any hop via the
# retry=/breaker= parameters, and (c) a chaos FaultPlan intercepts requests
# for deterministic failure injection (resilience/chaos.py).

_fault_injector = None      # callable(method, url, timeout) or None


def set_fault_injector(fn):
    """Install (fn) or clear (None) the chaos interceptor; returns the
    previous one so plans can nest/restore. The injector may return None
    (pass through), return `(status, body)` for a canned response, or raise
    the injected transport error. Production code never sets this —
    resilience.chaos.FaultPlan owns the seam."""
    global _fault_injector
    prev, _fault_injector = _fault_injector, fn
    return prev


def _effective_timeout(timeout):
    """Explicit timeout (or the module default), clamped to the calling
    thread's active resilience.Deadline — a hop may never outlive its
    caller's total budget, and an already-spent budget fails fast with
    DeadlineExceededError instead of opening a socket."""
    t = DEFAULT_TIMEOUT_S if timeout is None else float(timeout)
    from ..resilience.policy import current_deadline
    dl = current_deadline()
    return t if dl is None else dl.clamp(t)


def _canned_http_error(url, status, payload):
    """An injected error status shaped exactly like urllib would raise it
    (readable body), so retry/breaker/fleet code paths can't tell chaos
    from a real failing server."""
    body = dumps_http(payload if payload is not None else {}).encode()
    return urllib.error.HTTPError(url, status, "injected fault",
                                  email.message.Message(), io.BytesIO(body))


def _with_resilience(send, retry, breaker):
    if retry is None and breaker is None:
        return send()
    from ..resilience.policy import guarded_call
    return guarded_call(send, retry=retry, breaker=breaker)


def post_json(url, obj, timeout=None, headers=None, retry=None, breaker=None):
    """Client-side JSON POST (webhook sinks, remote routers, predict
    clients): returns the decoded JSON response body, or None for an empty
    body. Serializes with dumps_http (strict JSON + numpy-aware default) and
    injects the current trace context as a `traceparent` header.

    `timeout=None` means DEFAULT_TIMEOUT_S (never an infinite socket wait),
    and every timeout is clamped to the thread's active resilience.Deadline.
    `retry` (a RetryPolicy) and `breaker` (a CircuitBreaker) make this THE
    resilient client for any hop that wants them."""
    body = dumps_http(obj).encode()
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(_client_headers(headers))

    def send():
        t = _effective_timeout(timeout)
        inj = _fault_injector
        if inj is not None:
            canned = inj("POST", url, t)
            if canned is not None:
                status, payload = canned
                if status >= 400:
                    raise _canned_http_error(url, status, payload)
                return payload
        req = urllib.request.Request(url, data=body, headers=hdrs)
        with urllib.request.urlopen(req, timeout=t) as resp:
            data = resp.read()
        return _decode_response(data)

    return _with_resilience(send, retry, breaker)


def get_json(url, timeout=None, headers=None, with_status=False,
             retry=None, breaker=None):
    """Client-side JSON GET with trace-context injection (the scrape/poll
    half of post_json — fleet collection, smoke tools, health probes).

    Default: returns the decoded body, raising urllib.error.HTTPError on
    error statuses like any urllib client. `with_status=True` returns
    `(status, decoded_body)` and decodes error-status bodies instead of
    raising — a deep-health 503 response IS the payload a fleet collector
    wants, not an exception. Timeout semantics and `retry`/`breaker` match
    post_json."""
    hdrs = _client_headers(headers)

    def send():
        t = _effective_timeout(timeout)
        inj = _fault_injector
        if inj is not None:
            canned = inj("GET", url, t)
            if canned is not None:
                status, payload = canned
                if status >= 400 and not with_status:
                    raise _canned_http_error(url, status, payload)
                return (status, payload) if with_status else payload
        req = urllib.request.Request(url, headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=t) as resp:
                status, data = resp.status, resp.read()
        except urllib.error.HTTPError as e:
            if not with_status:
                raise
            status, data = e.code, e.read()
        decoded = _decode_response(data)
        return (status, decoded) if with_status else decoded

    return _with_resilience(send, retry, breaker)


def read_body(handler: BaseHTTPRequestHandler) -> bytes:
    n = int(handler.headers.get("Content-Length", 0))
    return handler.rfile.read(n) if n else b""


class QuietHandler(BaseHTTPRequestHandler):
    """Base handler with request logging silenced and the JSON helpers."""

    def log_message(self, *a):
        pass

    def send_json(self, status, obj, headers=None, default=None):
        send_json(self, status, obj, headers, default=default)

    def send_text(self, status, text, content_type="text/plain; charset=utf-8",
                  headers=None):
        send_text(self, status, text, content_type, headers)

    def body(self):
        return read_body(self)


class _BurstTolerantHTTPServer(ThreadingHTTPServer):
    # socketserver's default listen backlog is 5: an open-loop burst (the
    # loadgen ramp, a thundering-herd reconnect) RSTs the overflow and the
    # client sees a transport fault that looks exactly like a dead server.
    # A deeper backlog turns that into queueing — admission control (429)
    # stays the one intentional shedding point.
    request_queue_size = 128


class BackgroundHttpServer:
    """Owns the ThreadingHTTPServer lifecycle; subclass-or-compose with a
    handler class (usually a QuietHandler subclass closing over the owner)."""

    def __init__(self, host="127.0.0.1", port=0):
        self.host = host
        self.port = int(port)
        self._httpd = None
        self._thread = None

    def start_with(self, handler_cls):
        self._httpd = _BurstTolerantHTTPServer((self.host, self.port),
                                               handler_cls)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"
