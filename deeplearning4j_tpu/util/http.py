"""Shared stdlib HTTP-server plumbing for the JSON endpoints.

One lifecycle implementation for the three servers (streaming/serve.py,
modelimport/gateway.py, ui/server.py): ThreadingHTTPServer on a daemon
thread, port-0 resolution, shutdown/close, and JSON response writing.
"""
from __future__ import annotations

import json
import math
import threading
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer


def _sanitize_nonfinite(obj, default=None):
    """Deep-copy `obj` with non-finite floats replaced by None. Objects the
    json encoder would hand to `default` (numpy scalars, exceptions, ...) are
    converted HERE too, so a default that yields a non-finite float (e.g.
    np.float32('nan').item()) is sanitized instead of re-raising on the
    second serialization pass."""
    if isinstance(obj, float):     # incl. np.float64 (a float subclass)
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _sanitize_nonfinite(v, default) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize_nonfinite(v, default) for v in obj]
    if default is not None and not isinstance(obj, (str, int, bool,
                                                    type(None))):
        converted = default(obj)
        if converted is not obj:   # guard: a no-op default must not recurse
            return _sanitize_nonfinite(converted, default)
    return obj


def json_default(obj):
    """`default=` for payloads that may carry numpy values: anything
    .tolist()-able (numpy scalars AND arrays) becomes plain Python numbers/
    lists — dumps_safe then null-s non-finite ones — and everything else
    falls back to str so a response is never dropped mid-write."""
    tolist = getattr(obj, "tolist", None)
    if callable(tolist):
        try:
            return tolist()
        except (TypeError, ValueError):
            pass
    return str(obj)


def dumps_safe(obj, default=None) -> str:
    """json.dumps that never emits bare NaN/Infinity (which JSON.parse and
    every strict decoder reject): the fast path serializes with
    allow_nan=False, and only a payload that actually contains a non-finite
    float pays the sanitizing second pass (non-finite -> null). `default`
    passes through to json.dumps (log sinks use default=str; numpy-bearing
    payloads use default=json_default)."""
    try:
        return json.dumps(obj, allow_nan=False, default=default)
    except ValueError:
        return json.dumps(_sanitize_nonfinite(obj, default), allow_nan=False,
                          default=default)


def dumps_http(obj) -> str:
    """THE serializer for HTTP payloads that may carry stats/metrics values:
    dumps_safe with the numpy-aware default pre-applied, so call sites can't
    forget the `default=json_default` half of the pairing (forgetting it
    means a numpy scalar raises TypeError mid-response — the exact bug class
    GL002 exists to prevent)."""
    return dumps_safe(obj, default=json_default)


def send_json(handler: BaseHTTPRequestHandler, status: int, obj,
              headers=None, default=None) -> None:
    payload = dumps_safe(obj, default=default).encode()
    handler.send_response(status)
    handler.send_header("Content-Type", "application/json")
    handler.send_header("Content-Length", str(len(payload)))
    for k, v in (headers or {}).items():
        handler.send_header(k, str(v))
    handler.end_headers()
    handler.wfile.write(payload)


def send_text(handler: BaseHTTPRequestHandler, status: int, text,
              content_type="text/plain; charset=utf-8", headers=None) -> None:
    """Plain-text response (Prometheus exposition, trace exports)."""
    payload = text if isinstance(text, bytes) else str(text).encode()
    handler.send_response(status)
    handler.send_header("Content-Type", content_type)
    handler.send_header("Content-Length", str(len(payload)))
    for k, v in (headers or {}).items():
        handler.send_header(k, str(v))
    handler.end_headers()
    handler.wfile.write(payload)


def _client_headers(headers):
    """Outbound header dict with the current trace context injected. This is
    THE propagation choke point (graftlint GL008 keeps raw urllib out of the
    rest of the tree): every post_json/get_json call made inside a Tracer
    span carries a W3C `traceparent` header, so the receiving server's span
    joins the caller's trace."""
    hdrs = dict(headers or {})
    from ..telemetry.propagation import inject
    return inject(hdrs)


def _decode_response(data):
    if not data:
        return None
    try:
        return json.loads(data)
    except ValueError:
        # a 2xx ack with a non-JSON body ("ok") is still a success
        return data.decode(errors="replace")


def post_json(url, obj, timeout=5.0, headers=None):
    """Client-side JSON POST (webhook sinks, remote routers, predict
    clients): returns the decoded JSON response body, or None for an empty
    body. Serializes with dumps_http (strict JSON + numpy-aware default) and
    injects the current trace context as a `traceparent` header."""
    body = dumps_http(obj).encode()
    hdrs = {"Content-Type": "application/json"}
    hdrs.update(_client_headers(headers))
    req = urllib.request.Request(url, data=body, headers=hdrs)
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        data = resp.read()
    return _decode_response(data)


def get_json(url, timeout=5.0, headers=None, with_status=False):
    """Client-side JSON GET with trace-context injection (the scrape/poll
    half of post_json — fleet collection, smoke tools, health probes).

    Default: returns the decoded body, raising urllib.error.HTTPError on
    error statuses like any urllib client. `with_status=True` returns
    `(status, decoded_body)` and decodes error-status bodies instead of
    raising — a deep-health 503 response IS the payload a fleet collector
    wants, not an exception."""
    req = urllib.request.Request(url, headers=_client_headers(headers))
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            status, data = resp.status, resp.read()
    except urllib.error.HTTPError as e:
        if not with_status:
            raise
        status, data = e.code, e.read()
    decoded = _decode_response(data)
    return (status, decoded) if with_status else decoded


def read_body(handler: BaseHTTPRequestHandler) -> bytes:
    n = int(handler.headers.get("Content-Length", 0))
    return handler.rfile.read(n) if n else b""


class QuietHandler(BaseHTTPRequestHandler):
    """Base handler with request logging silenced and the JSON helpers."""

    def log_message(self, *a):
        pass

    def send_json(self, status, obj, headers=None, default=None):
        send_json(self, status, obj, headers, default=default)

    def send_text(self, status, text, content_type="text/plain; charset=utf-8",
                  headers=None):
        send_text(self, status, text, content_type, headers)

    def body(self):
        return read_body(self)


class BackgroundHttpServer:
    """Owns the ThreadingHTTPServer lifecycle; subclass-or-compose with a
    handler class (usually a QuietHandler subclass closing over the owner)."""

    def __init__(self, host="127.0.0.1", port=0):
        self.host = host
        self.port = int(port)
        self._httpd = None
        self._thread = None

    def start_with(self, handler_cls):
        self._httpd = ThreadingHTTPServer((self.host, self.port), handler_cls)
        self.port = self._httpd.server_address[1]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self):
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None

    @property
    def url(self):
        return f"http://{self.host}:{self.port}"
