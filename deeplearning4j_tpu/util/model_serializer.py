"""Model checkpointing: zip container with configuration.json +
coefficients.bin + updaterState.bin.

Reference: util/ModelSerializer.java (:43 writeModel, :83-135 — zip entries
`configuration.json` :94, `coefficients.bin` flattened params :99-108,
`updaterState.bin` :121-135) and util/ModelGuesser.java (type sniffing).

Same zip contract, adapted: coefficients.bin stores an .npz of the param
pytree (exact per-tensor layout — richer than the reference's single flat
vector, but a flat view export is also provided for parity), updaterState.bin
stores the optax state. A `format.json` entry records model class + dtype.
"""
from __future__ import annotations

import io
import json
import zipfile

import numpy as np
import jax
import jax.numpy as jnp


CONFIG_ENTRY = "configuration.json"
COEFFICIENTS_ENTRY = "coefficients.bin"
UPDATER_ENTRY = "updaterState.bin"
FORMAT_ENTRY = "format.json"
STATE_ENTRY = "state.bin"
NORMALIZER_ENTRY = "normalizer.json"


def _flatten_tree(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k in sorted(tree.keys()):
            out.update(_flatten_tree(tree[k], f"{prefix}{k}/"))
    elif isinstance(tree, (list, tuple)):
        for i, v in enumerate(tree):
            out.update(_flatten_tree(v, f"{prefix}{i}/"))
    elif tree is None:
        pass
    else:
        out[prefix[:-1]] = np.asarray(tree)
    return out


def _tree_to_npz_bytes(tree):
    flat = _flatten_tree(tree)
    buf = io.BytesIO()
    np.savez(buf, **{k.replace("/", "__SLASH__"): v for k, v in flat.items()})
    return buf.getvalue()


def _npz_bytes_to_flat(data):
    buf = io.BytesIO(data)
    npz = np.load(buf)
    return {k.replace("__SLASH__", "/"): npz[k] for k in npz.files}


def _writestr(zf, name, data):
    """Deterministic zip entry: fixed DOS timestamp (zipfile.writestr with a
    bare name stamps wall time, so the same model state would serialize to
    different bytes second over second). Identical state -> identical zip
    bytes is what makes async-vs-sync checkpoints comparable and manifest
    digests stable."""
    info = zipfile.ZipInfo(name, date_time=(1980, 1, 1, 0, 0, 0))
    info.compress_type = zipfile.ZIP_DEFLATED
    info.external_attr = 0o600 << 16
    zf.writestr(info, data)


def _rebuild_like(template, flat, prefix=""):
    """Rebuild a pytree in the shape of `template` from the flat name->array map."""
    if isinstance(template, dict):
        return {k: _rebuild_like(template[k], flat, f"{prefix}{k}/")
                for k in template.keys()}
    if isinstance(template, (list, tuple)):
        vals = [_rebuild_like(v, flat, f"{prefix}{i}/") for i, v in enumerate(template)]
        return type(template)(vals) if not isinstance(template, tuple) else tuple(vals)
    if template is None:
        return None
    key = prefix[:-1]
    return jnp.asarray(flat[key]) if key in flat else template


class ModelSerializer:
    @staticmethod
    def write_model(model, path, save_updater=True, normalizer=None):
        """`normalizer` (an etl.DataNormalizer fitted on the training data)
        rides in the zip as `normalizer.json`, so serving applies the
        identical preprocessing (reference: ModelSerializer
        .addNormalizerToModel / restoreNormalizerFromFile).

        A filesystem `path` is published DURABLY (util.fs.atomic_write:
        temp + fsync + os.replace + dir fsync — a crash mid-save leaves the
        previous model, never a torn zip); a file object is written
        directly (the async checkpoint writer serializes to memory first).
        `model` may also be a host snapshot proxy carrying a `model_class`
        attribute instead of being a live network (train.fault_tolerance)."""
        from ..nn.multilayer.network import MultiLayerNetwork
        from ..nn.graph.graph import ComputationGraph
        is_graph = isinstance(model, ComputationGraph) or \
            getattr(model, "model_class", None) == "ComputationGraph"
        # int8-quantized serving weights (nn/quant.py): zips stay f32 — the
        # host-side backup rebuilds the full-precision tree, so a restore
        # (or a later re-quantized deploy) never compounds quantization
        params = model.params
        wq = getattr(model, "_wq", None)
        if wq is not None:
            params = wq.restore_params(params)
        target = path if hasattr(path, "write") else io.BytesIO()
        with zipfile.ZipFile(target, "w", zipfile.ZIP_DEFLATED) as zf:
            _writestr(zf, FORMAT_ENTRY, json.dumps({
                "model_class": "ComputationGraph" if is_graph else "MultiLayerNetwork",
                "dtype": str(model.conf.dtype),
                "framework": "deeplearning4j-tpu",
                "version": 1,
            }))
            _writestr(zf, CONFIG_ENTRY, model.conf.to_json())
            _writestr(zf, COEFFICIENTS_ENTRY, _tree_to_npz_bytes(params))
            if model.states:
                _writestr(zf, STATE_ENTRY, _tree_to_npz_bytes(model.states))
            if save_updater and model.opt_state is not None:
                # optax states are namedtuple pytrees: store leaves positionally.
                # ZeRO-sharded updater state (parallel/zero.py) is converted
                # to its CANONICAL per-param layout first, so the zip stays
                # topology-independent: it restores into an unsharded model
                # or re-shards for any replica count.
                opt_state = model.opt_state
                zero = getattr(model, "_zero", None)
                if zero is not None:
                    opt_state = zero.to_canonical(opt_state, model.params)
                leaves = jax.tree_util.tree_leaves(opt_state)
                arrs = {f"leaf{i}": np.asarray(l) for i, l in enumerate(leaves)}
                buf = io.BytesIO()
                np.savez(buf, **arrs)
                _writestr(zf, UPDATER_ENTRY, buf.getvalue())
            if normalizer is not None:
                _writestr(zf, NORMALIZER_ENTRY, normalizer.to_json())
        if target is not path:
            from .fs import atomic_write
            atomic_write(path, target.getvalue())
        return path

    @staticmethod
    def add_normalizer(path, normalizer):
        """Append/replace the normalizer entry of an existing model zip
        (reference: ModelSerializer.addNormalizerToModel). zipfile append
        mode would duplicate the entry name, so the archive is rebuilt in
        memory and published through util.fs.atomic_write — rewriting in
        place would truncate the zip before the coefficients are
        re-written, and a non-durable replace could still destroy the
        trained model across a power loss."""
        from .fs import atomic_write
        with zipfile.ZipFile(path, "r") as zf:
            entries = [(n, zf.read(n)) for n in zf.namelist()
                       if n != NORMALIZER_ENTRY]
        buf = io.BytesIO()
        with zipfile.ZipFile(buf, "w", zipfile.ZIP_DEFLATED) as zf:
            for n, data in entries:
                _writestr(zf, n, data)
            _writestr(zf, NORMALIZER_ENTRY, normalizer.to_json())
        atomic_write(path, buf.getvalue())
        return path

    @staticmethod
    def restore_normalizer(path):
        """The zip's fitted DataNormalizer, or None when the model was saved
        without one (reference: ModelSerializer.restoreNormalizerFromFile)."""
        from ..etl.normalizer import DataNormalizer
        with zipfile.ZipFile(path, "r") as zf:
            if NORMALIZER_ENTRY not in zf.namelist():
                return None
            return DataNormalizer.from_json(zf.read(NORMALIZER_ENTRY).decode())

    @staticmethod
    def restore_multi_layer_network(path, load_updater=True):
        from ..nn.multilayer.network import MultiLayerNetwork
        from ..nn.conf.configuration import MultiLayerConfiguration
        with zipfile.ZipFile(path, "r") as zf:
            conf = MultiLayerConfiguration.from_json(zf.read(CONFIG_ENTRY).decode())
            net = MultiLayerNetwork(conf).init()
            ModelSerializer._restore_into(net, zf, load_updater)
        return net

    @staticmethod
    def restore_computation_graph(path, load_updater=True):
        from ..nn.graph.graph import ComputationGraph
        from ..nn.conf.graph_configuration import ComputationGraphConfiguration
        with zipfile.ZipFile(path, "r") as zf:
            conf = ComputationGraphConfiguration.from_json(zf.read(CONFIG_ENTRY).decode())
            net = ComputationGraph(conf).init()
            ModelSerializer._restore_into(net, zf, load_updater)
        return net

    @staticmethod
    def _restore_into(net, zf, load_updater):
        flat = _npz_bytes_to_flat(zf.read(COEFFICIENTS_ENTRY))
        net.params = _rebuild_like(net.params, flat)
        names = set(zf.namelist())
        if STATE_ENTRY in names:
            sflat = _npz_bytes_to_flat(zf.read(STATE_ENTRY))
            net.states = _rebuild_like(net.states, sflat)
        if load_updater and UPDATER_ENTRY in names:
            buf = io.BytesIO(zf.read(UPDATER_ENTRY))
            npz = np.load(buf)
            stored = [npz[f"leaf{i}"] for i in range(len(npz.files))]
            leaves, treedef = jax.tree_util.tree_flatten(net.opt_state)
            if len(stored) == len(leaves):
                new_leaves = [jnp.asarray(s, l.dtype) if hasattr(l, "dtype") else s
                              for s, l in zip(stored, leaves)]
                net.opt_state = jax.tree_util.tree_unflatten(treedef, new_leaves)

    @staticmethod
    def read_format(path):
        """Read the zip's format.json (model class, dtype, version) without
        deserializing any weights — cheap metadata sniff for model registries."""
        with zipfile.ZipFile(path, "r") as zf:
            if FORMAT_ENTRY in zf.namelist():
                return json.loads(zf.read(FORMAT_ENTRY).decode())
            return {"model_class": None, "framework": "unknown"}

    @staticmethod
    def restore(path, load_updater=True):
        """Sniff the model type and load it (reference: util/ModelGuesser.java)."""
        with zipfile.ZipFile(path, "r") as zf:
            if FORMAT_ENTRY in zf.namelist():
                fmt = json.loads(zf.read(FORMAT_ENTRY).decode())
                cls = fmt.get("model_class")
            else:
                cfg = json.loads(zf.read(CONFIG_ENTRY).decode())
                cls = ("ComputationGraph" if "ComputationGraph" in cfg.get("format", "")
                       else "MultiLayerNetwork")
        if cls == "ComputationGraph":
            return ModelSerializer.restore_computation_graph(path, load_updater)
        return ModelSerializer.restore_multi_layer_network(path, load_updater)


class ModelGuesser:
    """(reference: deeplearning4j-core util/ModelGuesser.java)"""

    @staticmethod
    def load_model_guess(path):
        return ModelSerializer.restore(path)
