"""Durable filesystem primitives: fsync'd atomic publish + dir manifests.

`os.replace` alone is atomic in the *namespace* but not durable: POSIX only
promises the rename survives a crash if the file's data was fsync'd before
the rename and the parent directory's entry after it. Without both, a power
loss can publish a name that points at zero-length or stale data — the
crash-after-replace bug that turns "the newest checkpoint" into a torn zip.
This module is the one place that does the fsync dance correctly
(graftlint GL013 `non-durable-publish` keeps bare `os.replace` publishers
from growing back elsewhere):

- ``atomic_write(path, data)``      — bytes -> temp file (same dir) ->
  fsync -> `os.replace` -> fsync(parent dir).
- ``publish_file(tmp, final)``      — same dance for a temp file the caller
  already streamed to (downloads).
- ``atomic_publish_dir(tmp, final)``— fsync every file and directory under
  `tmp`, `os.replace` the whole dir into place, fsync the parent — the
  checkpoint-directory publish.
- ``write_manifest`` / ``read_manifest`` / ``verify_manifest`` — a
  `MANIFEST.json` written *last* (per-file sha256 + byte sizes + caller
  metadata); a directory artifact without a valid manifest is by
  definition incomplete, and restore-time hash verification is the ONLY
  honest torn-write detector (write-time read-back is served from the page
  cache, which happily returns the bytes the crash will never persist).

Disk-fault seam: every byte written through ``write_bytes`` (and so through
``atomic_write``/``write_manifest``) passes the installed fault injector
first — `resilience.chaos.FaultPlan` installs its `torn_write` / `bitflip`
/ `enospc` / `slow_disk` rules here, so checkpoint chaos tests corrupt
exactly the file they script, deterministically, with zero monkeypatching.

Stdlib-only on purpose: `analysis/` (the jax-free graftlint entry) and
`tools/ckpt_doctor.py` import this module without paying the framework
import.
"""
from __future__ import annotations

import hashlib
import json
import os
import tempfile

MANIFEST_NAME = "MANIFEST.json"
MANIFEST_VERSION = 1

# ---------------------------------------------------------------------------
# disk-fault seam (resilience.chaos installs here; None in production)
# ---------------------------------------------------------------------------

_fault_injector = None


def set_fs_fault_injector(fn):
    """Install `fn(op, path, data) -> data` as the write-path interceptor
    (may raise OSError, return corrupted bytes, or advance the injected
    clock); returns the previous injector so plans can nest/uninstall."""
    global _fault_injector
    prev = _fault_injector
    _fault_injector = fn
    return prev


def _inject(op, path, data=None):
    fn = _fault_injector
    if fn is None:
        return data
    return fn(op, path, data)


# ---------------------------------------------------------------------------
# fsync + atomic publish
# ---------------------------------------------------------------------------

def fsync_file(path):
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def fsync_dir(path):
    """fsync a directory: makes the entries (renames, creates) durable."""
    fd = os.open(path, os.O_RDONLY)
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def write_bytes(path, data, fsync=True):
    """Write `data` (bytes or str) to `path` through the fault seam, then
    flush+fsync. NOT atomic — callers publishing an artifact want
    `atomic_write` (single file) or tmp-dir + `atomic_publish_dir`."""
    if isinstance(data, str):
        data = data.encode("utf-8")
    path = os.fspath(path)
    data = _inject("write", path, data)
    with open(path, "wb") as f:
        f.write(data)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    return path


def atomic_write(path, data, fsync=True):
    """Durably publish `data` at `path`: temp file in the same directory
    (same filesystem, so the replace stays atomic), fsync, `os.replace`,
    fsync the parent directory. A reader sees the old content or the new
    content, never a mix, even across power loss."""
    path = os.fspath(path)
    parent = os.path.dirname(os.path.abspath(path))
    fd, tmp = tempfile.mkstemp(prefix=os.path.basename(path) + ".",
                               suffix=".tmp", dir=parent)
    os.close(fd)
    try:
        write_bytes(tmp, data, fsync=fsync)
        os.replace(tmp, path)
        if fsync:
            fsync_dir(parent)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise
    return path


def publish_file(tmp, final, fsync=True):
    """Durably publish an already-written temp file: fsync it, `os.replace`
    into place, fsync the parent directory (the streamed-download case,
    where the caller wrote `tmp` incrementally)."""
    tmp, final = os.fspath(tmp), os.fspath(final)
    if fsync:
        fsync_file(tmp)
    os.replace(tmp, final)
    if fsync:
        fsync_dir(os.path.dirname(os.path.abspath(final)))
    return final


def atomic_publish_dir(tmp_dir, final_dir, fsync=True):
    """Durably publish a fully-written directory: fsync every file and every
    directory under `tmp_dir` (bottom-up is unnecessary — fsync order
    within the tree doesn't matter as long as ALL of it precedes the
    rename), `os.replace` the directory into place, fsync the parent."""
    tmp_dir, final_dir = os.fspath(tmp_dir), os.fspath(final_dir)
    if fsync:
        for dirpath, _dirnames, filenames in os.walk(tmp_dir):
            for name in filenames:
                fsync_file(os.path.join(dirpath, name))
            fsync_dir(dirpath)
    os.replace(tmp_dir, final_dir)
    if fsync:
        fsync_dir(os.path.dirname(os.path.abspath(final_dir)))
    return final_dir


def quarantine_dir(root, name, prefix="corrupt-"):
    """Move `root/name` aside as `root/<prefix><name>` (suffixing `.2`,
    `.3`... on collision) and return the new basename — the one rename-aside
    scheme shared by the trainer's restore walk and tools/ckpt_doctor.py."""
    src = os.path.join(root, name)
    dst = os.path.join(root, f"{prefix}{name}")
    n = 1
    while os.path.exists(dst):
        n += 1
        dst = os.path.join(root, f"{prefix}{name}.{n}")
    os.rename(src, dst)
    return os.path.basename(dst)


# ---------------------------------------------------------------------------
# manifests
# ---------------------------------------------------------------------------

def sha256_bytes(data) -> str:
    if isinstance(data, str):
        data = data.encode("utf-8")
    return hashlib.sha256(data).hexdigest()


def sha256_file(path, chunk=1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            block = f.read(chunk)
            if not block:
                break
            h.update(block)
    return h.hexdigest()


def _iter_rel_files(dirpath):
    for root, _dirs, files in os.walk(dirpath):
        for name in sorted(files):
            full = os.path.join(root, name)
            rel = os.path.relpath(full, dirpath).replace(os.sep, "/")
            if rel != MANIFEST_NAME:
                yield rel, full


def write_manifest(dirpath, files=None, fsync=True, **meta):
    """Write `dirpath/MANIFEST.json` LAST (after every data file): per-file
    sha256 + byte sizes plus caller metadata (step, wall time, topology...).

    `files`: {relname: (sha256_hex, n_bytes)} computed from the IN-MEMORY
    content the caller just wrote — the manifest then records what the
    writer *intended*, so a torn/bit-flipped on-disk file fails
    verification later. When None, the directory's current contents are
    hashed by read-back (third-party serializers like orbax, or an
    operator re-blessing a repaired dir via ckpt_doctor)."""
    if files is None:
        files = {rel: (sha256_file(full), os.path.getsize(full))
                 for rel, full in _iter_rel_files(dirpath)}
    doc = dict(meta)
    doc["version"] = MANIFEST_VERSION
    doc["files"] = {rel: {"sha256": digest, "bytes": int(size)}
                    for rel, (digest, size) in sorted(files.items())}
    atomic_write(os.path.join(dirpath, MANIFEST_NAME),
                 json.dumps(doc, indent=1, sort_keys=True) + "\n",
                 fsync=fsync)
    return doc


def read_manifest(dirpath):
    """Parse `dirpath/MANIFEST.json`; raises (OSError/ValueError) when
    missing or unreadable — the caller decides what incomplete means."""
    with open(os.path.join(dirpath, MANIFEST_NAME), encoding="utf-8") as f:
        return json.load(f)


def verify_manifest(dirpath, hash=True):
    """(ok, errors) for a manifested directory: the manifest must exist and
    parse, and every listed file must exist with the recorded byte size
    (and, with `hash=True`, the recorded sha256). Extra files NOT in the
    manifest are ignored — strays don't corrupt the listed artifact."""
    errors = []
    try:
        doc = read_manifest(dirpath)
    except OSError as e:
        return False, [f"no readable {MANIFEST_NAME}: {e}"]
    except ValueError as e:
        return False, [f"{MANIFEST_NAME} is not valid JSON: {e}"]
    entries = doc.get("files")
    if not isinstance(entries, dict) or not entries:
        return False, [f"{MANIFEST_NAME} lists no files"]
    for rel, meta in sorted(entries.items()):
        full = os.path.join(dirpath, rel.replace("/", os.sep))
        if not os.path.isfile(full):
            errors.append(f"{rel}: missing")
            continue
        size = os.path.getsize(full)
        if size != meta.get("bytes"):
            errors.append(f"{rel}: size {size} != manifest {meta.get('bytes')}"
                          f" (torn write)")
            continue
        if hash and sha256_file(full) != meta.get("sha256"):
            errors.append(f"{rel}: sha256 mismatch (corrupt content)")
    return (not errors), errors
