"""Time sources for cross-node stats timestamps.

Reference: spark/dl4j-spark/.../time/{TimeSource.java, TimeSourceProvider.java,
NTPTimeSource.java, SystemClockTimeSource.java} — Spark stats events are
stamped with NTP-corrected wall time so phase timings line up across nodes.

TPU redesign: same SPI. NTPTimeSource implements the SNTP (RFC 4330) client
exchange over UDP; in the zero-egress build environment the query fails and
the source falls back to the system clock with offset 0 (recorded in
`last_error`) — the offset arithmetic is exercised in tests by injecting a
fake response. TimeSourceProvider mirrors the reference's singleton +
system-property override with an env var.
"""
from __future__ import annotations

import os
import socket
import struct
import time

_NTP_EPOCH_DELTA = 2208988800  # seconds between 1900 (NTP) and 1970 (unix)


class TimeSource:
    def current_time_millis(self) -> int:
        raise NotImplementedError

    def monotonic(self) -> float:
        """Monotonic seconds for durations/deadlines (never NTP-corrected:
        an offset step mid-measurement would corrupt every latency)."""
        return time.monotonic()


class SystemClockTimeSource(TimeSource):
    """(reference: time/SystemClockTimeSource.java)"""

    def current_time_millis(self):
        return int(time.time() * 1000)


class NTPTimeSource(TimeSource):
    """(reference: time/NTPTimeSource.java — queries an NTP server every
    `update_frequency_ms` and applies the measured offset to wall time)."""

    DEFAULT_SERVER = "0.pool.ntp.org"

    def __init__(self, server=None, timeout=2.0, update_frequency_ms=1800000):
        self.server = server or os.environ.get("DL4J_TPU_NTP_SERVER",
                                               self.DEFAULT_SERVER)
        self.timeout = float(timeout)
        self.update_frequency_ms = int(update_frequency_ms)
        self.offset_ms = 0
        self.last_error = None
        self._last_update = 0.0
        self._maybe_update()

    @staticmethod
    def _parse_offset_ms(packet, t_send, t_recv):
        """SNTP offset = ((T2 - T1) + (T3 - T4)) / 2 (RFC 4330)."""
        if len(packet) < 48:
            raise ValueError("short NTP packet")
        sec2, frac2 = struct.unpack("!II", packet[32:40])   # receive ts
        sec3, frac3 = struct.unpack("!II", packet[40:48])   # transmit ts
        t2 = sec2 - _NTP_EPOCH_DELTA + frac2 / 2 ** 32
        t3 = sec3 - _NTP_EPOCH_DELTA + frac3 / 2 ** 32
        return ((t2 - t_send) + (t3 - t_recv)) / 2 * 1000.0

    def _query(self):
        pkt = bytearray(48)
        pkt[0] = 0x1B  # LI=0, VN=3, mode=3 (client)
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.settimeout(self.timeout)
            t_send = time.time()
            s.sendto(bytes(pkt), (self.server, 123))
            data, _ = s.recvfrom(512)
            t_recv = time.time()
        return self._parse_offset_ms(data, t_send, t_recv)

    def _maybe_update(self):
        now = time.time()
        if (now - self._last_update) * 1000 < self.update_frequency_ms and \
                self._last_update > 0:
            return
        self._last_update = now
        try:
            self.offset_ms = self._query()
            self.last_error = None
        except (OSError, ValueError) as e:
            # no egress / timeout / malformed reply: system clock fallback
            self.last_error = e

    def current_time_millis(self):
        self._maybe_update()
        return int(time.time() * 1000 + self.offset_ms)


class ManualClock(TimeSource):
    """Deterministic test clock: wall and monotonic time advance only via
    `advance()`, so deadline/latency/telemetry tests stop being wall-clock
    flaky. Install with TimeSourceProvider.set_instance(ManualClock())."""

    def __init__(self, start_s=1_000_000.0):
        self._now = float(start_s)

    def advance(self, seconds):
        self._now += float(seconds)
        return self._now

    def current_time_millis(self):
        return int(self._now * 1000)

    def monotonic(self):
        return self._now


class TimeSourceProvider:
    """(reference: time/TimeSourceProvider.java — singleton chosen by system
    property; here the DL4J_TPU_TIMESOURCE env var: 'ntp' or 'system')."""

    _instance = None

    @classmethod
    def get_instance(cls) -> TimeSource:
        if cls._instance is None:
            kind = os.environ.get("DL4J_TPU_TIMESOURCE", "system").lower()
            cls._instance = (NTPTimeSource() if kind == "ntp"
                             else SystemClockTimeSource())
        return cls._instance

    @classmethod
    def set_instance(cls, time_source):
        """Install a specific source (e.g. ManualClock in tests); pass None
        to fall back to the env-var-selected default on next use."""
        cls._instance = time_source

    @classmethod
    def reset(cls):
        cls._instance = None


# ---- module-level helpers: the single funnel for telemetry timestamps ------
# Everything observability-facing (stats reports, serving metrics, spans,
# registry deploy times) calls these instead of bare time.time(), so one
# set_instance(ManualClock()) makes a whole test run deterministic.

def now_s() -> float:
    """Wall-clock seconds (epoch) from the configured TimeSource."""
    return TimeSourceProvider.get_instance().current_time_millis() / 1000.0


def now_ms() -> int:
    """Wall-clock milliseconds (epoch) from the configured TimeSource."""
    return TimeSourceProvider.get_instance().current_time_millis()


def monotonic_s() -> float:
    """Monotonic seconds from the configured TimeSource (durations only)."""
    return TimeSourceProvider.get_instance().monotonic()
