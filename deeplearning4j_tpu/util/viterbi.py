"""Viterbi decoder for denoising label sequences.

Reference: deeplearning4j-nn/.../util/Viterbi.java — a Markov-chain smoother
over classifier outcome sequences: states tend to persist (self-transition
probability `meta_stability`), observations are correct with probability
`p_correct`. The reference's dynamic program leaves its backpointer matrix
unfilled (a long-standing upstream bug); this implementation keeps the same
constructor/decode contract but runs the standard, correct Viterbi recursion
with backtracking.
"""
from __future__ import annotations

import numpy as np


class Viterbi:
    def __init__(self, possible_labels, meta_stability=0.9, p_correct=0.99):
        self.possible_labels = np.asarray(possible_labels)
        self.states = int(self.possible_labels.shape[0])
        if self.states < 2:
            raise ValueError("need at least 2 states")
        self.meta_stability = float(meta_stability)
        self.p_correct = float(p_correct)
        # log transition matrix: diagonal = stay, off-diagonal splits the rest
        off = (1.0 - self.meta_stability) / (self.states - 1)
        T = np.full((self.states, self.states), np.log(off))
        np.fill_diagonal(T, np.log(self.meta_stability))
        self._logT = T
        # log emission: observed == state with p_correct
        self._log_correct = np.log(self.p_correct)
        self._log_incorrect = np.log((1.0 - self.p_correct) / (self.states - 1))

    def _to_outcomes(self, labels):
        labels = np.asarray(labels)
        if labels.ndim == 2 and labels.shape[1] > 1:  # binary label matrix
            return np.argmax(labels, axis=1)
        return labels.reshape(-1).astype(int)

    def decode(self, labels, binary_label_matrix=True):
        """Returns (log_likelihood, decoded_sequence). `labels` is either a
        [T, states] one-hot matrix (binary_label_matrix=True, reference
        default) or a length-T outcome vector."""
        obs = self._to_outcomes(labels) if binary_label_matrix else \
            np.asarray(labels).reshape(-1).astype(int)
        frames = len(obs)
        if frames == 0:
            return 0.0, np.zeros((0,), int)
        S = self.states
        emit = np.full((frames, S), self._log_incorrect)
        emit[np.arange(frames), obs] = self._log_correct
        V = np.zeros((frames, S))
        ptr = np.zeros((frames, S), int)
        V[0] = -np.log(S) + emit[0]
        for t in range(1, frames):
            scores = V[t - 1][:, None] + self._logT  # [from, to]
            ptr[t] = np.argmax(scores, axis=0)
            V[t] = scores[ptr[t], np.arange(S)] + emit[t]
        path = np.zeros(frames, int)
        path[-1] = int(np.argmax(V[-1]))
        for t in range(frames - 2, -1, -1):
            path[t] = ptr[t + 1][path[t + 1]]
        return float(np.max(V[-1])), self.possible_labels[path]
