"""Concurrency utilities.

Reference: deeplearning4j-core parallelism/ — MagicQueue.java (multi-device
batch distribution queue: one bounded queue per device, round-robin put,
device-affine take), AsyncIterator.java (background-thread prefetch over any
iterator), ConcurrentHashSet.java.

On TPU the JAX dispatch queue already overlaps host and device work; these
remain useful for host-side input pipelines feeding multiple logical shards.
"""
from __future__ import annotations

import collections
import queue
import threading

from .time_source import monotonic_s


class AtomicCounter:
    """Lock-protected counter shared by serving metrics and the inference
    servers (the `served` counter was previously mutated bare from concurrent
    handler threads — a lost-update data race under ThreadingHTTPServer)."""

    def __init__(self, value=0):
        self._value = int(value)   # guarded by: self._lock
        self._lock = threading.Lock()

    def add(self, n=1):
        with self._lock:
            self._value += n
            return self._value

    def get(self):
        with self._lock:
            return self._value

    @property
    def value(self):
        return self.get()


class MagicQueue:
    """Round-robin distribution of items to per-worker bounded queues
    (reference: parallelism/MagicQueue.java — mode SEQUENTIAL round-robin).

    `close()` is deterministic: every taker currently blocked in `poll` —
    however many per worker — wakes and returns None once its queue is empty;
    items enqueued before the close remain pollable (drain semantics). The
    previous implementation pushed one sentinel per worker queue, so with two
    concurrent takers on one worker only one of them ever unblocked."""

    def __init__(self, n_workers, capacity=8):
        self.n_workers = int(n_workers)
        # capacity<=0 means unbounded, matching the queue.Queue(maxsize=0)
        # semantics this class previously delegated to
        self._capacity = int(capacity) if capacity > 0 else float("inf")
        self._queues = [collections.deque() for _ in range(self.n_workers)]
        self._put_idx = 0
        self._idx_lock = threading.Lock()   # only the round-robin counter
        self._closed = False
        # per-worker locks (like the per-worker stdlib queues this replaces):
        # traffic on one worker never contends with another's
        self._locks = [threading.Lock() for _ in range(self.n_workers)]
        self._not_empty = [threading.Condition(lk) for lk in self._locks]
        self._not_full = [threading.Condition(lk) for lk in self._locks]

    def add(self, item):
        with self._idx_lock:
            idx = self._put_idx
            self._put_idx = (self._put_idx + 1) % self.n_workers
        with self._locks[idx]:
            if self._closed:
                raise RuntimeError("MagicQueue is closed")
            while len(self._queues[idx]) >= self._capacity:
                self._not_full[idx].wait()
                if self._closed:
                    raise RuntimeError("MagicQueue is closed")
            self._queues[idx].append(item)
            self._not_empty[idx].notify()

    put = add

    def poll(self, worker, timeout=None):
        """Take the next item for `worker` (device-affine take). Returns None
        on timeout, or — once the queue is closed and drained — immediately.

        The deadline reads the injected util.time_source clock, so a test
        that pre-advances a ManualClock past the deadline gets None with
        zero real blocking. The condition wait itself is real-time: if a
        full wait slice elapses with no wake-up and no clock progress (a
        frozen ManualClock can never expire the deadline on its own), the
        poll honors the real elapsed time and returns None instead of
        spinning forever."""
        deadline = None if timeout is None else monotonic_s() + timeout
        with self._locks[worker]:
            q = self._queues[worker]
            while not q:
                if self._closed:
                    return None
                if deadline is None:
                    self._not_empty[worker].wait()
                    continue
                remaining = deadline - monotonic_s()
                if remaining <= 0:
                    return None
                if not self._not_empty[worker].wait(remaining) and not q:
                    return None   # real slice elapsed, nothing arrived
            item = q.popleft()
            self._not_full[worker].notify()   # one pop frees one slot
            return item

    def drain(self, worker):
        """Pop and return everything currently queued for `worker`."""
        with self._locks[worker]:
            items = list(self._queues[worker])
            self._queues[worker].clear()
            self._not_full[worker].notify_all()
            return items

    @property
    def closed(self):
        return self._closed

    def size(self, worker=None):
        if worker is not None:
            with self._locks[worker]:
                return len(self._queues[worker])
        total = 0
        for w in range(self.n_workers):
            with self._locks[w]:
                total += len(self._queues[w])
        return total

    def close(self):
        """Stop accepting new items and wake every blocked taker (and any
        producer blocked on a full queue, which then raises). Setting the
        flag and notifying under each worker's lock guarantees no waiter
        misses the wake-up."""
        for w in range(self.n_workers):
            with self._locks[w]:
                self._closed = True
                self._not_empty[w].notify_all()
                self._not_full[w].notify_all()


class AsyncIterator:
    """Background-thread prefetch over any iterator (reference:
    parallelism/AsyncIterator.java)."""

    _SENTINEL = object()

    def __init__(self, iterator, buffer_size=8):
        self._queue = queue.Queue(maxsize=buffer_size)
        self._error = None

        def run():
            try:
                for item in iterator:
                    self._queue.put(item)
            except BaseException as e:  # propagate to consumer
                self._error = e
            finally:
                self._queue.put(self._SENTINEL)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if getattr(self, "_done", False):  # keep raising after exhaustion
            raise StopIteration
        item = self._queue.get()
        if item is self._SENTINEL:
            self._done = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item


class ConcurrentHashSet:
    """(reference: parallelism/ConcurrentHashSet.java)"""

    def __init__(self):
        self._set = set()          # guarded by: self._lock
        self._lock = threading.Lock()

    def add(self, item):
        with self._lock:
            if item in self._set:
                return False
            self._set.add(item)
            return True

    def remove(self, item):
        with self._lock:
            self._set.discard(item)

    def __contains__(self, item):
        with self._lock:
            return item in self._set

    def __len__(self):
        with self._lock:
            return len(self._set)


# ---------------------------------------------------------------------------
# lock sanitizer — the runtime half of the static GL018–GL020 rules
# ---------------------------------------------------------------------------
# The static pass (analysis/concurrency.py) proves what it can see; this is
# the ThreadSanitizer-style dynamic check for what it can't: install() swaps
# threading.Lock/RLock for a wrapping factory, so every lock created AFTER
# the swap tracks per-thread held-sets, the pairwise acquisition-order graph
# (an A->B edge plus a B->A edge observed at runtime = a real deadlock
# candidate, reported once per pair), wait/hold timing into the telemetry
# registry (lock_wait_ms / lock_hold_ms / lock_order_violations_total), and
# an optional long-hold watchdog. Off (the default) it is ZERO overhead:
# nothing is patched and locks are plain _thread primitives. The smoke arcs
# run with it installed and assert zero violations; /debug/locks serves
# table() live.

import itertools
import os as _os
import sys as _sys

#: the real factories, captured at import so SanitizedLock's inner locks and
#: the sanitizer's own bookkeeping can never recurse into the wrapper
_ORIG_LOCK = threading.Lock
_ORIG_RLOCK = threading.RLock


class SanitizedLock:
    """Drop-in threading.Lock/RLock wrapper that reports to a LockSanitizer.

    Supports the full lock protocol (context manager, acquire/release with
    blocking/timeout, locked) plus the private Condition protocol
    (_is_owned/_release_save/_acquire_restore), so Condition objects built
    on a sanitized lock — including threading.Condition() defaults created
    after install() — keep working, and their wait() cycles are tracked as
    a full release + re-acquire."""

    def __init__(self, sanitizer, reentrant, name, site):
        self._san = sanitizer
        self._reentrant = bool(reentrant)
        self._inner = _ORIG_RLOCK() if reentrant else _ORIG_LOCK()
        self.name = name
        self.site = site         # creation file:line — the histogram label
        self._owner = None       # thread ident; written only by the owner
        self._count = 0          # recursion depth;   "      "     "
        self._acquired_mono = None

    # -- lock protocol -------------------------------------------------------
    def acquire(self, blocking=True, timeout=-1):
        san = self._san
        if not san.tracking():
            return self._inner.acquire(blocking, timeout)
        me = threading.get_ident()
        if self._reentrant and self._owner == me:
            ok = self._inner.acquire(blocking, timeout)
            if ok:
                self._count += 1       # re-entry: no wait, no new edges
            return ok
        t0 = monotonic_s()
        ok = self._inner.acquire(blocking, timeout)
        if not ok:
            return ok
        self._owner, self._count = me, 1
        self._acquired_mono = monotonic_s()
        san._acquired(self, self._acquired_mono - t0)
        return ok

    def release(self):
        san = self._san
        if not san.tracking() or self._owner != threading.get_ident():
            # untracked, or acquired while tracking was off/busy
            self._inner.release()
            return
        self._count -= 1
        if self._count == 0:
            self._owner = None
            t = self._acquired_mono
            self._acquired_mono = None
            san._released(self, None if t is None else monotonic_s() - t)
        self._inner.release()

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<SanitizedLock {self.name} " \
               f"{'RLock' if self._reentrant else 'Lock'}>"

    # -- Condition protocol (threading.Condition probes for these) ----------
    def _is_owned(self):
        if self._reentrant:
            return self._inner._is_owned()
        # stdlib fallback semantics for plain Locks: "owned" = "held by
        # anyone"; mirrored so Condition behaves identically either way
        if self._inner.acquire(False):
            self._inner.release()
            return False
        return True

    def _release_save(self):
        """Full release for Condition.wait — unwind tracking completely."""
        if self._san.tracking() and self._owner == threading.get_ident():
            self._owner, self._count = None, 0
            t = self._acquired_mono
            self._acquired_mono = None
            self._san._released(self,
                                None if t is None else monotonic_s() - t)
        if self._reentrant:
            return self._inner._release_save()
        self._inner.release()
        return None

    def _acquire_restore(self, state):
        t0 = monotonic_s()
        if self._reentrant:
            self._inner._acquire_restore(state)
        else:
            self._inner.acquire()
        if self._san.tracking():
            self._owner, self._count = threading.get_ident(), 1
            self._acquired_mono = monotonic_s()
            self._san._acquired(self, self._acquired_mono - t0)


class LockSanitizer:
    """Process-wide lock monitor. install() patches the threading module's
    Lock/RLock factories; uninstall() restores them (already-created
    sanitized locks fall back to passthrough). All bookkeeping runs on the
    ORIGINAL primitives and behind a thread-local busy flag, so the
    sanitizer can never deadlock against the instrument locks it reports
    into. Timing reads util.time_source.monotonic_s, so ManualClock tests
    drive hold/wait measurements with zero real sleeps."""

    ENV_FLAG = "GRAFT_LOCK_SANITIZER"
    ENV_LONG_HOLD = "GRAFT_LOCK_SANITIZER_LONG_HOLD_MS"

    def __init__(self):
        self._meta = _ORIG_LOCK()    # guards everything below; NO metric
                                     # calls while held (creating a metric
                                     # creates a Lock -> our own factory)
        self._installed = False
        self._enabled = False
        self.long_hold_ms = None
        self._seq = itertools.count(1)   # lock-free under the GIL
        self._created = 0
        self._edges = {}             # (id_a, id_b) -> {"from","to","count"}
        self._held = {}              # thread ident -> [SanitizedLock, ...]
        self._thread_names = {}      # thread ident -> thread name
        self.violations = []         # dicts; bounded below
        self._reported_pairs = set() # unordered id pairs already reported
        self._reported_holds = set() # lock names already long-hold-reported
        self._tls = threading.local()
        self.max_violations = 256

    # -- lifecycle -----------------------------------------------------------
    def install(self, long_hold_ms=None):
        """Patch threading.Lock/RLock. Idempotent; returns self."""
        with self._meta:
            self.long_hold_ms = None if long_hold_ms is None \
                else float(long_hold_ms)
            if self._installed:
                self._enabled = True
                return self
            self._installed = True
            self._enabled = True
        threading.Lock = self._make_lock
        threading.RLock = self._make_rlock
        return self

    def uninstall(self):
        """Restore the real factories and stop tracking (stats are kept
        until reset())."""
        with self._meta:
            if not self._installed:
                return self
            self._installed = False
            self._enabled = False
        threading.Lock = _ORIG_LOCK
        threading.RLock = _ORIG_RLOCK
        return self

    def install_from_env(self, environ=None):
        """install() iff $GRAFT_LOCK_SANITIZER is truthy — the seam the
        smoke arcs and servers call unconditionally; a no-op (zero patching,
        zero overhead) unless the operator opted in."""
        env = _os.environ if environ is None else environ
        if str(env.get(self.ENV_FLAG, "")).lower() not in ("1", "true",
                                                           "yes", "on"):
            return None
        hold = env.get(self.ENV_LONG_HOLD)
        return self.install(
            long_hold_ms=float(hold) if hold else self.long_hold_ms)

    def reset(self):
        """Clear accumulated edges/violations (for tests and re-arming)."""
        with self._meta:
            self._edges.clear()
            self._held.clear()
            self._thread_names.clear()
            self.violations = []
            self._reported_pairs.clear()
            self._reported_holds.clear()

    @property
    def installed(self):
        return self._installed

    def tracking(self):
        """True when acquire/release events should be recorded: enabled and
        not re-entering from the sanitizer's own reporting path."""
        return self._enabled and not getattr(self._tls, "busy", False)

    # -- factories (what threading.Lock/RLock become) ------------------------
    def _make_lock(self):
        if getattr(self._tls, "busy", False):
            return _ORIG_LOCK()      # locks born inside the reporting path
        return SanitizedLock(self, False, *self._site_name("Lock"))

    def _make_rlock(self):
        if getattr(self._tls, "busy", False):
            return _ORIG_RLOCK()     # (telemetry internals) stay plain
        return SanitizedLock(self, True, *self._site_name("RLock"))

    def _site_name(self, kind):
        # NOT under _meta: the factory runs from arbitrary code, including
        # metric construction triggered by our own reporting while _meta is
        # held — itertools.count is atomic enough for a display name
        n = next(self._seq)
        self._created = n
        try:
            f = _sys._getframe(2)    # _make_* <- threading.Lock() <- caller
            site = f"{_os.path.basename(f.f_code.co_filename)}:{f.f_lineno}"
        except Exception:
            site = "?"
        return f"{kind}#{n}({site})", site

    # -- event sinks (called by SanitizedLock) -------------------------------
    @staticmethod
    def _thread_name(ident):
        # side-effect-free name lookup: threading.current_thread() REGISTERS
        # a _DummyThread for unknown threads, and that registration acquires
        # an Event lock — which, sanitized, would re-enter this very path
        t = threading._active.get(ident)
        return t.name if t is not None else f"thread-{ident}"

    def _acquired(self, lock, waited_s):
        me = threading.get_ident()
        inversions = 0
        with self._meta:
            held = self._held.setdefault(me, [])
            self._thread_names[me] = self._thread_name(me)
            for prior in held:
                if prior is lock:
                    continue
                inversions += self._edge(prior, lock, me)
            held.append(lock)
        for _ in range(inversions):      # metric calls OUTSIDE _meta
            self._count_inc("lock_order_violations_total")
        self._observe("lock_wait_ms", waited_s * 1000.0, lock)

    def _released(self, lock, held_s):
        me = threading.get_ident()
        with self._meta:
            held = self._held.get(me)
            if held is not None and lock in held:
                held.remove(lock)
                if not held:
                    del self._held[me]
        if held_s is None:
            return
        held_ms = held_s * 1000.0
        self._observe("lock_hold_ms", held_ms, lock)
        if self.long_hold_ms is not None and held_ms > self.long_hold_ms:
            with self._meta:
                if lock.name in self._reported_holds:
                    return
                self._reported_holds.add(lock.name)
                self._record({
                    "kind": "long-hold", "lock": lock.name,
                    "held_ms": round(held_ms, 3),
                    "limit_ms": self.long_hold_ms,
                    "thread": self._thread_name(me),
                })

    def _edge(self, a, b, ident):
        """Record a->b (a held while acquiring b); a pre-existing b->a edge
        is a lock-order inversion, reported once per unordered pair. Caller
        holds _meta; returns 1 when a NEW inversion was recorded so the
        caller can bump the counter after releasing it."""
        key = (id(a), id(b))
        e = self._edges.get(key)
        if e is None:
            self._edges[key] = {"from": a.name, "to": b.name, "count": 1}
        else:
            e["count"] += 1
        rev = self._edges.get((id(b), id(a)))
        if rev is None:
            return 0
        pair = frozenset((id(a), id(b)))
        if pair in self._reported_pairs:
            return 0
        self._reported_pairs.add(pair)
        self._record({
            "kind": "lock-order-inversion",
            "locks": [a.name, b.name],
            "thread": self._thread_names.get(ident, str(ident)),
            "detail": f"{a.name} -> {b.name} observed while "
                      f"{b.name} -> {a.name} was already on record",
        })
        return 1

    def _record(self, violation):
        # caller holds self._meta
        if len(self.violations) < self.max_violations:
            self.violations.append(violation)

    # -- metrics (lazy; never allowed to deadlock or raise) ------------------
    # The instruments are fetched ONCE (under the busy flag, so any locks
    # telemetry creates along the way come out plain), and every lock that
    # belongs to the telemetry plumbing itself is excluded from reporting:
    # observing the wait time of the wait-histogram's own lock into that
    # same histogram would re-acquire a lock the calling thread already
    # holds.
    def _instruments(self):
        m = self.__dict__.get("_m")
        if m is not None:
            return m
        self._tls.busy = True
        try:
            from ..telemetry.registry import get_registry
            reg = get_registry()
            m = {
                "lock_wait_ms": reg.histogram(
                    "lock_wait_ms", "time spent blocked acquiring locks"),
                "lock_hold_ms": reg.histogram(
                    "lock_hold_ms", "time locks were held"),
                "lock_order_violations_total": reg.counter(
                    "lock_order_violations_total",
                    "runtime lock-order inversions detected"),
            }
            skip = {id(reg._lock)}
            skip.update(id(inst._lock) for inst in m.values())
            self._metric_lock_ids = skip
            self._m = m
        except Exception:
            self._m = m = {}
            self._metric_lock_ids = set()
        finally:
            self._tls.busy = False
        return m

    def _observe(self, name, value_ms, lock):
        if getattr(self._tls, "busy", False):
            return
        m = self._instruments()
        hist = m.get(name)
        if hist is None or id(lock) in self._metric_lock_ids:
            return
        self._tls.busy = True
        try:
            hist.observe(value_ms, lock=lock.site)
        except Exception:
            pass                     # telemetry must never break a lock
        finally:
            self._tls.busy = False

    def _count_inc(self, name):
        if getattr(self._tls, "busy", False):
            return
        ctr = self._instruments().get(name)
        if ctr is None:
            return
        self._tls.busy = True
        try:
            ctr.inc()
        except Exception:
            pass
        finally:
            self._tls.busy = False

    # -- exposition ----------------------------------------------------------
    def table(self):
        """JSON-friendly live state for GET /debug/locks."""
        with self._meta:
            return {
                "installed": self._installed,
                "long_hold_ms": self.long_hold_ms,
                "locks_created": self._created,
                "violations": [dict(v) for v in self.violations],
                "edges": sorted((dict(e) for e in self._edges.values()),
                                key=lambda e: (e["from"], e["to"])),
                "held": {
                    self._thread_names.get(ident, str(ident)):
                        [lk.name for lk in locks]
                    for ident, locks in self._held.items()},
            }

    def report(self):
        """Summary for smoke-arc assertions: violation count + kinds."""
        with self._meta:
            kinds = {}
            for v in self.violations:
                kinds[v["kind"]] = kinds.get(v["kind"], 0) + 1
            return {"installed": self._installed,
                    "violations": len(self.violations),
                    "by_kind": kinds,
                    "edges": len(self._edges)}


#: process singleton — servers expose table() at /debug/locks, smoke arcs
#: install()/report()/uninstall() around their scenario
lock_sanitizer = LockSanitizer()
