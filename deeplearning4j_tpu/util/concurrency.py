"""Concurrency utilities.

Reference: deeplearning4j-core parallelism/ — MagicQueue.java (multi-device
batch distribution queue: one bounded queue per device, round-robin put,
device-affine take), AsyncIterator.java (background-thread prefetch over any
iterator), ConcurrentHashSet.java.

On TPU the JAX dispatch queue already overlaps host and device work; these
remain useful for host-side input pipelines feeding multiple logical shards.
"""
from __future__ import annotations

import queue
import threading


class MagicQueue:
    """Round-robin distribution of items to per-worker bounded queues
    (reference: parallelism/MagicQueue.java — mode SEQUENTIAL round-robin)."""

    _SENTINEL = object()

    def __init__(self, n_workers, capacity=8):
        self.n_workers = int(n_workers)
        self._queues = [queue.Queue(maxsize=capacity)
                        for _ in range(self.n_workers)]
        self._put_idx = 0
        self._lock = threading.Lock()

    def add(self, item):
        with self._lock:
            idx = self._put_idx
            self._put_idx = (self._put_idx + 1) % self.n_workers
        self._queues[idx].put(item)

    put = add

    def poll(self, worker, timeout=None):
        """Take the next item for `worker` (device-affine take)."""
        try:
            item = self._queues[worker].get(timeout=timeout)
        except queue.Empty:
            return None
        return None if item is self._SENTINEL else item

    def size(self, worker=None):
        if worker is not None:
            return self._queues[worker].qsize()
        return sum(q.qsize() for q in self._queues)

    def close(self):
        for q in self._queues:
            q.put(self._SENTINEL)


class AsyncIterator:
    """Background-thread prefetch over any iterator (reference:
    parallelism/AsyncIterator.java)."""

    _SENTINEL = object()

    def __init__(self, iterator, buffer_size=8):
        self._queue = queue.Queue(maxsize=buffer_size)
        self._error = None

        def run():
            try:
                for item in iterator:
                    self._queue.put(item)
            except BaseException as e:  # propagate to consumer
                self._error = e
            finally:
                self._queue.put(self._SENTINEL)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if getattr(self, "_done", False):  # keep raising after exhaustion
            raise StopIteration
        item = self._queue.get()
        if item is self._SENTINEL:
            self._done = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item


class ConcurrentHashSet:
    """(reference: parallelism/ConcurrentHashSet.java)"""

    def __init__(self):
        self._set = set()
        self._lock = threading.Lock()

    def add(self, item):
        with self._lock:
            if item in self._set:
                return False
            self._set.add(item)
            return True

    def remove(self, item):
        with self._lock:
            self._set.discard(item)

    def __contains__(self, item):
        with self._lock:
            return item in self._set

    def __len__(self):
        with self._lock:
            return len(self._set)
