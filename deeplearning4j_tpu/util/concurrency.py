"""Concurrency utilities.

Reference: deeplearning4j-core parallelism/ — MagicQueue.java (multi-device
batch distribution queue: one bounded queue per device, round-robin put,
device-affine take), AsyncIterator.java (background-thread prefetch over any
iterator), ConcurrentHashSet.java.

On TPU the JAX dispatch queue already overlaps host and device work; these
remain useful for host-side input pipelines feeding multiple logical shards.
"""
from __future__ import annotations

import collections
import queue
import threading

from .time_source import monotonic_s


class AtomicCounter:
    """Lock-protected counter shared by serving metrics and the inference
    servers (the `served` counter was previously mutated bare from concurrent
    handler threads — a lost-update data race under ThreadingHTTPServer)."""

    def __init__(self, value=0):
        self._value = int(value)   # guarded by: self._lock
        self._lock = threading.Lock()

    def add(self, n=1):
        with self._lock:
            self._value += n
            return self._value

    def get(self):
        with self._lock:
            return self._value

    @property
    def value(self):
        return self.get()


class MagicQueue:
    """Round-robin distribution of items to per-worker bounded queues
    (reference: parallelism/MagicQueue.java — mode SEQUENTIAL round-robin).

    `close()` is deterministic: every taker currently blocked in `poll` —
    however many per worker — wakes and returns None once its queue is empty;
    items enqueued before the close remain pollable (drain semantics). The
    previous implementation pushed one sentinel per worker queue, so with two
    concurrent takers on one worker only one of them ever unblocked."""

    def __init__(self, n_workers, capacity=8):
        self.n_workers = int(n_workers)
        # capacity<=0 means unbounded, matching the queue.Queue(maxsize=0)
        # semantics this class previously delegated to
        self._capacity = int(capacity) if capacity > 0 else float("inf")
        self._queues = [collections.deque() for _ in range(self.n_workers)]
        self._put_idx = 0
        self._idx_lock = threading.Lock()   # only the round-robin counter
        self._closed = False
        # per-worker locks (like the per-worker stdlib queues this replaces):
        # traffic on one worker never contends with another's
        self._locks = [threading.Lock() for _ in range(self.n_workers)]
        self._not_empty = [threading.Condition(lk) for lk in self._locks]
        self._not_full = [threading.Condition(lk) for lk in self._locks]

    def add(self, item):
        with self._idx_lock:
            idx = self._put_idx
            self._put_idx = (self._put_idx + 1) % self.n_workers
        with self._locks[idx]:
            if self._closed:
                raise RuntimeError("MagicQueue is closed")
            while len(self._queues[idx]) >= self._capacity:
                self._not_full[idx].wait()
                if self._closed:
                    raise RuntimeError("MagicQueue is closed")
            self._queues[idx].append(item)
            self._not_empty[idx].notify()

    put = add

    def poll(self, worker, timeout=None):
        """Take the next item for `worker` (device-affine take). Returns None
        on timeout, or — once the queue is closed and drained — immediately.

        The deadline reads the injected util.time_source clock, so a test
        that pre-advances a ManualClock past the deadline gets None with
        zero real blocking. The condition wait itself is real-time: if a
        full wait slice elapses with no wake-up and no clock progress (a
        frozen ManualClock can never expire the deadline on its own), the
        poll honors the real elapsed time and returns None instead of
        spinning forever."""
        deadline = None if timeout is None else monotonic_s() + timeout
        with self._locks[worker]:
            q = self._queues[worker]
            while not q:
                if self._closed:
                    return None
                if deadline is None:
                    self._not_empty[worker].wait()
                    continue
                remaining = deadline - monotonic_s()
                if remaining <= 0:
                    return None
                if not self._not_empty[worker].wait(remaining) and not q:
                    return None   # real slice elapsed, nothing arrived
            item = q.popleft()
            self._not_full[worker].notify()   # one pop frees one slot
            return item

    def drain(self, worker):
        """Pop and return everything currently queued for `worker`."""
        with self._locks[worker]:
            items = list(self._queues[worker])
            self._queues[worker].clear()
            self._not_full[worker].notify_all()
            return items

    @property
    def closed(self):
        return self._closed

    def size(self, worker=None):
        if worker is not None:
            with self._locks[worker]:
                return len(self._queues[worker])
        total = 0
        for w in range(self.n_workers):
            with self._locks[w]:
                total += len(self._queues[w])
        return total

    def close(self):
        """Stop accepting new items and wake every blocked taker (and any
        producer blocked on a full queue, which then raises). Setting the
        flag and notifying under each worker's lock guarantees no waiter
        misses the wake-up."""
        for w in range(self.n_workers):
            with self._locks[w]:
                self._closed = True
                self._not_empty[w].notify_all()
                self._not_full[w].notify_all()


class AsyncIterator:
    """Background-thread prefetch over any iterator (reference:
    parallelism/AsyncIterator.java)."""

    _SENTINEL = object()

    def __init__(self, iterator, buffer_size=8):
        self._queue = queue.Queue(maxsize=buffer_size)
        self._error = None

        def run():
            try:
                for item in iterator:
                    self._queue.put(item)
            except BaseException as e:  # propagate to consumer
                self._error = e
            finally:
                self._queue.put(self._SENTINEL)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        if getattr(self, "_done", False):  # keep raising after exhaustion
            raise StopIteration
        item = self._queue.get()
        if item is self._SENTINEL:
            self._done = True
            if self._error is not None:
                raise self._error
            raise StopIteration
        return item


class ConcurrentHashSet:
    """(reference: parallelism/ConcurrentHashSet.java)"""

    def __init__(self):
        self._set = set()          # guarded by: self._lock
        self._lock = threading.Lock()

    def add(self, item):
        with self._lock:
            if item in self._set:
                return False
            self._set.add(item)
            return True

    def remove(self, item):
        with self._lock:
            self._set.discard(item)

    def __contains__(self, item):
        with self._lock:
            return item in self._set

    def __len__(self):
        with self._lock:
            return len(self._set)
