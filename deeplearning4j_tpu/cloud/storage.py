"""Blob storage SPI + DataSet iteration over stored batches.

Reference: s3/uploader/S3Uploader.java (multi-part upload, bucket ensure),
s3/reader/{S3Downloader, BucketIterator, BaseS3DataSetIterator}.java.
The S3 client calls map to the SPI below; `LocalBlobStore` is the hermetic
backend (also how tests exercise the contract), and `get_blob_store` resolves
URLs to whichever backend's client library exists in the environment.
"""
from __future__ import annotations

import io
import os
import shutil

import numpy as np

from ..util.fs import atomic_write


class BlobStore:
    """upload/download/list over a bucket-like namespace."""

    def upload(self, local_path, key):
        raise NotImplementedError

    def upload_bytes(self, data: bytes, key):
        raise NotImplementedError

    def download(self, key, local_path):
        raise NotImplementedError

    def download_bytes(self, key) -> bytes:
        raise NotImplementedError

    def list_keys(self, prefix=""):
        raise NotImplementedError

    def delete(self, key):
        raise NotImplementedError


class LocalBlobStore(BlobStore):
    """Filesystem-backed store (reference parity: the S3 calls, minus the
    network; keys are slash-separated like object names)."""

    def __init__(self, root):
        self.root = str(root)
        os.makedirs(self.root, exist_ok=True)

    def _path(self, key):
        root = os.path.normpath(self.root)
        p = os.path.normpath(os.path.join(root, key))
        # prefix check must be boundary-aware: '/data/store2' shares the raw
        # string prefix of root '/data/store' but is OUTSIDE it
        if p != root and not p.startswith(root + os.sep):
            raise ValueError(f"key escapes the store root: {key}")
        return p

    def upload(self, local_path, key):
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        shutil.copyfile(local_path, dst)
        return key

    def upload_bytes(self, data, key):
        dst = self._path(key)
        os.makedirs(os.path.dirname(dst), exist_ok=True)
        # durable publish (fsync + replace + dir fsync): an object store
        # upload either exists completely or not at all, even across a
        # crash — the S3 semantics this local backend stands in for
        atomic_write(dst, data)
        return key

    def download(self, key, local_path):
        os.makedirs(os.path.dirname(os.path.abspath(local_path)), exist_ok=True)
        shutil.copyfile(self._path(key), local_path)
        return local_path

    def download_bytes(self, key):
        with open(self._path(key), "rb") as f:
            return f.read()

    def list_keys(self, prefix=""):
        out = []
        for dirpath, _, files in os.walk(self.root):
            for name in files:
                full = os.path.join(dirpath, name)
                key = os.path.relpath(full, self.root).replace(os.sep, "/")
                if key.startswith(prefix):
                    out.append(key)
        return sorted(out)

    def delete(self, key):
        os.remove(self._path(key))


def get_blob_store(url):
    """Resolve a store URL to a backend: file:///dir or a plain path ->
    LocalBlobStore; s3://bucket / gs://bucket -> the respective client if its
    library is installed (boto3 / google-cloud-storage are NOT bundled in
    this environment, so those raise a clear gating error instead)."""
    if url.startswith("file://"):
        return LocalBlobStore(url[len("file://"):])
    if url.startswith("s3://"):
        try:
            import boto3  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "s3:// stores need boto3, which is not installed in this "
                "environment; use file:// or install boto3") from e
        raise NotImplementedError("S3 backend: wire boto3 client here")
    if url.startswith("gs://"):
        try:
            from google.cloud import storage  # noqa: F401
        except ImportError as e:
            raise ImportError(
                "gs:// stores need google-cloud-storage, which is not "
                "installed; use file:// or install it") from e
        raise NotImplementedError("GCS backend: wire the client here")
    return LocalBlobStore(url)


class BlobDataSetIterator:
    """Iterates DataSets stored as .npz blobs under a prefix (reference:
    reader/BaseS3DataSetIterator.java — each S3 object is one serialized
    DataSet). Writing side: `save_dataset` stores features/labels arrays."""

    def __init__(self, store: BlobStore, prefix=""):
        self.store = store
        self.prefix = prefix
        self._keys = [k for k in store.list_keys(prefix) if k.endswith(".npz")]
        self._i = 0

    @staticmethod
    def save_dataset(store, key, ds):
        buf = io.BytesIO()
        arrays = {"features": np.asarray(ds.features),
                  "labels": np.asarray(ds.labels)}
        if ds.features_mask is not None:
            arrays["features_mask"] = np.asarray(ds.features_mask)
        if ds.labels_mask is not None:
            arrays["labels_mask"] = np.asarray(ds.labels_mask)
        np.savez(buf, **arrays)
        store.upload_bytes(buf.getvalue(), key)
        return key

    def __iter__(self):
        return self

    def __next__(self):
        if not self.has_next():
            raise StopIteration
        return self.next()

    def has_next(self):
        return self._i < len(self._keys)

    def next(self):
        from ..datasets.dataset import DataSet
        raw = self.store.download_bytes(self._keys[self._i])
        self._i += 1
        z = np.load(io.BytesIO(raw))
        return DataSet(z["features"], z["labels"],
                       z["features_mask"] if "features_mask" in z else None,
                       z["labels_mask"] if "labels_mask" in z else None)

    def reset(self):
        self._i = 0

    def async_supported(self):
        return True
