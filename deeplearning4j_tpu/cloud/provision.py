"""Cluster provisioning over a pluggable command transport.

Reference: ec2/provision/ClusterSetup.java (parallel worker provisioning:
upload the worker bundle, install deps, launch the trainer) and
HostProvisioner.java (jsch SSH: runRemoteCommand, SCP upload, retries).

TPU redesign: the same two roles with the SSH dependency behind a Transport
SPI — SshTransport shells out to the system ssh/scp binaries (the jsch
analog), LocalTransport executes in-process so provisioning logic is testable
hermetically. ClusterSetup fans out over hosts with a thread pool the way the
reference uses its executor.
"""
from __future__ import annotations

import os
import shlex
import subprocess
from concurrent.futures import ThreadPoolExecutor


class Transport:
    def run(self, host, command, timeout=300):
        """Returns (exit_code, stdout, stderr)."""
        raise NotImplementedError

    def put(self, host, local_path, remote_path, timeout=300):
        raise NotImplementedError

    def resolve(self, host, remote_path):
        """Host-local view of a remote path (identity for real transports)."""
        return remote_path


class LocalTransport(Transport):
    """Executes on the local machine (hermetic test backend). With a
    `sandbox_root`, each host gets its own directory subtree so concurrent
    per-host uploads to the same logical remote path don't collide on the
    one shared filesystem."""

    def __init__(self, sandbox_root=None):
        self.sandbox_root = None if sandbox_root is None else str(sandbox_root)

    def resolve(self, host, remote_path):
        if self.sandbox_root is None:
            return remote_path
        return os.path.join(self.sandbox_root, host,
                            remote_path.lstrip("/"))

    def run(self, host, command, timeout=300):
        p = subprocess.run(command, shell=True, capture_output=True,
                           timeout=timeout)
        return p.returncode, p.stdout.decode(), p.stderr.decode()

    def put(self, host, local_path, remote_path, timeout=300):
        dest = self.resolve(host, remote_path)
        os.makedirs(os.path.dirname(os.path.abspath(dest)), exist_ok=True)
        import shutil
        shutil.copyfile(local_path, dest)
        return dest


class SshTransport(Transport):
    """ssh/scp subprocess transport (reference: HostProvisioner.java over
    jsch). Key-based auth only; no password prompts in automation."""

    def __init__(self, user, key_file=None, ssh_opts=None,
                 strict_host_keys=True):
        # accept-new pins first-seen host keys and refuses changed ones —
        # this channel pipes uploaded scripts into bash, so a silent MITM
        # must not be the default. Recycled-IP fleets (new VM, same address)
        # opt out explicitly with strict_host_keys=False (or pass ssh_opts
        # with a per-cluster UserKnownHostsFile).
        if ssh_opts is None:
            ssh_opts = ("-o", "BatchMode=yes", "-o",
                        "StrictHostKeyChecking="
                        + ("accept-new" if strict_host_keys else "no"))
        self.user = user
        self.key_file = key_file
        self.ssh_opts = list(ssh_opts)

    def _key_args(self):
        return ["-i", self.key_file] if self.key_file else []

    def run(self, host, command, timeout=300):
        cmd = (["ssh"] + self._key_args() + self.ssh_opts
               + [f"{self.user}@{host}", command])
        p = subprocess.run(cmd, capture_output=True, timeout=timeout)
        return p.returncode, p.stdout.decode(), p.stderr.decode()

    def put(self, host, local_path, remote_path, timeout=300):
        cmd = (["scp"] + self._key_args() + self.ssh_opts
               + [local_path, f"{self.user}@{host}:{remote_path}"])
        p = subprocess.run(cmd, capture_output=True, timeout=timeout)
        if p.returncode != 0:
            raise RuntimeError(f"scp to {host} failed: {p.stderr.decode()}")
        return remote_path


class HostProvisioner:
    """Provision one host: upload artifacts, run setup commands with retries
    (reference: HostProvisioner.java — uploadAndRun, retry loop)."""

    def __init__(self, transport: Transport, host, retries=3):
        self.transport = transport
        self.host = host
        self.retries = int(retries)
        self.log = []

    def run(self, command):
        last = None
        for attempt in range(self.retries):
            rc, out, err = self.transport.run(self.host, command)
            self.log.append({"host": self.host, "command": command,
                             "attempt": attempt, "rc": rc})
            if rc == 0:
                return out
            last = RuntimeError(
                f"[{self.host}] command failed (rc={rc}): {command}\n{err}")
        raise last

    def upload(self, local_path, remote_path):
        self.transport.put(self.host, local_path, remote_path)
        self.log.append({"host": self.host, "upload": remote_path})
        return remote_path

    def upload_and_run(self, local_script, remote_path, interpreter="bash"):
        self.upload(local_script, remote_path)
        target = self.transport.resolve(self.host, remote_path)
        return self.run(f"{interpreter} {shlex.quote(target)}")


class ClusterSetup:
    """Fan provisioning out over all hosts in parallel (reference:
    ClusterSetup.java — one provisioner per EC2 box on an executor)."""

    def __init__(self, hosts, transport: Transport, retries=3, max_workers=8):
        self.provisioners = [HostProvisioner(transport, h, retries=retries)
                             for h in hosts]
        self.max_workers = int(max_workers)

    def run_on_all(self, command):
        """Run a command on every host concurrently; returns {host: stdout}.
        Raises if any host fails (after per-host retries)."""
        with ThreadPoolExecutor(max_workers=self.max_workers) as ex:
            futs = {p.host: ex.submit(p.run, command) for p in self.provisioners}
            return {h: f.result() for h, f in futs.items()}

    def upload_to_all(self, local_path, remote_path):
        with ThreadPoolExecutor(max_workers=self.max_workers) as ex:
            futs = [ex.submit(p.upload, local_path, remote_path)
                    for p in self.provisioners]
            for f in futs:
                f.result()

    def bootstrap(self, setup_script, remote_path="/tmp/dl4j_tpu_setup.sh"):
        """Upload + execute the bootstrap script everywhere (the
        ClusterSetup.java 'provision the whole cluster' entry)."""
        with ThreadPoolExecutor(max_workers=self.max_workers) as ex:
            futs = {p.host: ex.submit(p.upload_and_run, setup_script,
                                      remote_path) for p in self.provisioners}
            return {h: f.result() for h, f in futs.items()}
