"""Cloud provisioning + blob storage.

Reference: deeplearning4j-scaleout/deeplearning4j-aws (1.5k LoC) —
ec2/provision/{ClusterSetup,HostProvisioner}.java (jsch SSH provisioning of
EC2 workers) and s3/{uploader/S3Uploader, reader/S3Downloader,
reader/BaseS3DataSetIterator}.java (S3 blob IO + dataset iteration).

TPU redesign: on TPU fleets the "cluster" is a provisioned slice reached over
SSH and the blob store is GCS/S3-compatible object storage. The module keeps
the same two capability surfaces with pluggable backends:
- BlobStore SPI (upload/download/list/iterate-DataSets) with a local
  filesystem implementation always available and object-store backends gated
  on their client libraries being installed (no pip installs here);
- ClusterSetup/HostProvisioner over a Transport SPI (LocalTransport runs
  commands in-process for tests; SshTransport shells out to ssh/scp the way
  HostProvisioner.java drives jsch).
"""
from .storage import BlobStore, LocalBlobStore, BlobDataSetIterator, get_blob_store
from .provision import ClusterSetup, HostProvisioner, LocalTransport, SshTransport

__all__ = ["BlobStore", "LocalBlobStore", "BlobDataSetIterator",
           "get_blob_store", "ClusterSetup", "HostProvisioner",
           "LocalTransport", "SshTransport"]
