"""graftlint CLI (used by tools/lint.py and `python -m deeplearning4j_tpu.analysis`).

Usage:
    python tools/lint.py [paths...] [options]

Paths default to the package and tools/ trees. Exit status: 0 = clean (no
NEW violations, no parse errors), 1 = new violations or unparseable files,
2 = bad invocation.

Options:
    --format=text|json   json is machine-readable (pre-commit / CI tooling)
    --baseline PATH      baseline file (default tools/lint_baseline.json)
    --baseline-update    rewrite the baseline from current findings (keeps
                         notes on still-matching entries) and exit 0
    --baseline-prune     delete only the STALE entries (fixed code the
                         findings no longer match); never adds entries
    --no-baseline        ignore the baseline: report every violation as new
    --rules GL001,GL002  run a subset of rules
    --list-rules         print the rule catalog and exit
"""
from __future__ import annotations

import argparse
import json
import os
import sys

from .baseline import Baseline
from .core import Analyzer, all_rules

# repo root = parents of deeplearning4j_tpu/analysis/cli.py — but only when
# that actually IS a checkout: for a pip-installed `graftlint` the parents
# are site-packages, and rooting there would lint the installed copy instead
# of the user's project, so fall back to the invocation cwd
_PKG_PARENT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
_REPO_ROOT = _PKG_PARENT if os.path.exists(
    os.path.join(_PKG_PARENT, "pyproject.toml")) else os.getcwd()
DEFAULT_PATHS = ("deeplearning4j_tpu", "tools")
DEFAULT_BASELINE = os.path.join("tools", "lint_baseline.json")


def build_parser():
    p = argparse.ArgumentParser(
        prog="graftlint",
        description="AST-based static analysis enforcing this codebase's "
                    "invariants (clock discipline, strict JSON, lock guards, "
                    "jit host-sync hazards).")
    p.add_argument("paths", nargs="*", default=None,
                   help="files/directories to lint, relative to the CURRENT "
                        f"directory (default: {' '.join(DEFAULT_PATHS)} "
                        "under --root)")
    p.add_argument("--root", default=_REPO_ROOT,
                   help="root for relative paths + baseline (default: repo)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help="baseline file; an explicit relative path resolves "
                        "against the CURRENT directory "
                        f"(default: {DEFAULT_BASELINE} under --root)")
    p.add_argument("--baseline-update", action="store_true",
                   help="rewrite the baseline from current findings")
    p.add_argument("--baseline-prune", action="store_true",
                   help="delete baseline entries no current finding matches "
                        "(scoped to the analyzed files and active rules); "
                        "unlike --baseline-update this never ADDS entries")
    p.add_argument("--no-baseline", action="store_true",
                   help="ignore the baseline entirely")
    p.add_argument("--rules", default=None,
                   help="comma-separated subset of rule ids to run")
    p.add_argument("--list-rules", action="store_true")
    return p


def select_rules(spec):
    rules = all_rules()
    if spec is None:
        return rules
    wanted = {r.strip().upper() for r in spec.split(",") if r.strip()}
    known = {r.id for r in rules}
    unknown = wanted - known
    if unknown:
        raise SystemExit(f"graftlint: unknown rule(s): {', '.join(sorted(unknown))}")
    return [r for r in rules if r.id in wanted]


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    if args.list_rules:
        for r in all_rules():
            print(f"{r.id}  {r.name}")
            print(f"       {r.rationale}")
        return 0

    root = os.path.abspath(args.root)
    analyzer = Analyzer(rules=select_rules(args.rules), root=root)
    # explicit path arguments are resolved like any CLI resolves them —
    # against the invoker's cwd; only the defaults are root-relative
    paths = ([os.path.abspath(p) for p in args.paths] if args.paths
             else list(DEFAULT_PATHS))
    report = analyzer.analyze_paths(paths)

    baseline_path = (os.path.join(root, DEFAULT_BASELINE)
                     if args.baseline is None
                     else os.path.abspath(args.baseline))

    if args.baseline_update and args.baseline_prune:
        print("graftlint: --baseline-update already drops stale entries; "
              "pass one or the other, not both")
        return 2

    if args.baseline_prune:
        if report.errors:
            # refuse: an unparseable file yields zero findings, so every one
            # of its entries would look stale and be wrongly deleted
            for err in report.errors:
                print(f"PARSE ERROR: {err}")
            print("graftlint: baseline NOT pruned (fix the errors first)")
            return 1
        previous = Baseline.load(baseline_path)
        # prune is scoped exactly like --baseline-update: an entry is a
        # candidate only if this run actually re-checked it (its file was
        # analyzed AND its rule was active); everything else is untouchable
        analyzed = set(report.rel_files)
        active = {r.id for r in analyzer.rules}
        in_scope = [e for e in previous.entries
                    if e["path"] in analyzed and e["rule"] in active]
        stale = Baseline(in_scope).stale_entries(report.violations)
        stale_ids = {id(e) for e in stale}      # identity, not equality:
        kept = [e for e in previous.entries     # duplicate (rule,path,code)
                if id(e) not in stale_ids]      # entries prune one-for-one
        Baseline(kept).save(baseline_path)
        print(f"graftlint: baseline pruned: {len(stale)} stale "
              f"entr{'y' if len(stale) == 1 else 'ies'} removed, "
              f"{len(kept)} kept "
              f"-> {os.path.relpath(baseline_path, root)}")
        return 0

    if args.baseline_update:
        if report.errors:
            # refuse: an unparseable file reports zero violations, so its
            # baseline entries (and their notes) would be silently re-derived
            # to nothing and resurface as NEW debt once the file parses again
            for err in report.errors:
                print(f"PARSE ERROR: {err}")
            print("graftlint: baseline NOT updated (fix the errors first)")
            return 1
        previous = Baseline.load(baseline_path)
        # a SCOPED update (path or rule subset) re-derives only what this run
        # actually analyzed; entries outside the analyzed files / active
        # rules are preserved verbatim (notes included), never dropped
        analyzed = set(report.rel_files)
        active = {r.id for r in analyzer.rules}
        preserved = [e for e in previous.entries
                     if e["path"] not in analyzed or e["rule"] not in active]
        updated = Baseline.from_violations(report.violations,
                                           previous=previous)
        merged = sorted(preserved + updated.entries,
                        key=lambda e: (e["path"], e["line"], e["rule"]))
        Baseline(merged).save(baseline_path)
        print(f"graftlint: baseline updated: {len(merged)} "
              f"entr{'y' if len(merged) == 1 else 'ies'} "
              f"({len(updated.entries)} re-derived, {len(preserved)} "
              f"out-of-scope preserved) "
              f"-> {os.path.relpath(baseline_path, root)}")
        return 0

    if args.no_baseline:
        new, matched = report.violations, []
    else:
        new, matched = Baseline.load(baseline_path).split(report.violations)

    if args.format == "json":
        print(json.dumps({
            "new": [v.to_dict() for v in new],
            "baselined": len(matched),
            "files_checked": report.files_checked,
            "errors": report.errors,
            "ok": not new and not report.errors,
        }, indent=1))
    else:
        for v in new:
            print(v)
        for err in report.errors:
            print(f"PARSE ERROR: {err}")
        print(f"graftlint: {report.files_checked} files, "
              f"{len(new)} new violation(s), {len(matched)} baselined"
              + (f", {len(report.errors)} parse error(s)"
                 if report.errors else ""))
    return 1 if (new or report.errors) else 0


if __name__ == "__main__":
    sys.exit(main())
