"""Whole-program concurrency analysis: lockset inference + lock-order graph.

The stack runs ~30 lock-owning threads (batcher dispatch, decode scheduler,
frontend fan-outs, autoscaler, launchers, broker, fleet collectors, per-mesh
run locks); two concurrency bugs have already shipped — the PR 1 streaming
served-counter data race and the PR 16 mesh collective-rendezvous deadlock.
This module is the graftlint chapter for that bug class, in the spirit of
lockset/happens-before analyses (Eraser, ThreadSanitizer), scaled down to a
zero-setup AST pass:

* ``ClassModel`` / ``MethodSummary`` — per-class lockset inference. A small
  abstract interpreter walks every method simulating the held-lock set
  through ``with self._lock:`` blocks, ``acquire()``/``release()`` pairs
  (including the try/finally form), and re-entry; every ``self.attr``
  access, intra-class call, cross-class call through a typed attribute, and
  known-blocking call is recorded with the lockset held at that point.
  Locksets propagate through intra-class calls: a private helper inherits
  the *intersection* of the locksets at its call sites, and a lock passed
  as an argument (``self._helper(self._lock)`` … ``with lock:``) resolves
  back to the caller's lock attribute when every call site agrees.
* A repo-wide class index (built once per analysis run via the
  ``Rule.begin_program`` hook and shared by every rule below) resolves
  ``self.x = SomeClass(...)`` attributes to their class models, giving the
  approximate cross-class call graph and the *static lock-acquisition-order
  graph* across modules.

Rules on top of the shared model (RULES.md has the bug-history rationale):

* **GL003 lock-guard** — the declared-intent channel: ``# guarded by:
  self._lock`` annotations are checked against the inferred locksets
  (moved here from rules.py so annotation checking and inference share ONE
  model). ``# guarded by: none`` declares an attribute deliberately
  unguarded, silencing GL018.
* **GL018 unguarded-shared-write** — GL003 generalized from opt-in
  annotations to inference: an attribute written under a lock in one
  method but accessed lock-free in another is flagged without any
  annotation.
* **GL019 blocking-under-lock** — sleep/subprocess/socket/urlopen/HTTP/
  ``queue.get``/``Thread.join``/``block_until_ready`` reachable while a
  lock is held (the PR 16 deadlock shape and the PR 2
  snapshot-sorting-under-lock shape), propagated through intra-class calls
  and one level of cross-class calls.
* **GL020 lock-order-inversion** — cycles in the acquisition-order graph,
  reported at every edge of the cycle so both acquisition paths show up;
  re-acquiring a non-reentrant lock is the length-1 cycle.

Everything here is stdlib-only (ast) — the jax-free graftlint entry imports
this module, and the whole-repo pass must stay inside the lint gate's
seconds-level budget.
"""
from __future__ import annotations

import ast
import dataclasses
import re

from .core import Rule, register
from .rules import call_qual, is_self_attr, qualname

# ---------------------------------------------------------------------------
# classification tables
# ---------------------------------------------------------------------------

_LOCK_FACTORIES = {
    "threading.Lock": "Lock",
    "threading.RLock": "RLock",
    "threading.Condition": "Condition",
    "threading.Semaphore": "Semaphore",
    "threading.BoundedSemaphore": "Semaphore",
}
_QUEUE_CLASSES = {"Queue", "LifoQueue", "PriorityQueue", "JoinableQueue",
                  "MagicQueue"}
_THREAD_CLASSES = {"Thread"}

#: calls that park the calling thread (or dispatch to a device and wait):
#: exact quals, plus prefix families checked in _blocking_qual()
_BLOCKING_QUALS = {"time.sleep", "urllib.request.urlopen",
                   "jax.block_until_ready"}
_BLOCKING_PREFIXES = ("subprocess.", "socket.")
#: util.http helpers — blocking network round-trips wherever imported from
_BLOCKING_HTTP_NAMES = {"post_json", "get_json"}

# annotation channel (shared with GL003's historical syntax):
#   self._value = 0    # guarded by: self._lock
#   self._cache = {}   # guarded by: none   <- deliberately unguarded (GL018)
_GUARDED_RE = re.compile(r"#\s*guarded by:\s*(?:self\.([A-Za-z_]\w*)|(none))")


def _blocking_qual(qual):
    """Human-readable description if `qual` names a known-blocking call."""
    if qual is None:
        return None
    if qual in _BLOCKING_QUALS:
        return qual
    if qual.startswith(_BLOCKING_PREFIXES):
        return qual
    last = qual.rsplit(".", 1)[-1]
    if last in _BLOCKING_HTTP_NAMES and ".http" in qual:
        return last
    return None


# ---------------------------------------------------------------------------
# per-class model
# ---------------------------------------------------------------------------

# held-lockset tokens: ("attr", name) for self.<name>, ("param", name) for a
# lock received as an argument (resolved back to the caller's attribute when
# every intra-class call site agrees — see ClassModel._resolve_bindings),
# ("ext", "var.attr") for a lock-named attribute of a local (`with
# ctx.run_lock:` — the PR 16 mesh shape, where the lock lives on another
# object). Ext locks count for blocking-under-lock but stay out of the
# order graph (their identity is a variable name, not a class attribute).
_UNKNOWN = "?"          # a held lock we can't name (still counts as "a lock")

_LOCKISH_NAME = re.compile(r"lock|mutex|\bcv\b|cond", re.IGNORECASE)


@dataclasses.dataclass
class _Access:
    attr: str
    write: bool
    tokens: frozenset       # raw held tokens at the access
    node: object
    held: frozenset = frozenset()   # resolved names, filled by finalize()


@dataclasses.dataclass
class _Acquire:
    lock: str               # lock attribute being acquired
    before: frozenset       # raw held tokens just before the acquire
    node: object
    held_before: frozenset = frozenset()


@dataclasses.dataclass
class _CallSite:
    kind: str               # "self" | "attr"
    attr: str               # receiver attribute ("" for self-calls)
    method: str
    tokens: frozenset
    node: object
    args: tuple             # positional arg AST nodes (self-calls only)
    keywords: tuple         # (name, node) pairs
    held: frozenset = frozenset()


@dataclasses.dataclass
class _Blocking:
    desc: str
    tokens: frozenset
    node: object
    held: frozenset = frozenset()
    ext: frozenset = frozenset()    # held ext-lock display names


@dataclasses.dataclass
class MethodSummary:
    name: str
    node: object
    params: tuple = ()
    accesses: list = dataclasses.field(default_factory=list)
    acquires: list = dataclasses.field(default_factory=list)
    calls: list = dataclasses.field(default_factory=list)
    blocking: list = dataclasses.field(default_factory=list)
    # filled by ClassModel.finalize():
    inherited: frozenset = frozenset()   # locks held at EVERY call site
    bindings: dict = dataclasses.field(default_factory=dict)
    blocks_all: tuple = ()               # transitive blocking descs
    acquires_all: frozenset = frozenset()  # transitive lock attrs acquired


class _MethodWalker:
    """Simulates the held-lock set through one method body, recording every
    attribute access / call / acquire / blocking event with the lockset at
    that point. Nested function bodies (closures handed to threads, timers,
    fan-outs) are walked with an EMPTY lockset — they run later, usually on
    another thread, so a lock held at definition time guards nothing."""

    def __init__(self, model, summary):
        self.model = model
        self.s = summary
        self.held = []              # token stack (duplicates = re-entry)
        self.thread_vars = set()    # locals bound to threading.Thread(...)
        self.thread_lists = set()   # locals bound to lists of threads

    # -- public entry --------------------------------------------------------
    def walk(self, fn_node):
        self.s.params = tuple(a.arg for a in fn_node.args.args
                              if a.arg != "self")
        self._stmts(fn_node.body)

    def _tokens(self):
        return frozenset(self.held)

    # -- statements ----------------------------------------------------------
    def _stmts(self, body):
        for stmt in body:
            self._stmt(stmt)

    def _stmt(self, stmt):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            self._deferred(stmt.body)
        elif isinstance(stmt, ast.ClassDef):
            self._deferred(stmt.body)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            self._with(stmt)
        elif isinstance(stmt, ast.Try):
            # body/handlers/else/finally share ONE evolving lockset: this is
            # exactly what makes `L.acquire(); try: ... finally: L.release()`
            # come out right
            self._stmts(stmt.body)
            for h in stmt.handlers:
                self._stmts(h.body)
            self._stmts(stmt.orelse)
            self._stmts(stmt.finalbody)
        elif isinstance(stmt, ast.If):
            self._expr(stmt.test)
            self._branch(stmt.body)
            self._branch(stmt.orelse)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._expr(stmt.iter)
            if isinstance(stmt.target, ast.Name) and \
                    isinstance(stmt.iter, ast.Name) and \
                    stmt.iter.id in self.thread_lists:
                self.thread_vars.add(stmt.target.id)
            self._expr(stmt.target, write=True)
            self._branch(stmt.body)
            self._branch(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self._expr(stmt.test)
            self._branch(stmt.body)
            self._branch(stmt.orelse)
        elif isinstance(stmt, ast.Assign):
            self._expr(stmt.value)
            self._track_locals(stmt)
            for t in stmt.targets:
                self._expr(t, write=True)
        elif isinstance(stmt, ast.AugAssign):
            self._expr(stmt.value)
            self._expr(stmt.target, write=True)
        elif isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self._expr(stmt.value)
            self._expr(stmt.target, write=True)
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._expr(stmt.value)
        elif isinstance(stmt, (ast.Expr, ast.Await)):
            self._expr(stmt.value)
        elif isinstance(stmt, ast.Raise):
            for part in (stmt.exc, stmt.cause):
                if part is not None:
                    self._expr(part)
        elif isinstance(stmt, ast.Assert):
            self._expr(stmt.test)
            if stmt.msg is not None:
                self._expr(stmt.msg)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self._expr(t, write=True)
        # Pass/Break/Continue/Import/Global/Nonlocal: nothing to see

    def _branch(self, body):
        """Walk a conditional/loop body on a COPY of the lockset: an acquire
        inside one branch must not leak into code after the statement."""
        saved = list(self.held)
        self._stmts(body)
        self.held = saved

    def _deferred(self, body):
        """Nested function/class body: empty lockset, same summary."""
        saved, self.held = self.held, []
        self._stmts(body)
        self.held = saved

    def _with(self, stmt):
        pushed = []
        for item in stmt.items:
            ce = item.context_expr
            tok = self._lock_token(ce)
            if tok is not None:
                if tok[0] == "attr":
                    self.s.acquires.append(
                        _Acquire(tok[1], self._tokens(), ce))
                self.held.append(tok)
                pushed.append(tok)
            else:
                self._expr(ce)
            if item.optional_vars is not None:
                self._expr(item.optional_vars, write=True)
        self._stmts(stmt.body)
        for tok in pushed:
            self.held.remove(tok)

    def _lock_token(self, expr):
        """Token for `with <expr>:` when <expr> is a lock we can name."""
        if is_self_attr(expr):
            return ("attr", expr.attr)
        if isinstance(expr, ast.Name) and expr.id in self.s.params:
            return ("param", expr.id)
        if isinstance(expr, ast.Attribute) and \
                isinstance(expr.value, ast.Name) and \
                _LOCKISH_NAME.search(expr.attr):
            # `with ctx.run_lock:` — a lock living on another object
            return ("ext", f"{expr.value.id}.{expr.attr}")
        return None

    def _track_locals(self, assign):
        """x = threading.Thread(...) / x = [Thread(...) ...] for .join()."""
        if len(assign.targets) != 1 or \
                not isinstance(assign.targets[0], ast.Name):
            return
        name = assign.targets[0].id
        v = assign.value
        if self._is_thread_call(v):
            self.thread_vars.add(name)
        elif isinstance(v, (ast.List, ast.ListComp)):
            elts = v.elts if isinstance(v, ast.List) else [v.elt]
            if any(self._is_thread_call(e) for e in elts):
                self.thread_lists.add(name)

    def _is_thread_call(self, node):
        if not isinstance(node, ast.Call):
            return False
        qual = call_qual(node, self.model.aliases)
        if qual == "threading.Thread":
            return True
        return (isinstance(node.func, ast.Name)
                and node.func.id in _THREAD_CLASSES)

    # -- expressions ---------------------------------------------------------
    def _expr(self, node, write=False):
        if node is None:
            return
        if isinstance(node, ast.Attribute):
            if is_self_attr(node):
                self._access(node, write)
                return
            self._expr(node.value, write=False)
            return
        if isinstance(node, ast.Call):
            self._call(node)
            return
        if isinstance(node, (ast.Lambda,)):
            self._deferred([ast.Expr(value=node.body)])
            return
        if isinstance(node, ast.Subscript):
            # self.x[k] = v mutates the structure behind x: count the write
            self._expr(node.value, write=write)
            self._expr(node.slice, write=False)
            return
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for e in node.elts:
                self._expr(e, write=write)
            return
        if isinstance(node, ast.Starred):
            self._expr(node.value, write=write)
            return
        for child in ast.iter_child_nodes(node):
            self._expr(child, write=False)

    def _access(self, node, write):
        self.s.accesses.append(
            _Access(node.attr, write, self._tokens(), node))

    def _call(self, node):
        func = node.func
        # self.L.acquire() / self.L.release()
        if isinstance(func, ast.Attribute) and is_self_attr(func.value):
            recv = func.value.attr
            meth = func.attr
            self._access(func.value, False)
            if meth == "acquire":
                self.s.acquires.append(
                    _Acquire(recv, self._tokens(), node))
                self.held.append(("attr", recv))
            elif meth == "release":
                tok = ("attr", recv)
                if tok in self.held:
                    self.held.remove(tok)
            elif meth == "block_until_ready":
                self.s.blocking.append(
                    _Blocking("block_until_ready()", self._tokens(), node))
            elif recv in self.model.queues and meth in ("get", "put", "join"):
                self.s.blocking.append(_Blocking(
                    f"self.{recv}.{meth}()", self._tokens(), node))
            elif recv in self.model.threads and meth == "join":
                self.s.blocking.append(_Blocking(
                    f"self.{recv}.join()", self._tokens(), node))
            elif meth in ("wait", "wait_for", "notify", "notify_all"):
                pass    # Condition.wait releases the lock it waits on
            else:
                self.s.calls.append(_CallSite(
                    "attr", recv, meth, self._tokens(), node,
                    tuple(node.args),
                    tuple((kw.arg, kw.value) for kw in node.keywords)))
        elif isinstance(func, ast.Attribute) and \
                isinstance(func.value, ast.Name) and func.value.id == "self":
            self.s.calls.append(_CallSite(
                "self", "", func.attr, self._tokens(), node,
                tuple(node.args),
                tuple((kw.arg, kw.value) for kw in node.keywords)))
        else:
            qual = qualname(func, self.model.aliases) \
                if isinstance(func, (ast.Name, ast.Attribute)) else None
            desc = _blocking_qual(qual)
            if desc is not None:
                self.s.blocking.append(
                    _Blocking(f"{desc}()", self._tokens(), node))
            elif isinstance(func, ast.Attribute):
                if func.attr == "block_until_ready":
                    self.s.blocking.append(_Blocking(
                        "block_until_ready()", self._tokens(), node))
                elif func.attr == "join" and isinstance(func.value, ast.Name) \
                        and func.value.id in self.thread_vars:
                    self.s.blocking.append(_Blocking(
                        f"{func.value.id}.join()", self._tokens(), node))
            self._expr(func)
        for arg in node.args:
            self._method_ref(arg)
            self._expr(arg)
        for kw in node.keywords:
            self._method_ref(kw.value)
            self._expr(kw.value)

    def _method_ref(self, arg):
        """A bare `self._method` passed as an argument (retry wrappers,
        callbacks) counts as a call site for inherited-lockset intersection:
        `self._retry.call(self._attempt, ...)` under the lock means _attempt
        runs under the lock. Deferred references (Thread targets) are passed
        at lock-free sites, so the intersection stays empty there."""
        if is_self_attr(arg):
            self.s.calls.append(_CallSite(
                "ref", "", arg.attr, self._tokens(), arg, (), ()))


class ClassModel:
    """Lockset model for one class: lock attributes, typed attributes, the
    guarded-by annotation channel, and a MethodSummary per direct method."""

    EXEMPT_METHODS = {"__init__", "__del__"}

    def __init__(self, ctx, node):
        self.ctx = ctx
        self.name = node.name
        self.node = node
        self.aliases = ctx.aliases
        self.locks = {}         # attr -> "Lock"/"RLock"/"Condition"/...
        self.queues = set()
        self.threads = set()
        self.attr_types = {}    # attr -> class basename of its constructor
        self.guarded = {}       # attr -> (lock_attr, decl_line)
        self.declared_unguarded = set()   # `# guarded by: none`
        self.methods = {}       # name -> MethodSummary
        self._classify_attrs()
        self._scan_annotations()
        for meth in node.body:
            if isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                s = MethodSummary(meth.name, meth)
                _MethodWalker(self, s).walk(meth)
                self.methods[meth.name] = s
        self._finalize()

    # -- model construction --------------------------------------------------
    def _classify_attrs(self):
        for node in ast.walk(self.node):
            if isinstance(node, ast.Assign):
                targets, value = node.targets, node.value
            elif isinstance(node, ast.AnnAssign):
                targets, value = [node.target], node.value
            else:
                continue
            if not isinstance(value, ast.Call):
                continue
            qual = call_qual(value, self.aliases)
            base = value.func.id if isinstance(value.func, ast.Name) \
                else (value.func.attr
                      if isinstance(value.func, ast.Attribute) else None)
            for t in targets:
                if not is_self_attr(t):
                    continue
                if qual in _LOCK_FACTORIES:
                    self.locks[t.attr] = _LOCK_FACTORIES[qual]
                elif base in _QUEUE_CLASSES or (
                        qual or "").startswith("queue."):
                    self.queues.add(t.attr)
                elif qual == "threading.Thread" or base in _THREAD_CLASSES:
                    self.threads.add(t.attr)
                elif base is not None and base[:1].isupper():
                    self.attr_types[t.attr] = base

    def _scan_annotations(self):
        end = getattr(self.node, "end_lineno", self.node.lineno)
        for lineno in range(self.node.lineno, end + 1):
            m = _GUARDED_RE.search(self.ctx.line_text(lineno))
            if not m:
                continue
            attr = self._annotated_attr(lineno)
            if attr is None:
                continue
            if m.group(2):              # guarded by: none
                self.declared_unguarded.add(attr)
            else:
                self.guarded[attr] = (m.group(1), lineno)

    def _annotated_attr(self, lineno):
        """self.<attr> assigned on (a line of) the annotated statement — the
        annotation may sit on any line of a multi-line declaration."""
        for node in ast.walk(self.node):
            if isinstance(node, (ast.Assign, ast.AnnAssign, ast.AugAssign)) \
                    and node.lineno <= lineno \
                    <= getattr(node, "end_lineno", node.lineno):
                targets = node.targets if isinstance(node, ast.Assign) \
                    else [node.target]
                for t in targets:
                    if is_self_attr(t):
                        return t.attr
        return None

    # -- lockset propagation -------------------------------------------------
    def _finalize(self):
        self._resolve_bindings()
        self._propagate_inherited()
        for s in self.methods.values():
            for a in s.accesses:
                a.held = self._resolve(s, a.tokens) | s.inherited
            for ac in s.acquires:
                ac.held_before = self._resolve_attrs(ac.before)
            for c in s.calls:
                c.held = self._resolve_attrs(c.tokens)
            for b in s.blocking:
                b.held = self._resolve_attrs(b.tokens)
                b.ext = frozenset(n for k, n in b.tokens if k == "ext")
        self._propagate_blocking()
        self._propagate_acquires()

    def _resolve(self, summary, tokens):
        """Raw tokens -> lock names; a param-lock that doesn't resolve still
        counts as holding *a* lock (`?`) — the access isn't lock-free."""
        out = set()
        for kind, name in tokens:
            if kind == "attr":
                out.add(name)
            else:
                out.add(summary.bindings.get(name, _UNKNOWN))
        return frozenset(out)

    @staticmethod
    def _resolve_attrs(tokens):
        """Attribute-held locks only (order graph + blocking reports name
        real locks; param locks stay out of the cross-method graphs)."""
        return frozenset(n for k, n in tokens if k == "attr")

    def _call_sites(self, name):
        for s in self.methods.values():
            for c in s.calls:
                if c.kind in ("self", "ref") and c.method == name:
                    yield s, c

    def _resolve_bindings(self):
        """param name -> caller lock attr, when EVERY intra-class call site
        passes the same `self.<lock>` for that parameter."""
        for name, s in self.methods.items():
            bound = {}
            for caller, c in self._call_sites(name):
                for i, p in enumerate(s.params):
                    arg = c.args[i] if i < len(c.args) else \
                        next((v for k, v in c.keywords if k == p), None)
                    if arg is None:
                        continue
                    lock = arg.attr if (is_self_attr(arg)
                                        and arg.attr in self.locks) else None
                    prev = bound.get(p, lock)
                    bound[p] = lock if lock == prev else None
            s.bindings = {p: l for p, l in bound.items() if l}

    def _propagate_inherited(self):
        """Private helpers inherit the intersection of the locksets held at
        their intra-class call sites; public methods assume external callers
        (no locks). Bounded fixpoint over the intra-class call graph."""
        private = [n for n in self.methods
                   if n.startswith("_") and not n.startswith("__")]
        inh = {n: (None if n in private else frozenset())
               for n in self.methods}
        for _ in range(len(self.methods) + 1):
            changed = False
            for name in private:
                sites = list(self._call_sites(name))
                if not sites:
                    new = frozenset()
                else:
                    vals = []
                    for caller, c in sites:
                        base = inh[caller.name]
                        if base is None:
                            continue
                        vals.append(self._resolve_attrs(c.tokens) | base)
                    if not vals:
                        continue
                    new = frozenset.intersection(*vals)
                if new != inh[name]:
                    inh[name] = new
                    changed = True
            if not changed:
                break
        for name, s in self.methods.items():
            s.inherited = inh[name] or frozenset()

    def _propagate_blocking(self):
        """blocks_all: every blocking desc reachable through intra-class
        calls (regardless of locks — the caller's lockset decides)."""
        blocks = {n: {b.desc for b in s.blocking}
                  for n, s in self.methods.items()}
        for _ in range(len(self.methods) + 1):
            changed = False
            for n, s in self.methods.items():
                for c in s.calls:
                    if c.kind == "self" and c.method in blocks:
                        add = blocks[c.method] - blocks[n]
                        if add:
                            blocks[n] |= add
                            changed = True
            if not changed:
                break
        for n, s in self.methods.items():
            s.blocks_all = tuple(sorted(blocks[n]))

    def _propagate_acquires(self):
        """acquires_all: every lock attr acquired through intra-class calls."""
        acq = {n: {a.lock for a in s.acquires if a.lock in self.locks}
               for n, s in self.methods.items()}
        for _ in range(len(self.methods) + 1):
            changed = False
            for n, s in self.methods.items():
                for c in s.calls:
                    if c.kind == "self" and c.method in acq:
                        add = acq[c.method] - acq[n]
                        if add:
                            acq[n] |= add
                            changed = True
            if not changed:
                break
        for n, s in self.methods.items():
            s.acquires_all = frozenset(acq[n])


# ---------------------------------------------------------------------------
# program model (built once per analysis run, shared through the rule cache)
# ---------------------------------------------------------------------------


def file_models(ctx):
    """ClassModel for every class in one file."""
    return [ClassModel(ctx, node) for node in ctx.nodes
            if isinstance(node, ast.ClassDef)]


def get_program(contexts, cache):
    """The whole-program index: per-file class models plus a global
    name -> model map (ambiguous basenames resolve to None). Memoized in
    the per-run rule cache so GL003/GL018/GL019/GL020 build it once."""
    prog = cache.get("concurrency")
    if prog is not None:
        return prog
    files, classes = {}, {}
    for ctx in contexts:
        models = file_models(ctx)
        files[ctx.rel_path] = models
        for m in models:
            classes[m.name] = None if m.name in classes else m
    prog = {"files": files, "classes": classes}
    cache["concurrency"] = prog
    return prog


class _ConcurrencyRule(Rule):
    """Base: binds the shared program model before per-file checks."""

    def __init__(self):
        self._program = None

    def begin_program(self, contexts, cache):
        self._program = get_program(contexts, cache)

    def models(self, ctx):
        if self._program is None:      # direct rule.check() use in tests
            self._program = {"files": {}, "classes": {}}
        models = self._program["files"].get(ctx.rel_path)
        if models is None:
            models = file_models(ctx)
            self._program["files"][ctx.rel_path] = models
        return models

    def resolve_class(self, model, attr):
        """ClassModel behind `self.<attr>`, if its constructor basename maps
        to exactly one class in the program."""
        base = model.attr_types.get(attr)
        if base is None:
            return None
        return self._program["classes"].get(base)


# ---------------------------------------------------------------------------
# GL003 — lock-guard (annotation channel, now on the inferred lockset model)
# ---------------------------------------------------------------------------


@register
class LockGuardRule(_ConcurrencyRule):
    """Attributes annotated `# guarded by: self._lock` touched off-lock."""

    id = "GL003"
    name = "lock-guard"
    rationale = (
        "Shared mutable state documented as lock-guarded but read/written "
        "outside a `with self._lock:` block is a data race (the served-"
        "counter lost-update bug). The annotation makes the invariant "
        "machine-checked: declare it once where the attribute is "
        "initialized, and every off-lock access in the class is flagged — "
        "checked against the same inferred locksets GL018 uses, so helper "
        "methods called under the lock (or handed the lock) count as "
        "guarded. __init__/__del__ are exempt (no concurrent callers exist "
        "yet/still).")

    def check(self, ctx):
        for model in self.models(ctx):
            if not model.guarded:
                continue
            for name, s in model.methods.items():
                if name in model.EXEMPT_METHODS:
                    continue
                for a in s.accesses:
                    if a.attr not in model.guarded:
                        continue
                    lock, decl_line = model.guarded[a.attr]
                    if a.node.lineno == decl_line or lock in a.held:
                        continue
                    yield self.violation(
                        ctx, a.node,
                        f"self.{a.attr} is guarded by self.{lock} but "
                        f"accessed outside a `with self.{lock}:` block")


# ---------------------------------------------------------------------------
# GL018 — unguarded-shared-write (annotation-free lockset inference)
# ---------------------------------------------------------------------------


@register
class UnguardedSharedWriteRule(_ConcurrencyRule):
    """Attr written under a lock in one method, accessed lock-free in another."""

    id = "GL018"
    name = "unguarded-shared-write"
    rationale = (
        "An attribute written inside `with self._lock:` in one method is "
        "shared mutable state by declaration-of-behavior; touching it "
        "lock-free in another method of the same class is the PR 1 "
        "served-counter race without the annotation. GL003 generalized "
        "from opt-in annotations to inference — annotate `# guarded by: "
        "self.<lock>` to route it through GL003, or `# guarded by: none` "
        "to declare it deliberately unguarded.")

    def check(self, ctx):
        for model in self.models(ctx):
            if not model.locks:
                continue
            skip = (set(model.locks) | model.queues | model.threads
                    | set(model.guarded) | model.declared_unguarded)
            locked_writers = {}   # attr -> (method, lock) first locked write
            for name, s in model.methods.items():
                if name in model.EXEMPT_METHODS:
                    continue
                for a in s.accesses:
                    if a.attr in skip or not a.write or not a.held:
                        continue
                    lock = next((h for h in sorted(a.held)
                                 if h in model.locks), None)
                    if lock is None:
                        continue
                    locked_writers.setdefault(a.attr, (name, lock))
            if not locked_writers:
                continue
            write_methods = {}    # attr -> set of methods with locked writes
            for name, s in model.methods.items():
                for a in s.accesses:
                    if a.attr in locked_writers and a.write and a.held:
                        write_methods.setdefault(a.attr, set()).add(name)
            for name, s in model.methods.items():
                if name in model.EXEMPT_METHODS:
                    continue
                for a in s.accesses:
                    if a.attr not in locked_writers or a.held:
                        continue
                    if name in write_methods.get(a.attr, ()):
                        continue
                    w_meth, lock = locked_writers[a.attr]
                    yield self.violation(
                        ctx, a.node,
                        f"self.{a.attr} is written under self.{lock} in "
                        f"{w_meth}() but accessed lock-free here; hold the "
                        f"lock, or annotate the attribute `# guarded by: "
                        f"self.{lock}` / `# guarded by: none`")


# ---------------------------------------------------------------------------
# GL019 — blocking-under-lock
# ---------------------------------------------------------------------------


@register
class BlockingUnderLockRule(_ConcurrencyRule):
    """sleep/socket/subprocess/queue/join/device-sync while holding a lock."""

    id = "GL019"
    name = "blocking-under-lock"
    rationale = (
        "A blocking call under a lock turns one slow peer into a stalled "
        "process: every thread that needs the lock parks behind a network "
        "round-trip, a queue wait, or a device sync — the PR 16 mesh "
        "rendezvous deadlock (device wait under the run lock) and the PR 2 "
        "percentile-sort-under-the-metrics-lock stall both had this shape. "
        "Copy state out under the lock, block outside it; intentional "
        "holds (e.g. the mesh run lock serializing collective waves) are "
        "baselined with a note.")

    def check(self, ctx):
        for model in self.models(ctx):
            for name, s in model.methods.items():
                for b in s.blocking:
                    locks = sorted(h for h in b.held if h in model.locks)
                    if locks:
                        yield self.violation(
                            ctx, b.node,
                            f"{b.desc} blocks while holding "
                            f"self.{locks[0]}")
                    elif b.ext:
                        yield self.violation(
                            ctx, b.node,
                            f"{b.desc} blocks while holding "
                            f"{sorted(b.ext)[0]}")
                if not model.locks:
                    continue
                for c in s.calls:
                    locks = sorted(h for h in c.held if h in model.locks)
                    if not locks:
                        continue
                    target = None
                    if c.kind == "self":
                        target = model.methods.get(c.method)
                        label = f"self.{c.method}()"
                    else:
                        other = self.resolve_class(model, c.attr)
                        if other is not None:
                            target = other.methods.get(c.method)
                        label = f"self.{c.attr}.{c.method}()"
                    if target is not None and target.blocks_all:
                        yield self.violation(
                            ctx, c.node,
                            f"{label} reaches blocking "
                            f"{target.blocks_all[0]} while holding "
                            f"self.{locks[0]}")


# ---------------------------------------------------------------------------
# GL020 — lock-order-inversion
# ---------------------------------------------------------------------------


@register
class LockOrderInversionRule(_ConcurrencyRule):
    """Cycles in the static lock-acquisition-order graph."""

    id = "GL020"
    name = "lock-order-inversion"
    rationale = (
        "Two threads acquiring the same pair of locks in opposite orders "
        "deadlock the process the first time their schedules interleave — "
        "the bug class behind the PR 16 mesh run-lock freeze. The "
        "acquisition-order graph (lock A held while B is acquired => edge "
        "A->B, across intra-class helpers and typed-attribute calls) must "
        "stay acyclic; every edge of a cycle is reported so both "
        "acquisition paths are visible. Re-acquiring a non-reentrant lock "
        "is the length-1 cycle.")

    def begin_program(self, contexts, cache):
        super().begin_program(contexts, cache)
        if "lock_order" not in cache:
            cache["lock_order"] = self._build(self._program)
        self._cycle_edges = cache["lock_order"]

    def __init__(self):
        super().__init__()
        self._cycle_edges = None

    def _build(self, prog):
        edges = []   # (src_lockid, dst_lockid, rel_path, node, label)
        for rel_path, models in prog["files"].items():
            for model in models:
                self._class_edges(model, rel_path, edges)
        return self._cycles(edges)

    def _class_edges(self, model, rel_path, edges):
        def lock_id(m, attr):
            return (m.name, attr)

        for name, s in model.methods.items():
            for ac in s.acquires:
                if ac.lock not in model.locks:
                    continue
                dst = lock_id(model, ac.lock)
                if ("attr", ac.lock) in ac.before and \
                        model.locks[ac.lock] != "RLock":
                    edges.append((dst, dst, rel_path, ac.node,
                                  f"{model.name}.{name}() re-acquires "
                                  f"non-reentrant self.{ac.lock}"))
                for h in ac.held_before:
                    if h in model.locks and h != ac.lock:
                        edges.append((lock_id(model, h), dst, rel_path,
                                      ac.node,
                                      f"{model.name}.{name}() acquires "
                                      f"self.{ac.lock} while holding "
                                      f"self.{h}"))
            for c in s.calls:
                held = [h for h in c.held if h in model.locks]
                if not held:
                    continue
                if c.kind == "self":
                    target_model, target = model, model.methods.get(c.method)
                    label = f"self.{c.method}()"
                else:
                    target_model = self.resolve_class(model, c.attr)
                    target = target_model.methods.get(c.method) \
                        if target_model is not None else None
                    label = f"self.{c.attr}.{c.method}()"
                if target is None:
                    continue
                for dst_attr in target.acquires_all:
                    for h in held:
                        if target_model is model and dst_attr == h:
                            # same lock through a helper: a plain Lock
                            # self-deadlocks; an RLock re-enters fine
                            if model.locks[h] != "RLock":
                                edges.append((
                                    lock_id(model, h), lock_id(model, h),
                                    rel_path, c.node,
                                    f"{model.name}.{name}() holds self.{h} "
                                    f"and {label} re-acquires non-reentrant "
                                    f"self.{h}"))
                            continue
                        edges.append((
                            lock_id(model, h),
                            lock_id(target_model, dst_attr), rel_path,
                            c.node,
                            f"{model.name}.{name}() holds self.{h} and "
                            f"{label} acquires "
                            f"{target_model.name}.{dst_attr}"))

    @staticmethod
    def _cycles(edges):
        """Edges that sit on a cycle (Tarjan SCC; self-loops included),
        each annotated with a counter-path edge for the report."""
        graph = {}
        for src, dst, *_ in edges:
            graph.setdefault(src, set()).add(dst)
            graph.setdefault(dst, set())
        index, low, on, stack, comp = {}, {}, set(), [], {}
        counter = [0]

        def strongconnect(v):
            work = [(v, iter(sorted(graph[v])))]
            index[v] = low[v] = counter[0]
            counter[0] += 1
            stack.append(v)
            on.add(v)
            while work:
                node, it = work[-1]
                advanced = False
                for w in it:
                    if w not in index:
                        index[w] = low[w] = counter[0]
                        counter[0] += 1
                        stack.append(w)
                        on.add(w)
                        work.append((w, iter(sorted(graph[w]))))
                        advanced = True
                        break
                    if w in on:
                        low[node] = min(low[node], index[w])
                if advanced:
                    continue
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])
                if low[node] == index[node]:
                    while True:
                        w = stack.pop()
                        on.discard(w)
                        comp[w] = node
                        if w == node:
                            break

        for v in sorted(graph):
            if v not in index:
                strongconnect(v)
        multi = {c for c in set(comp.values())
                 if sum(1 for v in comp if comp[v] == c) > 1}
        cyclic = []
        for e in edges:
            src, dst = e[0], e[1]
            if src == dst or (comp.get(src) in multi
                              and comp[src] == comp.get(dst)):
                cyclic.append(e)
        out = []
        for e in cyclic:
            src, dst, rel_path, node, label = e
            counter_edge = next(
                (o for o in cyclic
                 if o is not e and o[0] == dst), None)
            out.append((rel_path, node, label, counter_edge))
        return out

    def check(self, ctx):
        for rel_path, node, label, counter_edge in (self._cycle_edges or ()):
            if rel_path != ctx.rel_path:
                continue
            if counter_edge is None:
                msg = f"lock-order inversion: {label} (self-deadlock)"
            else:
                _, _, c_path, c_node, c_label = counter_edge
                msg = (f"lock-order inversion: {label}, but {c_label} "
                       f"({c_path}:{c_node.lineno}) closes the cycle")
            yield self.violation(ctx, node, msg)
