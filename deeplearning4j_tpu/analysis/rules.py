"""GL001–GL017: the rule catalog (see RULES.md for the bug-history rationale).

Each rule is intra-file AST analysis with light import resolution: aliases
from ``import x as y`` / ``from m import n as y`` are resolved so
``np.asarray`` and ``numpy.asarray`` (or ``from jax import jit``) look the
same to a rule. Resolution is intentionally shallow — a linter trades
soundness for zero-setup speed; anything it can't prove, it stays quiet on.
"""
from __future__ import annotations

import ast
import re

from .core import Rule, import_aliases, register  # noqa: F401 (re-export)


# ---------------------------------------------------------------------------
# shared helpers
# ---------------------------------------------------------------------------


def qualname(node, aliases):
    """Resolve a Name/Attribute chain to a dotted origin, or None if the base
    name isn't an import-bound alias (i.e. probably a local variable)."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    base = aliases.get(node.id)
    if base is None:
        return None
    return ".".join([base] + parts[::-1])


def call_qual(node, aliases):
    """qualname of a Call's callee (None for non-calls/unresolvable)."""
    if not isinstance(node, ast.Call):
        return None
    return qualname(node.func, aliases)


def enclosing_function(ctx, node):
    """Innermost FunctionDef/AsyncFunctionDef containing `node`, or None."""
    for anc in ctx.ancestors(node):
        if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return anc
    return None


def is_self_attr(node, attr=None):
    return (isinstance(node, ast.Attribute)
            and isinstance(node.value, ast.Name) and node.value.id == "self"
            and (attr is None or node.attr == attr))


# ---------------------------------------------------------------------------
# GL001 — raw-clock
# ---------------------------------------------------------------------------

@register
class RawClockRule(Rule):
    """time.time()/time.monotonic() outside util/time_source."""

    id = "GL001"
    name = "raw-clock"
    rationale = (
        "Deadlines/timestamps read straight from the `time` module can't be "
        "driven by ManualClock, so every timeout test sleeps real wall time "
        "(or flakes). Route wall time through util.time_source.now_s()/"
        "now_ms() and durations/deadlines through monotonic_s().")

    ALLOW = ("util/time_source.py",)
    _CLOCKS = {"time.time": "now_s()/now_ms()",
               "time.monotonic": "monotonic_s()"}

    def check(self, ctx):
        if ctx.rel_path.endswith(self.ALLOW):
            return
        aliases = ctx.aliases
        for node in ctx.nodes:
            qual = call_qual(node, aliases)
            if qual in self._CLOCKS:
                yield self.violation(
                    ctx, node,
                    f"{qual}() read outside util/time_source; use "
                    f"util.time_source.{self._CLOCKS[qual]} so ManualClock "
                    f"tests can drive this clock")


# ---------------------------------------------------------------------------
# GL002 — unsafe-json
# ---------------------------------------------------------------------------

@register
class UnsafeJsonRule(Rule):
    """json.dumps on HTTP-response/payload paths instead of dumps_safe."""

    id = "GL002"
    name = "unsafe-json"
    rationale = (
        "Raw json.dumps emits bare NaN/Infinity, which JSON.parse and every "
        "strict decoder reject — a single non-finite float 500s or corrupts "
        "an HTTP response. util.http.dumps_safe serializes strict JSON "
        "(non-finite -> null, numpy scalars via default=).")

    # the one module allowed to call json.dumps on a payload path: the strict
    # serializer itself (dumps_safe's fast path IS json.dumps)
    ALLOW = ("util/http.py",)
    # modules whose whole job is building payloads that go over HTTP (stats
    # reports are POSTed to /remoteReceive and served back by UI endpoints):
    # every json.dumps there is payload serialization
    PAYLOAD_MODULES = ("ui/stats.py",)
    # callees whose arguments are HTTP bodies/responses
    _HTTP_SINKS = {"urllib.request.Request", "Request", "send_json",
                   "post_json"}

    def check(self, ctx):
        if ctx.rel_path.endswith(self.ALLOW):
            return
        aliases = ctx.aliases
        dumps_calls = [n for n in ctx.nodes
                       if call_qual(n, aliases) == "json.dumps"]
        if not dumps_calls:
            return
        if ctx.rel_path.endswith(self.PAYLOAD_MODULES):
            for call in dumps_calls:
                yield self._flag(ctx, call, "HTTP payload module")
            return
        handler_funcs = self._response_tuple_functions(ctx)
        flagged = set()
        for call in dumps_calls:
            fn = enclosing_function(ctx, call)
            if fn is not None and fn in handler_funcs:
                flagged.add(call)
                yield self._flag(ctx, call, "route handler response")
        for call, why in self._http_sink_flows(ctx, aliases, dumps_calls):
            if call not in flagged:
                flagged.add(call)
                yield self._flag(ctx, call, why)

    def _flag(self, ctx, call, why):
        return self.violation(
            ctx, call,
            f"json.dumps on an HTTP path ({why}); use util.http.dumps_safe "
            f"(strict JSON: non-finite floats -> null)")

    @staticmethod
    def _response_tuple_functions(ctx):
        """Functions returning the (status, content_type, body) route-handler
        tuple — identified by a content-type string constant in the tuple."""
        out = set()
        for node in ctx.nodes:
            if not (isinstance(node, ast.Return)
                    and isinstance(node.value, ast.Tuple)):
                continue
            for elt in node.value.elts:
                if (isinstance(elt, ast.Constant) and isinstance(elt.value, str)
                        and elt.value.startswith(("application/json", "text/"))):
                    fn = enclosing_function(ctx, node)
                    if fn is not None:
                        out.add(fn)
                    break
        return out

    def _http_sink_flows(self, ctx, aliases, dumps_calls):
        """(dumps_call, reason) pairs where the dumps result reaches an HTTP
        sink — inline, or through one simple same-function assignment."""
        dumps_set = set(dumps_calls)
        # name -> dumps node, for `body = json.dumps(d).encode()` idioms,
        # scoped per enclosing function to avoid cross-function aliasing
        tainted = {}
        for node in ctx.nodes:
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                for sub in ast.walk(node.value):
                    if sub in dumps_set:
                        fn = enclosing_function(ctx, node)
                        tainted[(fn, node.targets[0].id)] = sub
                        break
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            qual = qualname(node.func, aliases)
            name = node.func.attr if isinstance(node.func, ast.Attribute) \
                else (node.func.id if isinstance(node.func, ast.Name) else None)
            is_sink = (qual in self._HTTP_SINKS or name in self._HTTP_SINKS
                       or (name == "write" and isinstance(node.func, ast.Attribute)
                           and isinstance(node.func.value, ast.Attribute)
                           and node.func.value.attr == "wfile"))
            if not is_sink:
                continue
            fn = enclosing_function(ctx, node)
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for sub in ast.walk(arg):
                    if sub in dumps_set:
                        yield sub, "flows into an HTTP request/response"
                    elif isinstance(sub, ast.Name) \
                            and (fn, sub.id) in tainted:
                        yield tainted[(fn, sub.id)], \
                            f"'{sub.id}' flows into an HTTP request/response"


# ---------------------------------------------------------------------------
# GL003 — lock-guard: moved to concurrency.py, where the annotation channel
# is checked against the same inferred locksets GL018–GL020 use.
# ---------------------------------------------------------------------------


# ---------------------------------------------------------------------------
# GL004 — jit-host-sync
# ---------------------------------------------------------------------------

@register
class JitHostSyncRule(Rule):
    """Host round-trips / trace hazards inside jit-traced functions."""

    id = "GL004"
    name = "jit-host-sync"
    rationale = (
        ".item()/.tolist()/np.asarray/float()/int()/block_until_ready inside "
        "a jit-traced function either fails at trace time (concretization "
        "error) or silently forces a device->host sync per call, serializing "
        "the dispatch queue — the classic JAX/TF trace-hazard class that "
        "large codebases gate with lint.")

    _SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
    _SYNC_QUALS = {"numpy.asarray", "numpy.array", "jax.device_get"}

    def check(self, ctx):
        aliases = ctx.aliases
        seen = set()
        for fn in self._traced_functions(ctx, aliases):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call) or id(node) in seen:
                    continue
                v = self._hazard(ctx, node, aliases, fn)
                if v is not None:
                    seen.add(id(node))
                    yield v

    def _hazard(self, ctx, node, aliases, fn):
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in self._SYNC_ATTRS:
            return self.violation(
                ctx, node,
                f".{node.func.attr}() inside jit-traced `{fn.name}` forces a "
                f"host sync or fails at trace time")
        qual = call_qual(node, aliases)
        if qual in self._SYNC_QUALS:
            return self.violation(
                ctx, node,
                f"{qual}() inside jit-traced `{fn.name}` materializes the "
                f"array on host (trace hazard)")
        if isinstance(node.func, ast.Name) and node.func.id in ("float", "int") \
                and node.args and not all(isinstance(a, ast.Constant)
                                          for a in node.args):
            return self.violation(
                ctx, node,
                f"{node.func.id}() on a traced value inside `{fn.name}` "
                f"concretizes at trace time (TracerConversionError) or "
                f"host-syncs; use jnp casts or hoist out of jit")
        return None

    @classmethod
    def is_jit_expr(cls, node, aliases):
        """`jax.jit`, `jit` (imported from jax), or partial(jax.jit, ...)."""
        if qualname(node, aliases) == "jax.jit":
            return True
        if isinstance(node, ast.Call):
            q = qualname(node.func, aliases)
            if q == "jax.jit":
                return True
            if q in ("functools.partial", "partial") and node.args \
                    and qualname(node.args[0], aliases) == "jax.jit":
                return True
        return False

    def _traced_functions(self, ctx, aliases):
        """FunctionDefs traced by jit: decorated with jax.jit/partial(jax.jit)
        or passed by name to a jax.jit(...) call anywhere in the file."""
        wrapped_names = set()
        for node in ctx.nodes:
            if isinstance(node, ast.Call) \
                    and qualname(node.func, aliases) == "jax.jit" \
                    and node.args and isinstance(node.args[0], ast.Name):
                wrapped_names.add(node.args[0].id)
        for node in ctx.nodes:
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            if node.name in wrapped_names \
                    or any(self.is_jit_expr(d, aliases)
                           for d in node.decorator_list):
                yield node


# ---------------------------------------------------------------------------
# GL005 — thread-hygiene
# ---------------------------------------------------------------------------

@register
class ThreadHygieneRule(Rule):
    """Threads that outlive their owner; exceptions swallowed in workers."""

    id = "GL005"
    name = "thread-hygiene"
    rationale = (
        "A non-daemon thread that nothing joins keeps the interpreter alive "
        "after main() exits (hung test runs, zombie workers); a bare "
        "`except: pass` in a worker loop turns crashes into silent data "
        "loss. Either mark threads daemon= explicitly or join them from a "
        "close()/stop()/drain() path; worker loops must record or surface "
        "errors.")

    def check(self, ctx):
        aliases = ctx.aliases
        joined = self._joined_or_daemonized(ctx)
        for node in ctx.nodes:
            if isinstance(node, ast.Call) \
                    and qualname(node.func, aliases) == "threading.Thread" \
                    and not any(kw.arg == "daemon" for kw in node.keywords):
                target = self._assign_target(ctx, node)
                if target is None or target not in joined:
                    yield self.violation(
                        ctx, node,
                        "threading.Thread without daemon= and never joined: "
                        "pass daemon= explicitly, or join it from a "
                        "close()/stop()/drain() method")
            if isinstance(node, ast.ExceptHandler) \
                    and self._swallows_everything(node, aliases) \
                    and len(node.body) == 1 \
                    and isinstance(node.body[0], ast.Pass) \
                    and self._in_loop(ctx, node):
                yield self.violation(
                    ctx, node,
                    "`except: pass` inside a worker loop swallows every "
                    "error silently; record it (counter/log) or re-raise")

    @staticmethod
    def _swallows_everything(handler, aliases):
        if handler.type is None:
            return True
        qual = qualname(handler.type, aliases)
        name = handler.type.id if isinstance(handler.type, ast.Name) else None
        return name in ("Exception", "BaseException") \
            or qual in ("Exception", "BaseException")

    @staticmethod
    def _in_loop(ctx, node):
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.While, ast.For)):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
        return False

    def _assign_target(self, ctx, call):
        """'self.<attr>' / bare name the Thread is stored into, or None."""
        for anc in ctx.ancestors(call):
            if isinstance(anc, ast.Assign):
                t = anc.targets[0]
                if is_self_attr(t):
                    return f"self.{t.attr}"
                if isinstance(t, ast.Name):
                    return t.id
                return None
            if isinstance(anc, ast.stmt):
                return None
        return None

    @staticmethod
    def _joined_or_daemonized(ctx):
        """Targets with `<target>.join(...)` called or `.daemon = True`
        assigned anywhere in the file."""
        out = set()

        def target_of(node):
            if is_self_attr(node):
                return f"self.{node.attr}"
            if isinstance(node, ast.Name):
                return node.id
            return None

        for node in ctx.nodes:
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "join":
                t = target_of(node.func.value)
                if t:
                    out.add(t)
            if isinstance(node, ast.Assign) \
                    and isinstance(node.targets[0], ast.Attribute) \
                    and node.targets[0].attr == "daemon":
                t = target_of(node.targets[0].value)
                if t:
                    out.add(t)
        return out


# ---------------------------------------------------------------------------
# GL006 — per-call-jit
# ---------------------------------------------------------------------------

@register
class PerCallJitRule(Rule):
    """jax.jit(...) built inside a loop without a cached handle."""

    id = "GL006"
    name = "per-call-jit"
    rationale = (
        "Every jax.jit(...) call creates a FRESH wrapper with its own "
        "compilation cache — invoked per loop iteration or per request it "
        "recompiles every time (seconds per call on TPU). Hoist the jit "
        "out of the loop or store the wrapper in a keyed cache "
        "(`self._jits[key] = jax.jit(fn)` is recognized as the cache idiom).")

    def check(self, ctx):
        aliases = ctx.aliases
        for node in ctx.nodes:
            if not (isinstance(node, ast.Call)
                    and qualname(node.func, aliases) == "jax.jit"):
                continue
            if self._in_loop_directly(ctx, node) \
                    and not self._cached(ctx, node):
                yield self.violation(
                    ctx, node,
                    "jax.jit(...) constructed inside a loop recompiles on "
                    "every iteration; hoist it out or store the wrapper in "
                    "a keyed cache")

    @staticmethod
    def _in_loop_directly(ctx, node):
        """Inside a For/While of the SAME function body (a def boundary stops
        the search: code in a nested function doesn't run per iteration of
        the loop that merely defines it)."""
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.While, ast.For)):
                return True
            if isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                ast.Lambda)):
                return False
        return False

    @staticmethod
    def _cached(ctx, node):
        """`cache[key] = jax.jit(...)` (possibly inside a tuple) is the
        accepted memoization idiom."""
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.Assign):
                return any(isinstance(t, ast.Subscript) for t in anc.targets)
            if isinstance(anc, ast.stmt):
                return False
        return False


# ---------------------------------------------------------------------------
# GL007 — ingest-host-widening
# ---------------------------------------------------------------------------

@register
class IngestHostWideningRule(Rule):
    """Host-side float32/float64 widening casts on the ingest hot path."""

    id = "GL007"
    name = "ingest-host-widening"
    rationale = (
        "A host-side astype(np.float32)/np.asarray(..., np.float32) in a "
        "prefetcher/pipeline worker loop quadruples the bytes every batch "
        "drags across the host link — BENCH_r05 measured that link as THE "
        "end-to-end wall (e2e_binding=host_link, chip fed at 7.7% of "
        "compute). Ship narrow bytes (uint8/int codes) and let the compiled "
        "step do the widening on-device (etl.device_transform.DeviceIngest "
        "/ network.set_ingest); a deliberate host-path remainder belongs in "
        "the baseline with a note.")

    # the ingest hot path: everything running per-batch in these modules is
    # on (or feeding) a prefetcher/pipeline worker loop
    HOT_MODULES = ("etl/prefetch.py", "etl/pipeline.py")
    # elsewhere, only functions that self-identify as worker loops
    _WORKER_FN = re.compile(r"^(_?worker\w*|\w*_loop|_process|_put)$")
    _WIDE_QUALS = {"numpy.float32", "numpy.float64"}

    def check(self, ctx):
        aliases = ctx.aliases
        hot_module = ctx.rel_path.endswith(self.HOT_MODULES)
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            fn = enclosing_function(ctx, node)
            if fn is None:       # module-level constant setup: not per-batch
                continue
            if not hot_module and not self._WORKER_FN.match(fn.name):
                continue
            wide = self._widening(node, aliases)
            if wide is not None:
                yield self.violation(
                    ctx, node,
                    f"host-side widening cast to {wide} on the ingest hot "
                    f"path (`{fn.name}`): ship narrow bytes and cast on "
                    f"device (etl.device_transform), or baseline with a "
                    f"note if the wide host path is intentional")

    def _widening(self, node, aliases):
        """The float32/float64 target of an astype/asarray/array widening
        call, or None."""
        if isinstance(node.func, ast.Attribute) and node.func.attr == "astype":
            cand = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "dtype"), None)
            return self._float_dtype(cand, aliases)
        qual = call_qual(node, aliases)
        if qual in ("numpy.asarray", "numpy.array"):
            cand = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "dtype"), None)
            return self._float_dtype(cand, aliases)
        return None

    def _float_dtype(self, node, aliases):
        if node is None:
            return None
        qual = qualname(node, aliases)
        if qual in self._WIDE_QUALS:
            return qual
        if isinstance(node, ast.Constant) and node.value in ("float32",
                                                             "float64"):
            return node.value
        return None


# ---------------------------------------------------------------------------
# GL008 — raw-http-client
# ---------------------------------------------------------------------------

@register
class RawHttpClientRule(Rule):
    """Outbound urllib.request / http.client use outside util/http.py."""

    id = "GL008"
    name = "raw-http-client"
    rationale = (
        "util.http.post_json/get_json are THE outbound HTTP choke point: "
        "they inject the W3C traceparent header (telemetry.propagation), so "
        "every cross-process hop joins the caller's trace, and they "
        "serialize strict JSON. A raw urllib.request/http.client call "
        "bypasses both — the request becomes an untraceable hole in the "
        "fleet view. A deliberate raw client (bulk artifact download) "
        "belongs in the baseline with a note.")

    ALLOW = ("util/http.py",)
    _CLIENT_PREFIXES = ("urllib.request.", "http.client.")

    def check(self, ctx):
        if ctx.rel_path.endswith(self.ALLOW):
            return
        aliases = ctx.aliases
        for node in ctx.nodes:
            qual = call_qual(node, aliases)
            if qual is not None and qual.startswith(self._CLIENT_PREFIXES):
                yield self.violation(
                    ctx, node,
                    f"{qual}() outside util/http.py bypasses the traceparent-"
                    f"injecting client choke point; use util.http.post_json/"
                    f"get_json (or baseline a deliberate raw client with a "
                    f"note)")


# ---------------------------------------------------------------------------
# GL009 — raw-retry-loop
# ---------------------------------------------------------------------------

@register
class RawRetryLoopRule(Rule):
    """Ad-hoc for/while retry loops with in-loop sleeps outside resilience/."""

    id = "GL009"
    name = "raw-retry-loop"
    rationale = (
        "A hand-rolled `for attempt in range(n): try ... except: "
        "time.sleep(...)` loop has no jitter (retries synchronize into "
        "thundering herds), no retry budget (a fleet-wide outage is "
        "amplified by the retry factor), no deadline (the caller waits the "
        "full worst case), and its own bespoke backoff constants. "
        "resilience.RetryPolicy is the one implementation with all four; "
        "the repo had grown three divergent copies of this loop before it "
        "existed. Sleeps that merely pace a loop (no except handler) are "
        "not retries and stay quiet.")

    # the policy implementation itself (and its chaos harness) may sleep
    ALLOW_DIR = "deeplearning4j_tpu/resilience/"

    def check(self, ctx):
        if ctx.rel_path.startswith(self.ALLOW_DIR):
            return
        aliases = ctx.aliases
        for node in ctx.nodes:
            if call_qual(node, aliases) != "time.sleep":
                continue
            if self._sleep_in_loop_handler(ctx, node):
                yield self.violation(
                    ctx, node,
                    "sleep inside an except handler inside a loop — a "
                    "hand-rolled retry; use resilience.RetryPolicy "
                    "(backoff + jitter + budget + deadline) instead")

    @staticmethod
    def _sleep_in_loop_handler(ctx, node):
        """The retry tell: the sleep sits INSIDE an except handler that is
        itself inside a for/while in the same function — the shape of all
        three hand-rolled loops this rule was derived from. A pacing sleep
        in a loop that merely CONTAINS an unrelated try/except (queue
        pollers draining with `except Empty: pass`, loops defining
        callbacks with their own handlers) stays quiet. A def/lambda
        boundary stops the search, like GL006/GL007."""
        handler = False
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.ExceptHandler):
                handler = True
            elif isinstance(anc, (ast.While, ast.For)):
                return handler
            elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda)):
                return False
        return False


# ---------------------------------------------------------------------------
# GL010 — jit-missing-donation
# ---------------------------------------------------------------------------

@register
class JitMissingDonationRule(Rule):
    """jax.jit over a params/opt_state-taking step without donate_argnums."""

    id = "GL010"
    name = "jit-missing-donation"
    rationale = (
        "The headline train step sits at the HBM roofline "
        "(BENCH_r05 roofline_util~1.0): without donate_argnums the XLA "
        "executable allocates FRESH output buffers for params and updater "
        "state every step — double the state bytes resident and an extra "
        "full copy of HBM traffic, i.e. milliseconds per step. Every "
        "train-step jit in the nn/ and parallel/ hot modules must donate "
        "its params/opt_state arguments (the functional analog of the "
        "reference's in-place flattened param view). Inference jits that "
        "take `params` but must NOT donate them (the same buffers serve "
        "every call) are deliberate remainders — baseline them with a "
        "note.")

    HOT_DIRS = ("deeplearning4j_tpu/nn/", "deeplearning4j_tpu/parallel/")
    STATE_ARGS = frozenset({"params", "opt_state"})

    def check(self, ctx):
        if not ctx.rel_path.startswith(self.HOT_DIRS):
            return
        aliases = ctx.aliases
        defs = {}
        for node in ctx.nodes:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                defs.setdefault(node.name, node)
        for node in ctx.nodes:
            # call form: jax.jit(step_fn, ...) — resolve a Name argument to
            # its def in this file (the repo idiom: def then jit) or an
            # inline lambda; opaque expressions stay quiet (shallow-and-
            # sound-enough, like every rule here)
            if isinstance(node, ast.Call) \
                    and qualname(node.func, aliases) == "jax.jit" \
                    and not self._donates(node):
                target = node.args[0] if node.args else None
                fn = None
                if isinstance(target, ast.Name):
                    fn = defs.get(target.id)
                elif isinstance(target, ast.Lambda):
                    fn = target
                if fn is not None and self._takes_state(fn):
                    yield self.violation(
                        ctx, node,
                        "jax.jit over a params/opt_state-taking function "
                        "without donate_argnums: the step pays a fresh "
                        "state-sized allocation + copy every call; donate "
                        "the state args (or baseline an inference jit "
                        "with a note)")
            # decorator form: @jax.jit above a params/opt_state-taking def
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if qualname(dec, aliases) == "jax.jit" \
                            and self._takes_state(node):
                        yield self.violation(
                            ctx, node,
                            f"@jax.jit on `{node.name}({', '.join(a.arg for a in node.args.args)})` "
                            "cannot pass donate_argnums: use "
                            "jax.jit(fn, donate_argnums=...) so the "
                            "params/opt_state buffers alias in place")

    @staticmethod
    def _donates(call):
        return any(kw.arg == "donate_argnums" for kw in call.keywords)

    @classmethod
    def _takes_state(cls, fn):
        names = {a.arg for a in fn.args.args + fn.args.kwonlyargs}
        return bool(names & cls.STATE_ARGS)


# ---------------------------------------------------------------------------
# GL011 — decode-dynamic-shape
# ---------------------------------------------------------------------------

@register
class DecodeDynamicShapeRule(Rule):
    """Token-count-dependent shapes in decode/generate loops."""

    id = "GL011"
    name = "decode-dynamic-shape"
    rationale = (
        "An autoregressive decode loop that grows a tensor per token "
        "(jnp.concatenate/append of the sequence-so-far) or derives a "
        "shape from a python-int len() of the tokens-so-far presents XLA "
        "with a NEW shape every token — one full executable compile per "
        "generated token, orders of magnitude over the dispatch cost (the "
        "Julia-TPU paper's central observation, and the exact failure mode "
        "the decode engine's fixed-shape KV cache + dynamic_update_slice "
        "exists to prevent). In a decode-loop-named function, grow a "
        "FIXED-capacity buffer with lax.dynamic_update_slice and mask by a "
        "length vector instead.")

    # functions (any enclosing def) whose name marks a decode/token loop
    NAME_RE = re.compile(r"decode|generate|autoregress|token_loop",
                         re.IGNORECASE)
    GROW_CALLS = frozenset({
        "numpy.concatenate", "numpy.append", "numpy.hstack", "numpy.vstack",
        "jax.numpy.concatenate", "jax.numpy.append", "jax.numpy.hstack",
        "jax.numpy.vstack"})
    SHAPE_CTORS = frozenset({
        "numpy.zeros", "numpy.ones", "numpy.full", "numpy.empty",
        "numpy.arange", "jax.numpy.zeros", "jax.numpy.ones",
        "jax.numpy.full", "jax.numpy.empty", "jax.numpy.arange",
        "jax.nn.one_hot"})

    def check(self, ctx):
        aliases = ctx.aliases
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            if not self._in_decode_loop(ctx, node):
                continue
            qual = qualname(node.func, aliases)
            if qual in self.GROW_CALLS:
                yield self.violation(
                    ctx, node,
                    f"{qual.split('.')[-1]} inside a decode loop grows the "
                    "sequence tensor per token — a fresh shape (and XLA "
                    "compile) every step; append into a fixed-capacity "
                    "cache with lax.dynamic_update_slice + a length mask")
            elif qual in self.SHAPE_CTORS and self._len_arg(node):
                yield self.violation(
                    ctx, node,
                    f"{qual.split('.')[-1]} sized by len(...) inside a "
                    "decode loop — a python-int shape that tracks the "
                    "token count recompiles every step; size by the fixed "
                    "cache capacity and mask the tail")

    @classmethod
    def _in_decode_loop(cls, ctx, node):
        """Inside a for/while that is itself inside (or equal to the body
        of) a def whose name matches NAME_RE. The loop requirement keeps
        one-shot setup concat (building the prompt) quiet; the name
        requirement keeps ordinary data plumbing quiet."""
        in_loop = False
        for anc in ctx.ancestors(node):
            if isinstance(anc, (ast.For, ast.While)):
                in_loop = True
            elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if in_loop and cls.NAME_RE.search(anc.name):
                    return True
                # keep walking: a helper defined inside a decode fn whose
                # OWN name doesn't match is still that decode loop's body
        return False

    @staticmethod
    def _len_arg(call):
        """Any argument expression containing a len(...) call."""
        for arg in list(call.args) + [kw.value for kw in call.keywords]:
            for sub in ast.walk(arg):
                if isinstance(sub, ast.Call) \
                        and isinstance(sub.func, ast.Name) \
                        and sub.func.id == "len":
                    return True
        return False


# ---------------------------------------------------------------------------
# GL012 — unbounded-spawn
# ---------------------------------------------------------------------------

@register
class UnboundedSpawnRule(Rule):
    """Thread/process spawn inside a while loop without a max-count guard."""

    id = "GL012"
    name = "unbounded-spawn"
    rationale = (
        "The elastic subsystem makes replica/thread spawning a routine "
        "reaction to load signals — and a reaction loop with no ceiling is "
        "how a flapping signal (or a health probe that never goes green) "
        "forks servers until the host dies. Spawn authority therefore "
        "lives behind the ReplicaLauncher SPI (elastic/launcher.py), which "
        "enforces max_replicas at the one choke point. Everywhere else, a "
        "threading.Thread/subprocess.Popen constructed inside a `while` "
        "loop — the unbounded-iteration shape — must sit in a function "
        "that visibly bounds the count (a comparison against a "
        "max/cap/limit/capacity name, or a non-blocking Semaphore "
        "acquire). For-loop spawns over a materialized collection "
        "(_fan_out, pipeline worker pools) are bounded by construction "
        "and stay quiet.")

    SPAWN_CALLS = frozenset({"threading.Thread", "subprocess.Popen",
                             "multiprocessing.Process"})
    #: the launcher/controller modules that OWN spawn (and its guard)
    ALLOWED_FILES = ("deeplearning4j_tpu/elastic/launcher.py",)
    GUARD_RE = re.compile(r"max|cap(?:acity)?|limit|budget|bound",
                          re.IGNORECASE)

    def check(self, ctx):
        if ctx.rel_path in self.ALLOWED_FILES:
            return
        aliases = ctx.aliases
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            if qualname(node.func, aliases) not in self.SPAWN_CALLS:
                continue
            fn = self._enclosing_while_fn(ctx, node)
            if fn is None:
                continue
            if self._has_count_guard(fn):
                continue
            yield self.violation(
                ctx, node,
                "thread/process spawn inside a while loop with no visible "
                "max-count guard: a wedged condition forks until the host "
                "dies; bound it (compare against a max_*/cap/limit, or a "
                "non-blocking Semaphore.acquire) or route the spawn "
                "through the elastic ReplicaLauncher SPI")

    @staticmethod
    def _enclosing_while_fn(ctx, node):
        """The enclosing function def IF the spawn sits inside a `while`
        loop within it (the innermost def wins: a bounded helper defined
        inside an unbounded loop is judged on its own body)."""
        in_while = False
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.While):
                in_while = True
            elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return anc if in_while else None
        return None

    @classmethod
    def _has_count_guard(cls, fn):
        """A visible bound anywhere in the enclosing function: a comparison
        touching a max/cap/limit-named name or attribute, or a
        `sem.acquire(blocking=False)` try-acquire."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Compare):
                for side in [node.left] + list(node.comparators):
                    for sub in ast.walk(side):
                        name = None
                        if isinstance(sub, ast.Name):
                            name = sub.id
                        elif isinstance(sub, ast.Attribute):
                            name = sub.attr
                        if name is not None and cls.GUARD_RE.search(name):
                            return True
            if isinstance(node, ast.Call) \
                    and isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "acquire":
                for kw in node.keywords:
                    if kw.arg == "blocking" \
                            and isinstance(kw.value, ast.Constant) \
                            and kw.value.value is False:
                        return True
        return False


# ---------------------------------------------------------------------------
# GL013 — non-durable-publish
# ---------------------------------------------------------------------------

@register
class NonDurablePublishRule(Rule):
    """Bare os.replace publishing a persistent artifact outside util/fs.py."""

    id = "GL013"
    name = "non-durable-publish"
    rationale = (
        "os.replace is atomic in the NAMESPACE but not durable: POSIX only "
        "promises the rename survives a crash if the file's data was "
        "fsync'd before it and the parent directory's entry after it. "
        "Without both, a power loss can publish a name pointing at "
        "zero-length or stale data — the crash-after-replace bug that "
        "turned 'the newest checkpoint' into a torn zip. util.fs "
        "(atomic_write / publish_file / atomic_publish_dir) does the fsync "
        "dance once, correctly, and feeds the disk-fault chaos seam; a "
        "deliberately non-durable replace (scratch/cache-only files) "
        "belongs in the baseline with a note.")

    ALLOW = ("util/fs.py",)

    def check(self, ctx):
        if ctx.rel_path.endswith(self.ALLOW):
            return
        aliases = ctx.aliases
        for node in ctx.nodes:
            if call_qual(node, aliases) == "os.replace":
                yield self.violation(
                    ctx, node,
                    "os.replace publishes without the fsync-before/after "
                    "dance (not durable across power loss); route the "
                    "publish through util.fs.atomic_write / publish_file / "
                    "atomic_publish_dir, or baseline a deliberately "
                    "non-durable replace with a note")


# ---------------------------------------------------------------------------
# GL014 — quant-silent-widening
# ---------------------------------------------------------------------------

@register
class QuantSilentWideningRule(Rule):
    """float32/float64 widening of quantized moment/weight leaves outside
    the designated quant/dequant modules."""

    id = "GL014"
    name = "quant-silent-widening"
    rationale = (
        "The bytes diet (ROADMAP item 3) only works while the quantized "
        "leaves STAY narrow: an `astype(np.float32)` / `jnp.float32(...)` "
        "on moment or weight-quant leaves outside nn/quant.py or "
        "parallel/zero.py silently re-materializes the f32 bytes the diet "
        "removed (HBM reads widen again at roofline_util~1.0) AND bypasses "
        "the codec's exact-round-trip contract — a hand-widened moment "
        "re-quantizes through a different path and the bitwise re-shard "
        "guarantees quietly rot. Decode through the codec (MomentCodec."
        "decode / WeightQuant.dequant), or baseline a deliberate host-side "
        "widening with a note.")

    # the designated quant/dequant homes: the codecs themselves and the
    # ZeRO layout that drives them
    ALLOW = ("nn/quant.py", "parallel/zero.py")
    # receivers/arguments that look like quantized artifacts — exact
    # segment tokens only ("quantile"/"quantity" must NOT match)
    _QUANT_NAME = re.compile(
        r"(^|_)(q8|q?codes?|q?scales?|quant|quantized|dequant|dequantized"
        r"|moments?|mu|nu)(_|$)")
    _WIDE_QUALS = {"numpy.float32", "numpy.float64",
                   "jax.numpy.float32", "jax.numpy.float64"}

    def check(self, ctx):
        if ctx.rel_path.endswith(self.ALLOW):
            return
        aliases = ctx.aliases
        for node in ctx.nodes:
            if not isinstance(node, ast.Call):
                continue
            wide, target = self._widening(node, aliases)
            if wide is None or target is None:
                continue
            name = self._leaf_name(target)
            if name is not None and self._QUANT_NAME.search(name):
                yield self.violation(
                    ctx, node,
                    f"widening `{name}` to {wide} outside the designated "
                    f"quant modules re-materializes the bytes the diet "
                    f"removed and bypasses the codec round-trip; decode "
                    f"via nn.quant (MomentCodec.decode / WeightQuant."
                    f"dequant), or baseline a deliberate widening with a "
                    f"note")

    def _widening(self, node, aliases):
        """(widened-to dtype, the node being widened), or (None, None)."""
        # x.astype(np.float32) / x.astype(dtype=np.float32) / x.astype("float32")
        if isinstance(node.func, ast.Attribute) and node.func.attr == "astype":
            cand = node.args[0] if node.args else next(
                (kw.value for kw in node.keywords if kw.arg == "dtype"), None)
            return self._float_dtype(cand, aliases), node.func.value
        qual = call_qual(node, aliases)
        # jnp.float32(x) / np.float64(x) constructor-style widening
        if qual in self._WIDE_QUALS and node.args:
            return qual, node.args[0]
        # np.asarray(x, np.float32) / jnp.array(x, dtype=jnp.float32)
        if qual in ("numpy.asarray", "numpy.array",
                    "jax.numpy.asarray", "jax.numpy.array") and node.args:
            cand = node.args[1] if len(node.args) > 1 else next(
                (kw.value for kw in node.keywords if kw.arg == "dtype"), None)
            return self._float_dtype(cand, aliases), node.args[0]
        return None, None

    def _float_dtype(self, node, aliases):
        if node is None:
            return None
        qual = qualname(node, aliases)
        if qual in self._WIDE_QUALS:
            return qual
        if isinstance(node, ast.Constant) and node.value in ("float32",
                                                             "float64"):
            return node.value
        return None

    @staticmethod
    def _leaf_name(node):
        """The identifier a widening targets: bare name, attribute tail
        (self._mu -> "_mu"), or a constant-string subscript key
        (state["qcodes"] -> "qcodes"). Calls/expressions stay None — the
        rule only claims what it can name."""
        if isinstance(node, ast.Name):
            return node.id
        if isinstance(node, ast.Attribute):
            return node.attr
        if isinstance(node, ast.Subscript) \
                and isinstance(node.slice, ast.Constant) \
                and isinstance(node.slice.value, str):
            return node.slice.value
        return None


# ---------------------------------------------------------------------------
# GL015 — mesh-replicated-dispatch
# ---------------------------------------------------------------------------

@register
class MeshReplicatedDispatchRule(Rule):
    """Batch placement in serving/decode hot paths without a sharding."""

    id = "GL015"
    name = "mesh-replicated-dispatch"
    rationale = (
        "Mesh-sharded serving (ROADMAP item 1, serving/mesh.py) only "
        "splits a /predict wave across chips if the batch is PLACED with a "
        "NamedSharding before the jitted forward: a bare jax.device_put "
        "(or an implicit jnp.asarray placement) in a serving/decode "
        "dispatch path commits the whole batch to device 0, XLA compiles "
        "a replicated executable, and N-1 chips idle while reporting a "
        "healthy mesh — throughput silently collapses to single-chip with "
        "no error anywhere. In serving/ and decode/ hot paths, every "
        "device placement of a batch-shaped operand must flow through a "
        "NamedSharding / with_sharding_constraint / *_sharding helper (or "
        "sit in a visibly sharding-aware statement).")

    #: the modules whose dispatch paths feed mesh executables
    HOT_PREFIXES = ("deeplearning4j_tpu/serving/",
                    "deeplearning4j_tpu/decode/")
    #: functions that ARE the dispatch hot path (batcher dispatch, model
    #: forward, decode legs) — implicit placement only matters where the
    #: batch meets the executable
    HOT_FN_RE = re.compile(
        r"dispatch|output|predict|prefill|step|generate|warmup",
        re.IGNORECASE)
    _PLACERS = ("jax.device_put",)
    _IMPLICIT = ("jax.numpy.asarray", "jax.numpy.array", "jax.numpy.stack")
    _SHARDY = re.compile(r"shard", re.IGNORECASE)

    def check(self, ctx):
        if not ctx.rel_path.startswith(self.HOT_PREFIXES):
            return
        aliases = ctx.aliases
        for node in ctx.nodes:
            qual = call_qual(node, aliases)
            if qual in self._PLACERS:
                if not self._sharding_aware(self._statement(ctx, node)):
                    yield self.violation(
                        ctx, node,
                        "device_put without a NamedSharding in a "
                        "serving/decode hot path commits the operand to one "
                        "device — the mesh executable replicates and N-1 "
                        "chips idle; place through mesh.batch_sharding / "
                        "cache_sharding (or an explicit NamedSharding)")
            elif qual in self._IMPLICIT:
                fn = enclosing_function(ctx, node)
                if fn is not None and self.HOT_FN_RE.search(fn.name) \
                        and not self._sharding_aware(fn):
                    yield self.violation(
                        ctx, node,
                        f"{qual.split('.')[-1]} in dispatch hot path "
                        f"`{fn.name}` places the batch implicitly on device "
                        "0 with no sharding anywhere in the function; "
                        "np.asarray on the host side, then device_put under "
                        "the mesh batch sharding")

    @staticmethod
    def _statement(ctx, node):
        """Nearest enclosing statement — the visibility scope for 'is this
        placement sharding-aware': `tree_map(lambda l, s: device_put(l, s),
        cache, self.cache_shardings())` is aware through its sibling arg."""
        for anc in ctx.ancestors(node):
            if isinstance(anc, ast.stmt):
                return anc
        return node

    @classmethod
    def _sharding_aware(cls, tree):
        """Any identifier/attribute/arg name containing 'shard' in the
        subtree (NamedSharding, with_sharding_constraint, batch_sharding,
        even_sharding, pshard, out_shardings=...)."""
        if tree is None:
            return False
        for sub in ast.walk(tree):
            if isinstance(sub, ast.Name) and cls._SHARDY.search(sub.id):
                return True
            if isinstance(sub, ast.Attribute) \
                    and cls._SHARDY.search(sub.attr):
                return True
            if isinstance(sub, ast.keyword) and sub.arg \
                    and cls._SHARDY.search(sub.arg):
                return True
            if isinstance(sub, ast.arg) and cls._SHARDY.search(sub.arg):
                return True
        return False


# ---------------------------------------------------------------------------
# GL016 — sampling-recompile-key
# ---------------------------------------------------------------------------

@register
class SamplingRecompileKeyRule(Rule):
    """Sampling params as jit static args or executable-cache-key parts."""

    id = "GL016"
    name = "sampling-recompile-key"
    rationale = (
        "Decode serves ONE step executable for every request mix; sampling "
        "params (temperature / top_k / top_p / seed) ride as batch-shaped "
        "array operands of that executable (decode/sampling.py). The "
        "moment one of them becomes a `jax.jit` static argument or a "
        "component of an executable-cache key, every novel value triggers "
        "a fresh trace+compile in the serving hot path — seconds of XLA "
        "per REQUEST, an unbounded executable cache, and a latency cliff "
        "that only shows under parameter-diverse traffic (the single-user "
        "smoke test never sees it). In serving/ and decode/, sampling "
        "params must never be static args or cache-key components.")

    #: the modules whose executables serve per-request traffic
    HOT_PREFIXES = ("deeplearning4j_tpu/serving/",
                    "deeplearning4j_tpu/decode/")
    #: identifier shapes of per-request sampling knobs; matched on whole
    #: underscore-separated words so `seed_bucket` hits but `reseed` and
    #: `processed` don't
    _SAMPLING = re.compile(
        r"(^|_)(temperature|temp|top_k|topk|top_p|topp|seed|sampler|"
        r"sampling)($|_)", re.IGNORECASE)
    _JIT = ("jax.jit", "jax.pjit")
    #: dict methods whose first argument is a lookup key
    _KEYED = ("get", "setdefault", "pop")

    def check(self, ctx):
        if not ctx.rel_path.startswith(self.HOT_PREFIXES):
            return
        aliases = ctx.aliases
        for node in ctx.nodes:
            if isinstance(node, ast.Call):
                if self._is_jit(node, aliases):
                    yield from self._check_jit(ctx, node, aliases)
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr in self._KEYED and node.args:
                    hit = self._sampling_key(node.args[0])
                    if hit:
                        yield self.violation(
                            ctx, node, self._key_msg(hit, node.func.attr))
            elif isinstance(node, ast.Subscript):
                hit = self._sampling_key(node.slice)
                if hit:
                    yield self.violation(
                        ctx, node, self._key_msg(hit, "subscript"))

    # -- jit static args -----------------------------------------------------
    @classmethod
    def _is_jit(cls, node, aliases):
        """jax.jit(...) directly, or functools.partial(jax.jit, ...) as the
        decorator spelling."""
        qual = call_qual(node, aliases)
        if qual in cls._JIT:
            return True
        return (qual == "functools.partial" and node.args
                and qualname(node.args[0], aliases) in cls._JIT)

    def _check_jit(self, ctx, node, aliases):
        nums = []
        for kw in node.keywords:
            if kw.arg == "static_argnames":
                for name in self._str_consts(kw.value):
                    if self._SAMPLING.search(name):
                        yield self.violation(
                            ctx, node,
                            f"static_argnames={name!r}: a sampling param as "
                            "a jit static arg retraces the decode "
                            "executable for every novel value — pass it as "
                            "a batch-shaped array operand "
                            "(sampling.batch_operands) instead")
            elif kw.arg == "static_argnums":
                nums = self._int_consts(kw.value)
        if nums:
            params = self._callee_params(ctx, node, aliases)
            for i in nums:
                if params and -len(params) <= i < len(params) \
                        and self._SAMPLING.search(params[i]):
                    yield self.violation(
                        ctx, node,
                        f"static_argnums includes `{params[i]}`: a sampling "
                        "param as a jit static arg retraces the decode "
                        "executable for every novel value — pass it as a "
                        "batch-shaped array operand "
                        "(sampling.batch_operands) instead")

    @staticmethod
    def _str_consts(node):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return [e.value for e in node.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, str)]
        return []

    @staticmethod
    def _int_consts(node):
        if isinstance(node, ast.Constant) and isinstance(node.value, int):
            return [node.value]
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return [e.value for e in node.elts
                    if isinstance(e, ast.Constant)
                    and isinstance(e.value, int)]
        return []

    @classmethod
    def _callee_params(cls, ctx, node, aliases):
        """Positional param names of the function being jitted, where a
        shallow look can resolve them: an inline lambda, a module-level def
        named by the first argument, or — for the decorator spelling — the
        decorated function itself."""
        callee = None
        for arg in node.args:
            if qualname(arg, aliases) in cls._JIT:
                continue                    # partial(jax.jit, ...)'s target
            callee = arg
            break
        if isinstance(callee, ast.Lambda):
            return [a.arg for a in callee.args.args]
        if isinstance(callee, ast.Name):
            for n in ctx.nodes:
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                        and n.name == callee.id:
                    return [a.arg for a in n.args.args]
            return None
        fn = enclosing_function(ctx, node)
        if fn is not None and any(
                node is d or any(node is w for w in ast.walk(d))
                for d in fn.decorator_list):
            return [a.arg for a in fn.args.args]
        return None

    # -- cache keys ----------------------------------------------------------
    @classmethod
    def _sampling_key(cls, expr):
        """A sampling value used AS a lookup key: the bare Name/Attribute
        itself (`fns[cfg.seed]`), or anywhere inside a composite
        Tuple/f-string key (`fns[(L, temperature)]`, `fns[f"s:{seed}"]`).
        Two shapes deliberately stay quiet: string CONSTANTS
        (`operands["temperature"]` is the legitimate operand-dict read —
        the field NAME is fixed, the values live in the array), and
        arithmetic index expressions (`sorted_p[top_k - 1]` is array math
        on a filtered distribution, not an executable-cache key)."""
        if isinstance(expr, (ast.Name, ast.Attribute)):
            return cls._ident_match(expr)
        if isinstance(expr, (ast.Tuple, ast.JoinedStr)):
            for sub in ast.walk(expr):
                hit = cls._ident_match(sub)
                if hit:
                    return hit
        return None

    @classmethod
    def _ident_match(cls, node):
        if isinstance(node, ast.Name) and cls._SAMPLING.search(node.id):
            return node.id
        if isinstance(node, ast.Attribute) \
                and cls._SAMPLING.search(node.attr):
            return node.attr
        return None

    @staticmethod
    def _key_msg(ident, via):
        return (f"sampling param `{ident}` flows into a lookup key "
                f"({via}): keyed executables/caches grow one entry per "
                "novel value and each miss is a fresh trace+compile in "
                "the decode hot path — key by SHAPE (bucket, window, "
                "slot count) and pass sampling values as array operands")


# ---------------------------------------------------------------------------
# GL017 — untracked-jit-cache
# ---------------------------------------------------------------------------

@register
class UntrackedJitCacheRule(Rule):
    """jax.jit result stored into an executable cache without telemetry."""

    id = "GL017"
    name = "untracked-jit-cache"
    rationale = (
        "Every executable the hot modules cache (`self._jit_cache[key]`, "
        "decode step tables, bucket dicts) is supposed to funnel through "
        "the compile-telemetry seam — `timed_first_call` / `CompileTracker` "
        "— which is also where the live cost plane (telemetry/cost.py) "
        "captures XLA's flops/bytes for `/profile/cost`. A bare "
        "`cache[key] = jax.jit(fn)` compiles and dispatches INVISIBLY: no "
        "jit_compiles_total counter, no compile-time gauge, no cost row — "
        "ISSUE 19's whole failure mode of 'which executable is eating the "
        "bandwidth' with one row missing. In serving/, decode/, and nn/, "
        "wrap the jitted callable in timed_first_call(..., label) (or route "
        "it through CompileTracker/the cost registry) before caching it.")

    #: the modules whose cached executables must show up in cost telemetry
    HOT_PREFIXES = ("deeplearning4j_tpu/serving/",
                    "deeplearning4j_tpu/decode/",
                    "deeplearning4j_tpu/nn/")
    _JIT = ("jax.jit", "jax.pjit")
    #: wrapper callables that route the compile through the telemetry plane;
    #: matched on the resolved qualname's last component so both
    #: `timed_first_call(...)` and `xla.timed_first_call(...)` count
    _TRACKED = frozenset({"timed_first_call", "capture", "capture_compiled"})
    #: dict methods that store their second argument under a key
    _STORES = ("setdefault",)

    def check(self, ctx):
        if not ctx.rel_path.startswith(self.HOT_PREFIXES):
            return
        aliases = ctx.aliases
        for node in ctx.nodes:
            if not (isinstance(node, ast.Call)
                    and call_qual(node, aliases) in self._JIT):
                continue
            store = self._cache_store(ctx, node, aliases)
            if store is not None:
                yield self.violation(
                    ctx, store,
                    "jax.jit result stored into an executable cache without "
                    "compile telemetry: wrap it in timed_first_call(jit_fn, "
                    "\"<label>\") so jit_compiles_total / compile seconds / "
                    "the /profile/cost row exist for this executable")

    def _cache_store(self, ctx, jit_call, aliases):
        """The store statement if this jit call's value lands directly in a
        subscript assignment or dict.setdefault WITHOUT passing through a
        tracked wrapper on the way; None otherwise (returns, local names,
        and anything opaque stay quiet — shallow and sound-enough)."""
        child = jit_call
        for anc in ctx.ancestors(jit_call):
            if isinstance(anc, ast.Call):
                fn = anc.func
                last = fn.attr if isinstance(fn, ast.Attribute) else (
                    fn.id if isinstance(fn, ast.Name) else None)
                qual = qualname(fn, aliases)
                if qual is not None:
                    last = qual.rsplit(".", 1)[-1]
                if last in self._TRACKED:
                    return None               # routed through telemetry
                if last in self._STORES and len(anc.args) >= 2 \
                        and child is anc.args[1]:
                    return anc                # d.setdefault(key, jax.jit(...))
            elif isinstance(anc, ast.Assign):
                if child is anc.value and any(
                        isinstance(t, ast.Subscript) for t in anc.targets):
                    return anc                # cache[key] = jax.jit(...)
                return None
            elif isinstance(anc, (ast.FunctionDef, ast.AsyncFunctionDef,
                                  ast.Lambda, ast.Return, ast.Module)):
                return None
            child = anc
        return None
