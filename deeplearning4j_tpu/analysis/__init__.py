"""graftlint — AST-based static analysis enforcing this codebase's invariants.

The last several PRs fixed the *same classes* of bug by hand: non-finite
floats leaking through raw ``json.dumps`` into HTTP responses, clocks read
outside ``util/time_source`` (so ManualClock tests can't drive them), and
lock-guarded state touched off-lock. Production stacks stop re-fixing bug
classes by encoding them as machine-checked invariants — the same
lint-as-a-test-gate discipline JAX itself and large TF codebases use for
trace/host-sync hazards. This package is that checker.

Pieces:
  core.py         Rule SPI, registry, suppression comments, Analyzer (with
                  the begin_program hook for whole-program rules)
  rules.py        per-file rules (see RULES.md for the catalog + rationale)
  concurrency.py  whole-program lockset inference + lock-order graph:
                  GL003 (annotation channel), GL018–GL020
  baseline.py     committed-baseline support (pre-existing violations don't
                  block; NEW ones fail)
  cli.py          `python -m deeplearning4j_tpu.analysis` / tools/lint.py

Run:   python tools/lint.py [paths...] [--format=json|text]
Gate:  tests/test_static_analysis.py runs the whole pass in tier-1.
"""
from .baseline import Baseline
from .core import Analyzer, FileContext, Report, Rule, Violation, all_rules, \
    get_rule, register
from . import rules  # noqa: F401  (import for the registration side effect)
from . import concurrency  # noqa: F401  (GL003/GL018–GL020 registration)

__all__ = [
    "Analyzer", "Baseline", "FileContext", "Report", "Rule", "Violation",
    "all_rules", "get_rule", "register",
]
