"""Committed-baseline support: pre-existing violations don't block the gate,
NEW ones fail.

The baseline is a JSON file of annotated entries. Matching is by
(rule, path, stripped-source-line) — NOT by line number — so edits elsewhere
in a file never invalidate the baseline; identical lines are matched as a
multiset (N entries absorb at most N findings). ``--baseline-update``
rewrites the file from the current findings, preserving the human-written
``note`` on every entry that still matches.

Every entry SHOULD carry a note saying why the violation is tolerated; the
repo's committed baseline (tools/lint_baseline.json) is kept note-complete
and the test gate asserts it stays that way.
"""
from __future__ import annotations

import collections
import json
import os

VERSION = 1


class Baseline:
    def __init__(self, entries=None):
        # entry: {"rule", "path", "line", "code", "note"}
        self.entries = list(entries or [])

    # -- persistence ---------------------------------------------------------
    @classmethod
    def load(cls, path):
        """Load from `path`; a missing file is an empty baseline (so a fresh
        checkout of a clean repo needs no baseline at all)."""
        if not os.path.exists(path):
            return cls()
        with open(path, encoding="utf-8") as f:
            data = json.load(f)
        if data.get("version") != VERSION:
            raise ValueError(
                f"unsupported baseline version {data.get('version')!r} "
                f"in {path} (expected {VERSION})")
        return cls(data.get("entries", []))

    def save(self, path):
        # util.fs is stdlib-only, so the jax-free graftlint entry can still
        # import this module; the durable write keeps a crash mid
        # --baseline-update from torching the committed baseline
        from ..util.fs import atomic_write
        data = {"version": VERSION, "entries": self.entries}
        atomic_write(path, json.dumps(data, indent=1, sort_keys=True) + "\n")

    # -- matching ------------------------------------------------------------
    @staticmethod
    def _key(entry):
        return (entry["rule"], entry["path"], entry["code"])

    def split(self, violations):
        """Partition `violations` into (new, baselined)."""
        budget = collections.Counter(self._key(e) for e in self.entries)
        new, matched = [], []
        for v in violations:
            if budget[v.key] > 0:
                budget[v.key] -= 1
                matched.append(v)
            else:
                new.append(v)
        return new, matched

    def stale_entries(self, violations):
        """Entries no longer matched by any current violation (fixed code
        whose baseline entry should be dropped on the next --baseline-update)."""
        seen = collections.Counter(v.key for v in violations)
        stale = []
        for e in self.entries:
            if seen[self._key(e)] > 0:
                seen[self._key(e)] -= 1
            else:
                stale.append(e)
        return stale

    @classmethod
    def from_violations(cls, violations, previous=None):
        """Build a fresh baseline from current findings, carrying over notes
        from a previous baseline's still-matching entries."""
        notes = collections.defaultdict(list)
        if previous is not None:
            for e in previous.entries:
                if e.get("note"):
                    notes[cls._key(e)].append(e["note"])
        entries = []
        for v in sorted(violations, key=lambda v: (v.path, v.line, v.rule)):
            pool = notes.get(v.key)
            entries.append({
                "rule": v.rule, "path": v.path, "line": v.line,
                "code": v.code, "note": pool.pop(0) if pool else "",
            })
        return cls(entries)
