"""graftlint core: Rule SPI, registry, suppression comments, Analyzer.

Design notes
------------
* Rules are pure functions of one parsed file (``FileContext``): source text,
  AST, comment map, and light import resolution. Rules that need *program*
  context (the concurrency pass) override ``Rule.begin_program``, which runs
  once per analysis with every FileContext and a shared cache before any
  per-file ``check`` — so whole-program indexes are built exactly once and
  violations still report (and suppress, and baseline) per file.
* Suppression is comment-driven, pylint-style but with a project-specific
  marker so it can never collide with other linters:
      x = time.time()          # graftlint: disable=GL001  <why it's OK>
      # graftlint: disable=GL003           (alone on a line: applies to the
      #                                      NEXT line — for long statements)
      # graftlint: disable-file=GL004      (anywhere: whole file)
  A bare ``disable`` with no ``=RULES`` silences every rule for that line.
* Pre-existing violations live in a committed baseline (baseline.py) so the
  gate only fails on NEW findings; suppressions are for violations a human has
  judged acceptable *forever* (and must carry a rationale in the comment).
"""
from __future__ import annotations

import ast
import dataclasses
import io
import os
import re
import tokenize

_SUPPRESS_RE = re.compile(
    r"graftlint:\s*(?P<kind>disable-file|disable)"
    r"(?:\s*=\s*(?P<rules>[A-Za-z0-9_]+(?:\s*,\s*[A-Za-z0-9_]+)*))?")

_ALL = object()  # sentinel: "every rule" in a suppression set (NOT None —
                 # dict.get misses must stay distinguishable from it)


@dataclasses.dataclass
class Violation:
    """One finding: rule id, location, message, and the stripped source line
    (`code`) that serves as the line-drift-tolerant baseline fingerprint."""

    rule: str
    path: str       # posix path relative to the analysis root
    line: int
    col: int
    message: str
    code: str

    @property
    def key(self):
        """Baseline identity: stable across unrelated edits above the line."""
        return (self.rule, self.path, self.code)

    def to_dict(self):
        return dataclasses.asdict(self)

    def __str__(self):
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}"


def import_aliases(tree_or_ctx):
    """name-in-scope -> dotted origin ("np" -> "numpy", "jit" -> "jax.jit",
    "Thread" -> "threading.Thread"). Relative imports keep their dots."""
    aliases = {}
    nodes = tree_or_ctx.nodes if isinstance(tree_or_ctx, FileContext) \
        else ast.walk(tree_or_ctx)
    for node in nodes:
        if isinstance(node, ast.Import):
            for a in node.names:
                aliases[a.asname or a.name.split(".")[0]] = \
                    a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom):
            mod = ("." * node.level) + (node.module or "")
            for a in node.names:
                if a.name == "*":
                    continue
                aliases[a.asname or a.name] = f"{mod}.{a.name}" if mod else a.name
    return aliases


class FileContext:
    """Everything a rule may look at for one file."""

    def __init__(self, source, rel_path, filename=None):
        self.source = source
        self.rel_path = rel_path.replace(os.sep, "/")
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=filename or rel_path)
        self._parents = None
        self._nodes = None
        self._aliases = None
        self._line_disables = {}   # lineno -> set of rule ids, or _ALL
        self._file_disables = set()
        self._file_disables_all = False
        self._scan_comments()

    # -- comments ------------------------------------------------------------
    def _scan_comments(self):
        """Collect suppression comments via tokenize (never fooled by a
        'graftlint:' inside a string literal); falls back to a line scan on
        tokenizer errors so a weird-but-parseable file still lints."""
        if "graftlint" not in self.source:
            return      # fast path: no marker anywhere, skip tokenizing
        comments = []
        try:
            for tok in tokenize.generate_tokens(io.StringIO(self.source).readline):
                if tok.type == tokenize.COMMENT:
                    comments.append((tok.start[0], tok.start[1], tok.string))
        except (tokenize.TokenError, IndentationError):
            for i, text in enumerate(self.lines, 1):
                if "#" in text:
                    col = text.index("#")
                    comments.append((i, col, text[col:]))
        for lineno, col, text in comments:
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = m.group("rules")
            rule_set = (_ALL if rules is None
                        else {r.strip() for r in rules.split(",")})
            if m.group("kind") == "disable-file":
                if rule_set is _ALL:
                    self._file_disables_all = True
                else:
                    self._file_disables |= rule_set
            else:
                # a comment alone on its line suppresses the NEXT line
                target = lineno
                if self.lines[lineno - 1][:col].strip() == "":
                    target = lineno + 1
                prev = self._line_disables.get(target)
                if prev is _ALL or rule_set is _ALL:
                    self._line_disables[target] = _ALL
                else:
                    self._line_disables[target] = (prev or set()) | rule_set

    def suppressed(self, rule_id, line) -> bool:
        if self._file_disables_all or rule_id in self._file_disables:
            return True
        rules = self._line_disables.get(line)
        return rules is _ALL or (rules is not None and rule_id in rules)

    # -- helpers for rules ---------------------------------------------------
    def line_text(self, lineno) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1]
        return ""

    @property
    def nodes(self):
        """Flat list of every AST node, cached — six rules over 150+ files
        must not each re-walk the whole tree."""
        if self._nodes is None:
            self._nodes = list(ast.walk(self.tree))
        return self._nodes

    @property
    def aliases(self):
        """Cached import_aliases(self.tree)."""
        if self._aliases is None:
            self._aliases = import_aliases(self)
        return self._aliases

    @property
    def parents(self):
        """node -> parent map over the whole tree (built once on demand)."""
        if self._parents is None:
            self._parents = {}
            for parent in self.nodes:
                for child in ast.iter_child_nodes(parent):
                    self._parents[child] = parent
        return self._parents

    def ancestors(self, node):
        """Yield node's ancestors, innermost first."""
        p = self.parents.get(node)
        while p is not None:
            yield p
            p = self.parents.get(p)


class Rule:
    """SPI: subclass, set `id`/`name`/`rationale`, implement `check`, and
    decorate with @register. `check` yields/returns Violations; suppression
    and baseline filtering happen in the Analyzer, not in rules."""

    id = "GL000"
    name = "abstract-rule"
    rationale = ""

    def begin_program(self, contexts, cache):
        """Called once per analysis run, before any check(), with EVERY
        FileContext that will be checked plus a cache dict shared by all
        rules in the run (so e.g. the concurrency model is built once even
        though three rules consume it). Default: no program state."""

    def check(self, ctx: FileContext):
        raise NotImplementedError

    def violation(self, ctx, node, message) -> Violation:
        line = getattr(node, "lineno", 0) if not isinstance(node, int) else node
        col = getattr(node, "col_offset", 0) if not isinstance(node, int) else 0
        return Violation(rule=self.id, path=ctx.rel_path, line=line, col=col,
                         message=message, code=ctx.line_text(line).strip())


_REGISTRY: dict[str, type] = {}


def register(cls):
    """Class decorator adding a Rule subclass to the global registry."""
    if cls.id in _REGISTRY and _REGISTRY[cls.id] is not cls:
        raise ValueError(f"duplicate rule id {cls.id}")
    _REGISTRY[cls.id] = cls
    return cls


def all_rules():
    """Fresh instances of every registered rule, ordered by id."""
    return [_REGISTRY[rid]() for rid in sorted(_REGISTRY)]


def get_rule(rule_id) -> Rule:
    return _REGISTRY[rule_id]()


@dataclasses.dataclass
class Report:
    violations: list      # suppression-filtered, sorted
    errors: list          # unparseable files / missing paths
    files_checked: int
    rel_files: list = dataclasses.field(default_factory=list)
    # ^ root-relative paths analyzed — a scoped --baseline-update uses this
    # to know which baseline entries were re-derived vs out of scope


_SKIP_DIRS = {"__pycache__", ".git", ".hg", "build", "dist", ".eggs",
              "node_modules"}


class Analyzer:
    """Runs a rule set over files/trees of Python sources."""

    def __init__(self, rules=None, root=None):
        self.rules = list(rules) if rules is not None else all_rules()
        self.root = os.path.abspath(root or os.getcwd())

    def analyze_source(self, source, rel_path):
        """Lint one in-memory source string; returns (violations, error).
        Program rules see a one-file program (their cross-file edges simply
        don't exist), so seeded single-source tests still exercise them."""
        try:
            ctx = FileContext(source, rel_path)
        except (SyntaxError, ValueError) as e:
            return [], f"{rel_path}: {type(e).__name__}: {e}"
        return self._check_contexts([ctx]), None

    def _check_contexts(self, ctxs):
        """One analysis run: program hooks once over every context, then the
        per-file checks, suppression-filtered and sorted."""
        cache = {}
        for rule in self.rules:
            rule.begin_program(ctxs, cache)
        out = []
        for ctx in ctxs:
            for rule in self.rules:
                for v in rule.check(ctx):
                    if not ctx.suppressed(v.rule, v.line):
                        out.append(v)
        out.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
        return out

    def analyze_file(self, path):
        rel = os.path.relpath(os.path.abspath(path), self.root)
        try:
            with open(path, encoding="utf-8") as f:
                source = f.read()
        except (OSError, UnicodeDecodeError) as e:
            return [], f"{rel}: {type(e).__name__}: {e}"
        return self.analyze_source(source, rel)

    def iter_python_files(self, paths):
        for p in paths:
            p = os.path.join(self.root, p) if not os.path.isabs(p) else p
            if os.path.isfile(p):
                yield p
            else:
                for dirpath, dirnames, filenames in os.walk(p):
                    dirnames[:] = sorted(d for d in dirnames
                                         if d not in _SKIP_DIRS)
                    for fn in sorted(filenames):
                        if fn.endswith(".py"):
                            yield os.path.join(dirpath, fn)

    def analyze_paths(self, paths) -> Report:
        errors, n = [], 0
        for p in paths:
            full = p if os.path.isabs(p) else os.path.join(self.root, p)
            if not os.path.exists(full):
                # a typoed path in CI must fail loudly, not lint 0 files green
                errors.append(f"{p}: path does not exist")
        # parse EVERY file first: program rules (lock-order, cross-class
        # locksets) need the whole file set before any per-file check runs
        rel_files, ctxs = [], []
        for path in self.iter_python_files(paths):
            n += 1
            rel = (os.path.relpath(os.path.abspath(path), self.root)
                   .replace(os.sep, "/"))
            rel_files.append(rel)
            try:
                with open(path, encoding="utf-8") as f:
                    source = f.read()
                ctxs.append(FileContext(source, rel))
            except (OSError, UnicodeDecodeError, SyntaxError, ValueError) as e:
                errors.append(f"{rel}: {type(e).__name__}: {e}")
        violations = self._check_contexts(ctxs)
        return Report(violations=violations, errors=errors, files_checked=n,
                      rel_files=rel_files)
