"""`python -m deeplearning4j_tpu.analysis` — the graftlint entry point."""
import sys

from .cli import main

sys.exit(main())
