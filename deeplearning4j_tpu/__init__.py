"""deeplearning4j-tpu: a TPU-native deep learning framework with the
capabilities of deeplearning4j (reference: puchka/deeplearning4j v0.7.3),
re-designed on JAX/XLA — whole-step compilation, SPMD sharding over device
meshes, lax control flow for recurrence, NHWC/MXU-friendly layouts.
"""
from .nn.conf.configuration import (NeuralNetConfiguration, MultiLayerConfiguration,
                                    BackpropType, OptimizationAlgorithm)
from .nn.conf.inputs import InputType
from .nn.conf import layers
from .nn.conf.layers import (DenseLayer, OutputLayer, RnnOutputLayer, LossLayer,
                             CenterLossOutputLayer, EmbeddingLayer, ConvolutionLayer,
                             SubsamplingLayer, BatchNormalization,
                             LocalResponseNormalization, GravesLSTM, LSTM,
                             GravesBidirectionalLSTM, ActivationLayer, DropoutLayer,
                             GlobalPoolingLayer, ZeroPaddingLayer, AutoEncoder, RBM,
                             VariationalAutoencoder, SelfAttentionLayer,
                             LayerNormalization, MixtureOfExpertsLayer)
from .nn.updaters import (Sgd, Adam, AdaMax, AdaDelta, AdaGrad, RmsProp, Nesterovs,
                          NoOp, GradientNormalization)
from .nn.weights import WeightInit
from .nn.multilayer.network import MultiLayerNetwork
from .nn.graph.graph import ComputationGraph
from .nn.conf.graph_configuration import (ComputationGraphConfiguration,
                                          ElementWiseVertex, MergeVertex,
                                          SubsetVertex, StackVertex, UnstackVertex,
                                          ScaleVertex, L2NormalizeVertex, L2Vertex,
                                          PreprocessorVertex, LastTimeStepVertex,
                                          DuplicateToTimeSeriesVertex)
from .util.model_serializer import ModelSerializer, ModelGuesser
from .datasets.dataset import DataSet, MultiDataSet
from .datasets.iterator.base import (DataSetIterator, ListDataSetIterator,
                                     INDArrayDataSetIterator, AsyncDataSetIterator,
                                     MultipleEpochsIterator, ExistingDataSetIterator,
                                     DevicePrefetchIterator)
from .etl import (Schema, TransformProcess, DataNormalizer,
                  NormalizerStandardize, NormalizerMinMaxScaler,
                  ParallelPipelineExecutor, DevicePrefetcher)
from .eval.evaluation import Evaluation
from .eval.roc import ROC, ROCMultiClass, RegressionEvaluation
from .optimize.listeners import (ScoreIterationListener, PerformanceListener,
                                 CollectScoresIterationListener)
from .telemetry import (MetricsRegistry, Tracer, TelemetryListener,
                        enable_tracing, get_registry, get_tracer)

__version__ = "0.1.0"
