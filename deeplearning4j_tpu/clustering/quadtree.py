"""QuadTree: 2-D spatial subdivision with center-of-mass aggregation.

Reference: deeplearning4j-core/.../clustering/quadtree/QuadTree.java (+
Cell.java) — the 2-D special case behind Barnes-Hut t-SNE; the general-D
sibling is clustering/sptree.py. Kept as its own class for reference parity:
boundary Cell, northWest/../southEast children, insert with duplicate
collapsing, subdivide, and the Barnes-Hut force accumulation entry
(computeNonEdgeForces with the theta criterion).
"""
from __future__ import annotations

import numpy as np


class Cell:
    """Axis-aligned square cell (reference: quadtree/Cell.java)."""

    def __init__(self, x, y, hw, hh):
        self.x, self.y, self.hw, self.hh = float(x), float(y), float(hw), float(hh)

    def contains(self, px, py):
        return (self.x - self.hw <= px <= self.x + self.hw
                and self.y - self.hh <= py <= self.y + self.hh)


class QuadTree:
    QT_NODE_CAPACITY = 1  # one point per leaf, like the reference

    def __init__(self, data=None, cell=None):
        self.cell = cell
        self.center_of_mass = np.zeros(2)
        self.cum_size = 0
        self.size = 0
        self.point = None
        self.north_west = self.north_east = None
        self.south_west = self.south_east = None
        if data is not None:
            data = np.asarray(data, np.float64)
            if self.cell is None:
                mins, maxs = data.min(0), data.max(0)
                c = (mins + maxs) / 2
                half = (maxs - mins) / 2 + 1e-5
                self.cell = Cell(c[0], c[1], half[0], half[1])
            for p in data:
                self.insert(p)

    def is_leaf(self):
        return self.north_west is None

    def subdivide(self):
        c = self.cell
        hw, hh = c.hw / 2, c.hh / 2
        self.north_west = QuadTree(cell=Cell(c.x - hw, c.y + hh, hw, hh))
        self.north_east = QuadTree(cell=Cell(c.x + hw, c.y + hh, hw, hh))
        self.south_west = QuadTree(cell=Cell(c.x - hw, c.y - hh, hw, hh))
        self.south_east = QuadTree(cell=Cell(c.x + hw, c.y - hh, hw, hh))

    def _children(self):
        return (self.north_west, self.north_east, self.south_west,
                self.south_east)

    def insert(self, p):
        p = np.asarray(p, np.float64)
        if not self.cell.contains(p[0], p[1]):
            return False
        self.cum_size += 1
        self.center_of_mass += (p - self.center_of_mass) / self.cum_size
        if self.is_leaf():
            if self.point is None:
                self.point = p.copy()
                self.size = 1
                return True
            if np.allclose(self.point, p):  # duplicate point collapses
                self.size += 1
                return True
            self.subdivide()
            old, self.point, self.size = self.point, None, 0
            for ch in self._children():
                if ch.insert(old):
                    break
        for ch in self._children():
            if ch.insert(p):
                return True
        return False  # numerically on a boundary sliver; counted in mass

    def depth(self):
        if self.is_leaf():
            return 1
        return 1 + max(ch.depth() for ch in self._children()
                       if ch.cum_size > 0)

    def compute_non_edge_forces(self, point, theta=0.5):
        """Barnes-Hut negative-force accumulation for one point: returns
        (neg_force[2], sum_q) using the theta * (cell_size / dist) criterion
        (reference: QuadTree.computeNonEdgeForces)."""
        point = np.asarray(point, np.float64)
        neg = np.zeros(2)
        sum_q = 0.0
        stack = [self]
        while stack:
            node = stack.pop()
            if node.cum_size == 0:
                continue
            diff = point - node.center_of_mass
            dist2 = float(diff @ diff)
            max_width = max(node.cell.hw, node.cell.hh) * 2
            if node.is_leaf() or max_width * max_width < theta * theta * dist2:
                if node.is_leaf() and node.point is not None and \
                        np.allclose(node.point, point):
                    n_dup = node.size - 1  # exclude the query point itself
                    if n_dup <= 0:
                        continue
                    mult = n_dup
                else:
                    mult = node.cum_size
                q = 1.0 / (1.0 + dist2)
                sum_q += mult * q
                neg += mult * q * q * diff
            else:
                stack.extend(ch for ch in node._children())
        return neg, sum_q
