"""Space-partitioning tree (generalized quadtree/octree) for Barnes-Hut.

Reference: clustering/sptree/SpTree.java (+ quadtree/ 2-D special case) —
cell subdivision with center-of-mass aggregation, used by BarnesHutTsne for
O(N log N) repulsive-force estimation.
"""
from __future__ import annotations

import numpy as np


class SpTree:
    def __init__(self, data, corner=None, width=None):
        data = np.asarray(data, np.float64)
        self.dim = data.shape[1]
        if corner is None:
            mins = data.min(0)
            maxs = data.max(0)
            center = (mins + maxs) / 2
            width = (maxs - mins).max() * 0.5 + 1e-5
            corner = center - width
            width = np.full(self.dim, 2 * width)
        self.corner = np.asarray(corner, np.float64)
        self.width = np.asarray(width, np.float64)
        self.center_of_mass = np.zeros(self.dim)
        self.cum_size = 0
        self.children = None
        self.point = None
        self.point_idx = -1
        for i, p in enumerate(data):
            self.insert(p, i)

    @classmethod
    def _empty(cls, corner, width):
        node = cls.__new__(cls)
        node.dim = len(corner)
        node.corner = corner
        node.width = width
        node.center_of_mass = np.zeros(node.dim)
        node.cum_size = 0
        node.children = None
        node.point = None
        node.point_idx = -1
        return node

    def _contains(self, p):
        return np.all(p >= self.corner) and np.all(p <= self.corner + self.width)

    def insert(self, p, idx):
        if not self._contains(p):
            return False
        self.cum_size += 1
        self.center_of_mass += (p - self.center_of_mass) / self.cum_size
        if self.children is None and self.point is None:
            self.point = np.array(p)
            self.point_idx = idx
            return True
        if self.children is None:
            if np.allclose(self.point, p):
                return True  # duplicate point: mass already counted
            self._subdivide()
        for c in self.children:
            if c.insert(p, idx):
                return True
        return False

    def _subdivide(self):
        half = self.width / 2
        self.children = []
        for mask in range(2 ** self.dim):
            offs = np.array([(mask >> d) & 1 for d in range(self.dim)])
            corner = self.corner + offs * half
            self.children.append(SpTree._empty(corner, half))
        p, i = self.point, self.point_idx
        self.point = None
        self.point_idx = -1
        for c in self.children:
            if c.insert(p, i):
                break

    def compute_non_edge_forces(self, point, theta, neg_f):
        """Barnes-Hut negative-force accumulation for one query point
        (reference: SpTree.computeNonEdgeForces). Returns the accumulated
        normalization sum; neg_f is mutated in place."""
        if self.cum_size == 0:
            return 0.0
        diff = point - self.center_of_mass
        d2 = float(diff @ diff)
        max_width = float(self.width.max())
        if self.children is None or (d2 > 0 and max_width ** 2 / d2 < theta ** 2):
            if self.point is not None and np.allclose(self.point, point):
                return 0.0
            q = 1.0 / (1.0 + d2)
            mult = self.cum_size * q
            s = mult
            neg_f += mult * q * diff
            return s
        s = 0.0
        for c in self.children:
            s += c.compute_non_edge_forces(point, theta, neg_f)
        return s
