"""Clustering + spatial trees (reference: deeplearning4j-core clustering/ —
kmeans/KMeansClustering.java, kdtree/, vptree/, quadtree/, sptree/SpTree.java,
cluster/ model classes; 33 files, ~4.1k LoC). Supports t-SNE and
nearest-neighbor workloads.
"""
from .kmeans import KMeansClustering, Cluster, ClusterSet, Point
from .kdtree import KDTree
from .vptree import VPTree
from .sptree import SpTree

__all__ = ["KMeansClustering", "Cluster", "ClusterSet", "Point",
           "KDTree", "VPTree", "SpTree"]
