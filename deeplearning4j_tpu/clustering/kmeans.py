"""K-means clustering.

Reference: clustering/kmeans/KMeansClustering.java + clustering/cluster/
(Point, Cluster, ClusterSet, ClusterUtils — iteration strategy with max
iterations / distance-variation convergence).

TPU-first: the assignment+update inner loop is one jitted XLA computation
(pairwise distances on the MXU, segment-sum centroid update) instead of the
reference's per-point Java loops.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp


class Point:
    """(reference: clustering/cluster/Point.java)"""

    def __init__(self, array, point_id=None, label=None):
        self.array = np.asarray(array, np.float32)
        self.id = point_id
        self.label = label


class Cluster:
    def __init__(self, center, cluster_id):
        self.center = center
        self.id = cluster_id
        self.points = []


class ClusterSet:
    def __init__(self, centers, assignments, points):
        self.centers = np.asarray(centers)
        self.assignments = np.asarray(assignments)
        self.clusters = [Cluster(self.centers[i], i)
                         for i in range(len(self.centers))]
        for p, a in zip(points, assignments):
            self.clusters[int(a)].points.append(p)

    def get_clusters(self):
        return self.clusters

    def nearest_cluster(self, x):
        d = ((self.centers - np.asarray(x)) ** 2).sum(-1)
        return self.clusters[int(d.argmin())]


@functools.partial(jax.jit, static_argnames=("k",))
def _kmeans_step(x, centers, k):
    d = jnp.sum((x[:, None, :] - centers[None]) ** 2, -1)     # N,K
    assign = jnp.argmin(d, -1)
    one_hot = jax.nn.one_hot(assign, k, dtype=x.dtype)        # N,K
    counts = one_hot.sum(0)                                    # K
    sums = one_hot.T @ x                                       # K,D
    new_centers = jnp.where(counts[:, None] > 0,
                            sums / jnp.maximum(counts[:, None], 1.0),
                            centers)
    cost = jnp.sum(jnp.min(d, -1))
    return new_centers, assign, cost


class KMeansClustering:
    """(reference: KMeansClustering.setup(clusterCount, maxIterations,
    distanceFunction) + applyTo(points))"""

    def __init__(self, k, max_iterations=100, tol=1e-4, seed=0):
        self.k = int(k)
        self.max_iterations = int(max_iterations)
        self.tol = tol
        self.seed = seed
        self.centers = None

    @staticmethod
    def setup(cluster_count, max_iterations=100, distance_function="euclidean",
              seed=0):
        return KMeansClustering(cluster_count, max_iterations, seed=seed)

    def apply_to(self, points):
        """points: list[Point] or array [N, D]. Returns ClusterSet."""
        if isinstance(points, (list, tuple)) and points and \
                isinstance(points[0], Point):
            pts = points
            x = np.stack([p.array for p in points])
        else:
            x = np.asarray(points, np.float32)
            pts = [Point(row, point_id=i) for i, row in enumerate(x)]
        rng = np.random.default_rng(self.seed)
        # k-means++ seeding: spread initial centers by D^2 sampling (avoids
        # the split-cluster local optima plain random init falls into)
        first = rng.integers(len(x))
        chosen = [first]
        d2 = ((x - x[first]) ** 2).sum(-1)
        for _ in range(1, self.k):
            total = d2.sum()
            if total > 0:
                nxt = int(rng.choice(len(x), p=d2 / total))
            else:  # all remaining points coincide with a center — pick uniformly
                nxt = int(rng.integers(len(x)))
            chosen.append(nxt)
            d2 = np.minimum(d2, ((x - x[nxt]) ** 2).sum(-1))
        centers = jnp.asarray(x[np.array(chosen)])
        xj = jnp.asarray(x)
        prev_cost = np.inf
        assign = None
        for _ in range(self.max_iterations):
            centers, assign, cost = _kmeans_step(xj, centers, self.k)
            cost = float(cost)
            if abs(prev_cost - cost) < self.tol * max(abs(prev_cost), 1.0):
                break
            prev_cost = cost
        self.centers = np.asarray(centers)
        return ClusterSet(self.centers, np.asarray(assign), pts)

    fit = apply_to
