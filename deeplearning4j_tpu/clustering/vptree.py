"""Vantage-point tree for metric nearest-neighbor search.

Reference: clustering/vptree/VPTree.java — the structure Barnes-Hut t-SNE uses
to find the k nearest neighbours under arbitrary metrics
(plot/BarnesHutTsne.java uses it for the input-similarity sparse P matrix).
"""
from __future__ import annotations

import heapq

import numpy as np


class _VPNode:
    __slots__ = ("idx", "threshold", "inside", "outside", "bucket")

    def __init__(self, idx):
        self.idx = idx
        self.threshold = 0.0
        self.inside = None
        self.outside = None
        self.bucket = None   # leaf bucket for degenerate splits


class VPTree:
    def __init__(self, points, distance="euclidean", seed=0):
        self.points = np.asarray(points, np.float64)
        self.distance = distance
        self._rng = np.random.default_rng(seed)
        idxs = list(range(len(self.points)))
        self.root = self._build(idxs)

    def _dist(self, a, b):
        if self.distance == "cosine":
            na, nb = np.linalg.norm(a), np.linalg.norm(b)
            if na == 0 or nb == 0:
                return 1.0
            return 1.0 - float(a @ b / (na * nb))
        return float(np.linalg.norm(a - b))

    def _build(self, idxs):
        if not idxs:
            return None
        vp = idxs[self._rng.integers(0, len(idxs))]
        rest = [i for i in idxs if i != vp]
        node = _VPNode(vp)
        if not rest:
            return node
        dists = [self._dist(self.points[vp], self.points[i]) for i in rest]
        median = float(np.median(dists))
        node.threshold = median
        inside = [i for i, d in zip(rest, dists) if d < median]
        outside = [i for i, d in zip(rest, dists) if d >= median]
        if not inside or not outside:
            # degenerate split (duplicate-heavy data: every distance equals
            # the median) — store the rest as a linearly-scanned leaf bucket
            # instead of recursing O(n) deep
            node.bucket = rest
            return node
        node.inside = self._build(inside)
        node.outside = self._build(outside)
        return node

    def search(self, query, k):
        """k nearest to query: ([indices], [distances]) ascending."""
        query = np.asarray(query, np.float64)
        heap = []  # (-dist, idx) max-heap
        tau = [np.inf]

        def consider(i, d):
            if len(heap) < k:
                heapq.heappush(heap, (-d, i))
                if len(heap) == k:
                    tau[0] = -heap[0][0]
            elif d < tau[0]:
                heapq.heapreplace(heap, (-d, i))
                tau[0] = -heap[0][0]

        def visit(node):
            if node is None:
                return
            d = self._dist(self.points[node.idx], query)
            consider(node.idx, d)
            if node.bucket is not None:
                for i in node.bucket:
                    consider(i, self._dist(self.points[i], query))
                return
            if node.inside is None and node.outside is None:
                return
            if d < node.threshold:
                visit(node.inside)
                if d + tau[0] >= node.threshold:
                    visit(node.outside)
            else:
                visit(node.outside)
                if d - tau[0] <= node.threshold:
                    visit(node.inside)

        visit(self.root)
        pairs = sorted((-hd, i) for hd, i in heap)
        return [i for _, i in pairs], [d for d, _ in pairs]
