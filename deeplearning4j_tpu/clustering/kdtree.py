"""KD-tree for nearest-neighbor queries.

Reference: clustering/kdtree/KDTree.java (+ HyperRect.java) — axis-cycled
binary space partition with insert, nn (nearest neighbour) and knn queries.
Host-side structure (tree build/search is pointer-chasing, not MXU work).
"""
from __future__ import annotations

import heapq

import numpy as np


class _Node:
    __slots__ = ("point", "idx", "left", "right", "axis")

    def __init__(self, point, idx, axis):
        self.point = point
        self.idx = idx
        self.axis = axis
        self.left = None
        self.right = None


class KDTree:
    def __init__(self, dims=None, points=None):
        self.dims = dims
        self.root = None
        self.size = 0
        if points is not None:
            points = np.asarray(points, np.float64)
            self.dims = points.shape[1]
            # balanced bulk build by median split
            idxs = np.arange(len(points))
            self.root = self._build(points, idxs, 0)
            self.size = len(points)

    def _build(self, pts, idxs, depth):
        if len(idxs) == 0:
            return None
        axis = depth % self.dims
        order = idxs[np.argsort(pts[idxs, axis])]
        mid = len(order) // 2
        node = _Node(pts[order[mid]], int(order[mid]), axis)
        node.left = self._build(pts, order[:mid], depth + 1)
        node.right = self._build(pts, order[mid + 1:], depth + 1)
        return node

    def insert(self, point, idx=None):
        point = np.asarray(point, np.float64)
        if self.dims is None:
            self.dims = len(point)
        idx = self.size if idx is None else idx
        node = _Node(point, idx, 0)
        if self.root is None:
            self.root = node
        else:
            cur = self.root
            depth = 0
            while True:
                axis = depth % self.dims
                branch = "left" if point[axis] < cur.point[axis] else "right"
                nxt = getattr(cur, branch)
                if nxt is None:
                    node.axis = (depth + 1) % self.dims
                    setattr(cur, branch, node)
                    break
                cur = nxt
                depth += 1
        self.size += 1
        return idx

    def nn(self, query):
        """Nearest neighbour: returns (distance, point, idx)."""
        res = self.knn(query, 1)
        return res[0] if res else None

    def knn(self, query, k):
        """k nearest: [(distance, point, idx)] ascending."""
        query = np.asarray(query, np.float64)
        heap = []  # max-heap by -dist

        points = {}

        def visit(node, depth):
            if node is None:
                return
            d = float(np.linalg.norm(node.point - query))
            points[node.idx] = node.point
            # tuples compare (dist, idx) only — never the point arrays
            if len(heap) < k:
                heapq.heappush(heap, (-d, node.idx))
            elif d < -heap[0][0]:
                heapq.heapreplace(heap, (-d, node.idx))
            axis = depth % self.dims
            diff = query[axis] - node.point[axis]
            near, far = (node.left, node.right) if diff < 0 else (node.right, node.left)
            visit(near, depth + 1)
            if len(heap) < k or abs(diff) < -heap[0][0]:
                visit(far, depth + 1)

        visit(self.root, 0)
        return [(d, points[i], i) for d, i in sorted((-hd, i) for hd, i in heap)]
