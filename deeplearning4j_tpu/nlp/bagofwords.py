"""Count-based text vectorizers.

Reference: bagofwords/vectorizer/ — BagOfWordsVectorizer (term counts),
TfidfVectorizer (tf-idf weights), both producing DataSets over a vocab.
"""
from __future__ import annotations

import math

import numpy as np

from .vocab import VocabConstructor
from .tokenization import DefaultTokenizerFactory


class BagOfWordsVectorizer:
    def __init__(self, min_word_frequency=1, tokenizer_factory=None,
                 stop_words=None):
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = min_word_frequency
        self.stop_words = stop_words
        self.vocab = None

    def fit(self, texts):
        self.vocab = VocabConstructor(
            self.tokenizer_factory, self.min_word_frequency,
            self.stop_words).build_vocab(list(texts), build_huffman=False)
        return self

    def _weight(self, count, doc_tokens, word):
        return float(count)

    def transform(self, text):
        v = np.zeros(self.vocab.num_words(), np.float32)
        toks = self.tokenizer_factory.create(text).get_tokens()
        for t in toks:
            i = self.vocab.index_of(t)
            if i >= 0:
                v[i] += 1
        return self._post(v, toks)

    def _post(self, v, toks):
        return v

    def fit_transform(self, texts):
        texts = list(texts)
        self.fit(texts)
        return np.stack([self.transform(t) for t in texts])

    def vectorize(self, text, label=None, n_labels=None):
        """Returns a DataSet like the reference's vectorize(String, label)."""
        from ..datasets.dataset import DataSet
        feats = self.transform(text)[None, :]
        if label is None:
            return DataSet(feats, np.zeros((1, 1), np.float32))
        labels = np.zeros((1, n_labels), np.float32)
        labels[0, label] = 1
        return DataSet(feats, labels)


class TfidfVectorizer(BagOfWordsVectorizer):
    """(reference: bagofwords/vectorizer/TfidfVectorizer.java)"""

    def __init__(self, **kw):
        super().__init__(**kw)
        self._idf = None
        self._n_docs = 0

    def fit(self, texts):
        texts = list(texts)
        super().fit(texts)
        self._n_docs = len(texts)
        df = np.zeros(self.vocab.num_words(), np.float64)
        for t in texts:
            seen = {self.vocab.index_of(tok)
                    for tok in self.tokenizer_factory.create(t).get_tokens()}
            for i in seen:
                if i >= 0:
                    df[i] += 1
        self._idf = np.log(self._n_docs / np.maximum(df, 1.0))
        return self

    def _post(self, v, toks):
        n = max(len(toks), 1)
        tf = v / n
        return (tf * self._idf).astype(np.float32)
