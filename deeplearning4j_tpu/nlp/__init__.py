"""NLP stack (reference: deeplearning4j-nlp-parent, 308 files / 45.8k LoC):
embeddings (Word2Vec/ParagraphVectors/GloVe), tokenization, vocab/Huffman,
serialization, count vectorizers, CNN sentence iterator.

See SURVEY.md §2.6. The reference's Hogwild thread parallelism (P7) is
replaced by device-batched XLA scatter-add training (embeddings.py).
"""
from .tokenization import (DefaultTokenizer, NGramTokenizer,
                           DefaultTokenizerFactory, NGramTokenizerFactory,
                           CommonPreprocessor, LowCasePreProcessor,
                           EndingPreProcessor, StopWords)
from .text import (SentenceIterator, CollectionSentenceIterator,
                   BasicLineIterator, LineSentenceIterator, FileSentenceIterator,
                   LabelledDocument, LabelsSource, LabelAwareIterator,
                   SimpleLabelAwareIterator)
from .vocab import VocabWord, VocabCache, VocabConstructor, Huffman
from .embeddings import InMemoryLookupTable, WeightLookupTable
from .sequence_vectors import SequenceVectors, Word2Vec, ParagraphVectors, WordVectors
from .glove import Glove
from .serializer import WordVectorSerializer
from .bagofwords import BagOfWordsVectorizer, TfidfVectorizer
from .cnn_sentence import CnnSentenceDataSetIterator

__all__ = [
    "DefaultTokenizer", "NGramTokenizer", "DefaultTokenizerFactory",
    "NGramTokenizerFactory", "CommonPreprocessor", "LowCasePreProcessor",
    "EndingPreProcessor", "StopWords",
    "SentenceIterator", "CollectionSentenceIterator", "BasicLineIterator",
    "LineSentenceIterator", "FileSentenceIterator", "LabelledDocument",
    "LabelsSource", "LabelAwareIterator", "SimpleLabelAwareIterator",
    "VocabWord", "VocabCache", "VocabConstructor", "Huffman",
    "InMemoryLookupTable", "WeightLookupTable",
    "SequenceVectors", "Word2Vec", "ParagraphVectors", "WordVectors", "Glove",
    "WordVectorSerializer", "BagOfWordsVectorizer", "TfidfVectorizer",
    "CnnSentenceDataSetIterator",
]
