"""Text annotators: sentence segmentation, tokenization, stemming, PoS tags.

Reference: deeplearning4j-nlp-uima/.../text/annotator/{SentenceAnnotator,
TokenizerAnnotator, StemmerAnnotator, PoStagger}.java (3.2k LoC) — thin UIMA
AnalysisEngine wrappers over ClearTK/OpenNLP models. The UIMA machinery is a
host-side pipeline contract, so the redesign keeps the annotator SPI (process
an Annotation document, add typed spans) with self-contained implementations:
rule-based sentence splitting, the TokenizerFactory SPI for tokens, a Porter
stemmer, and a lexicon+suffix PoS tagger (Brill-style baseline) — no external
model downloads (zero-egress environment).
"""
from __future__ import annotations

import re


class Span:
    __slots__ = ("begin", "end", "text", "kind", "attrs")

    def __init__(self, begin, end, text, kind, **attrs):
        self.begin, self.end, self.text, self.kind = begin, end, text, kind
        self.attrs = attrs

    def __repr__(self):
        extra = f" {self.attrs}" if self.attrs else ""
        return f"<{self.kind} [{self.begin}:{self.end}] {self.text!r}{extra}>"


class Annotation:
    """The document being annotated (the CAS analog)."""

    def __init__(self, text):
        self.text = text
        self.spans = []

    def add(self, span):
        self.spans.append(span)
        return span

    def select(self, kind):
        return [s for s in self.spans if s.kind == kind]


class Annotator:
    def process(self, annotation: Annotation) -> Annotation:
        raise NotImplementedError


class AnnotatorPipeline(Annotator):
    """Runs annotators in order (the AnalysisEngine aggregate analog)."""

    def __init__(self, *annotators):
        self.annotators = list(annotators)

    def process(self, annotation):
        if isinstance(annotation, str):
            annotation = Annotation(annotation)
        for a in self.annotators:
            annotation = a.process(annotation)
        return annotation


_ABBREV = {"mr", "mrs", "ms", "dr", "prof", "sr", "jr", "st", "vs", "etc",
           "e.g", "i.e", "fig", "no", "vol", "inc", "ltd", "co", "u.s", "u.k"}


class SentenceAnnotator(Annotator):
    """Sentence segmentation on ./!/? with abbreviation and decimal guards
    (reference: annotator/SentenceAnnotator.java)."""

    _boundary = re.compile(r"[.!?]+[\"')\]]*\s+|[.!?]+[\"')\]]*$")

    def process(self, ann):
        text = ann.text
        start = 0
        for m in self._boundary.finditer(text):
            end = m.end()
            # abbreviation / decimal guard: don't split after "Dr." or "3."
            head = text[start:m.start()].rstrip()
            last = head.split()[-1].lower().rstrip(".") if head.split() else ""
            nxt = text[end:end + 1]
            if last in _ABBREV or (nxt and nxt.islower()):
                continue
            seg = text[start:end].strip()
            if seg:
                ann.add(Span(start, end, seg, "sentence"))
            start = end
        tail = text[start:].strip()
        if tail:
            ann.add(Span(start, len(text), tail, "sentence"))
        return ann


class TokenizerAnnotator(Annotator):
    """Tokenizes each sentence span (whole doc if none) through the
    TokenizerFactory SPI (reference: annotator/TokenizerAnnotator.java)."""

    def __init__(self, factory=None):
        from .tokenization import DefaultTokenizerFactory
        self.factory = factory or DefaultTokenizerFactory()

    _PUNCT = ".,;:!?\"'()[]{}"

    def process(self, ann):
        sentences = ann.select("sentence") or [
            Span(0, len(ann.text), ann.text, "sentence")]
        for s in sentences:
            pos = s.begin
            for tok in self.factory.create(s.text).get_tokens():
                found = ann.text.find(tok, pos, s.end)
                b = found if found >= 0 else pos
                if found >= 0:
                    pos = found + len(tok)
                # surrounding punctuation is not part of the word token
                # (whitespace tokenizers leave "models." attached)
                core = tok.strip(self._PUNCT)
                if not core:
                    ann.add(Span(b, b + len(tok), tok, "token"))
                    continue
                off = tok.index(core)
                ann.add(Span(b + off, b + off + len(core), core, "token"))
        return ann


class StemmerAnnotator(Annotator):
    """Porter-style suffix stripping on token spans (reference:
    annotator/StemmerAnnotator.java wrapping the Snowball stemmer)."""

    _steps = [
        ("sses", "ss"), ("ies", "i"), ("ational", "ate"), ("tional", "tion"),
        ("izer", "ize"), ("fulness", "ful"), ("ousness", "ous"),
        ("iveness", "ive"), ("ments", "ment"), ("ment", "ment"),
        ("ings", ""), ("ing", ""), ("edly", ""), ("ed", ""), ("ly", ""),
        ("es", ""), ("s", ""),
    ]

    def _stem(self, w):
        if len(w) <= 3:
            return w
        lw = w.lower()
        for suf, rep in self._steps:
            if lw.endswith(suf) and len(lw) - len(suf) + len(rep) >= 3:
                out = lw[: len(lw) - len(suf)] + rep
                # restore a dropped 'e' for C-V-C+e stems (mak -> make)
                if suf in ("ing", "ed") and len(out) >= 3 and \
                        out[-1] not in "aeiou" and out[-2] in "aeiou" and \
                        out[-3] not in "aeiou" and out[-1] not in "wxy":
                    pass  # ambiguous; keep stripped form (baseline behavior)
                return out
        return lw

    def process(self, ann):
        for t in ann.select("token"):
            t.attrs["stem"] = self._stem(t.text)
        return ann


# closed-class lexicon + suffix rules: the classic rule-based baseline
_POS_LEXICON = {
    "the": "DT", "a": "DT", "an": "DT", "this": "DT", "that": "DT",
    "these": "DT", "those": "DT",
    "i": "PRP", "you": "PRP", "he": "PRP", "she": "PRP", "it": "PRP",
    "we": "PRP", "they": "PRP", "me": "PRP", "him": "PRP", "her": "PRP",
    "them": "PRP", "us": "PRP",
    "my": "PRP$", "your": "PRP$", "his": "PRP$", "its": "PRP$",
    "our": "PRP$", "their": "PRP$",
    "is": "VBZ", "am": "VBP", "are": "VBP", "was": "VBD", "were": "VBD",
    "be": "VB", "been": "VBN", "being": "VBG",
    "have": "VBP", "has": "VBZ", "had": "VBD", "do": "VBP", "does": "VBZ",
    "did": "VBD", "will": "MD", "would": "MD", "can": "MD", "could": "MD",
    "shall": "MD", "should": "MD", "may": "MD", "might": "MD", "must": "MD",
    "and": "CC", "or": "CC", "but": "CC", "nor": "CC", "yet": "CC",
    "in": "IN", "on": "IN", "at": "IN", "by": "IN", "for": "IN",
    "with": "IN", "from": "IN", "to": "TO", "of": "IN", "as": "IN",
    "if": "IN", "because": "IN", "while": "IN", "than": "IN",
    "not": "RB", "very": "RB", "also": "RB", "only": "RB", "never": "RB",
    "always": "RB", "often": "RB", "there": "EX",
}

_POS_SUFFIX = [
    ("ness", "NN"), ("ment", "NN"), ("tion", "NN"), ("sion", "NN"),
    ("ship", "NN"), ("ance", "NN"), ("ence", "NN"), ("ity", "NN"),
    ("ing", "VBG"), ("ed", "VBD"), ("ly", "RB"), ("ous", "JJ"),
    ("ful", "JJ"), ("ive", "JJ"), ("able", "JJ"), ("ible", "JJ"),
    ("al", "JJ"), ("est", "JJS"), ("er", "NN"), ("s", "NNS"),
]


class PoStagger(Annotator):
    """Lexicon + suffix-rule PoS tags on token spans using the Penn tagset
    (reference: annotator/PoStagger.java wrapping the OpenNLP maxent model;
    here the classic rule baseline — deterministic, no model file)."""

    def process(self, ann):
        for t in ann.select("token"):
            w = t.text
            lw = w.lower()
            if lw in _POS_LEXICON:
                tag = _POS_LEXICON[lw]
            elif re.fullmatch(r"[-+]?\d[\d,.]*", w):
                tag = "CD"
            elif not any(c.isalnum() for c in w):
                tag = "SYM"
            elif w[0].isupper() and t.begin > 0:
                tag = "NNP"
            else:
                tag = "NN"
                for suf, stag in _POS_SUFFIX:
                    if lw.endswith(suf) and len(lw) > len(suf) + 2:
                        tag = stag
                        break
            t.attrs["pos"] = tag
        return ann
