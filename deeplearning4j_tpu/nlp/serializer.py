"""Word-vector serialization.

Reference: models/embeddings/loader/WordVectorSerializer.java — text format
(one `word v1 v2 ...` line per word) and the Google word2vec binary format
(header "V D\\n", then per word: name + space + D little-endian float32s).
"""
from __future__ import annotations

import struct

import numpy as np
import jax.numpy as jnp


class WordVectorSerializer:
    # ------------------------------------------------------------- text
    @staticmethod
    def write_word_vectors(model, path):
        """Text format (reference: WordVectorSerializer.writeWordVectors)."""
        W = model.lookup_table.get_weights()
        with open(path, "w", encoding="utf-8") as fh:
            for vw in model.vocab.vocab_words():
                vec = " ".join(f"{x:.6g}" for x in W[vw.index])
                fh.write(f"{vw.word} {vec}\n")

    @staticmethod
    def read_word_vectors(path):
        """Returns (words, matrix)."""
        words, rows = [], []
        with open(path, "r", encoding="utf-8") as fh:
            for line in fh:
                parts = line.rstrip("\n").split(" ")
                if len(parts) < 2:
                    continue
                words.append(parts[0])
                rows.append(np.array([float(x) for x in parts[1:]], np.float32))
        return words, np.stack(rows) if rows else np.zeros((0, 0), np.float32)

    # ----------------------------------------------------------- binary
    @staticmethod
    def write_binary(model, path):
        """Google word2vec binary format (reference:
        WordVectorSerializer.writeWordVectors binary branch)."""
        W = model.lookup_table.get_weights().astype("<f4")
        V, D = W.shape
        with open(path, "wb") as fh:
            fh.write(f"{V} {D}\n".encode())
            for vw in model.vocab.vocab_words():
                fh.write(vw.word.encode("utf-8") + b" ")
                fh.write(W[vw.index].tobytes())
                fh.write(b"\n")

    @staticmethod
    def read_binary(path):
        """Returns (words, matrix) from Google binary format (reference:
        WordVectorSerializer.loadGoogleModel)."""
        with open(path, "rb") as fh:
            header = b""
            while not header.endswith(b"\n"):
                header += fh.read(1)
            V, D = (int(x) for x in header.split())
            words, rows = [], []
            for _ in range(V):
                name = b""
                while True:
                    ch = fh.read(1)
                    if ch in (b" ", b""):
                        break
                    name += ch
                vec = np.frombuffer(fh.read(4 * D), dtype="<f4")
                nl = fh.read(1)
                if nl not in (b"\n", b""):
                    fh.seek(-1, 1)
                words.append(name.decode("utf-8"))
                rows.append(vec)
        return words, np.stack(rows)

    # --------------------------------------------------------- full model
    @staticmethod
    def load_static_model(path, binary=False):
        """Build a query-only WordVectors from a vectors file (reference:
        WordVectorSerializer.loadStaticModel)."""
        from .sequence_vectors import WordVectors
        from .vocab import VocabCache, VocabWord
        from .embeddings import InMemoryLookupTable
        words, W = (WordVectorSerializer.read_binary(path) if binary
                    else WordVectorSerializer.read_word_vectors(path))
        cache = VocabCache()
        for w in words:
            cache.add_token(VocabWord(w, 1))
        cache.finalize_indices()
        # finalize sorts alphabetically on count ties — restore file order
        for i, w in enumerate(words):
            cache.word_for(w).index = i
        cache._by_index = [cache.word_for(w) for w in words]
        lt = InMemoryLookupTable(cache, W.shape[1] if W.size else 0)
        lt.syn0 = jnp.asarray(W)
        model = WordVectors()
        model.vocab = cache
        model.lookup_table = lt
        return model
