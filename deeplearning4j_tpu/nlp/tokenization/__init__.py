"""Tokenization (reference: deeplearning4j-nlp
text/tokenization/tokenizer/ + tokenizerfactory/ — DefaultTokenizer,
NGramTokenizer, DefaultTokenizerFactory, NGramTokenizerFactory,
TokenPreProcess impls CommonPreprocessor, LowCasePreProcessor,
EndingPreProcessor).
"""
from __future__ import annotations

import re


# ----------------------------------------------------------- preprocessors

class TokenPreProcess:
    def pre_process(self, token: str) -> str:
        raise NotImplementedError


class CommonPreprocessor(TokenPreProcess):
    """Lowercase + strip punctuation/special chars (reference:
    tokenization/tokenizer/preprocessor/CommonPreprocessor.java)."""
    _punct = re.compile(r"[\d\.:,\"'\(\)\[\]|/?!;]+")

    def pre_process(self, token):
        return self._punct.sub("", token).lower()


class LowCasePreProcessor(TokenPreProcess):
    def pre_process(self, token):
        return token.lower()


class EndingPreProcessor(TokenPreProcess):
    """Crude suffix stemmer (reference:
    tokenization/tokenizer/preprocessor/EndingPreProcessor.java)."""

    def pre_process(self, token):
        if token.endswith("s") and not token.endswith("ss"):
            token = token[:-1]
        if token.endswith("."):
            token = token[:-1]
        if token.endswith("ly"):
            token = token[:-2]
        if token.endswith("ing"):
            token = token[:-3]
        return token


class StemmingPreprocessor(CommonPreprocessor):
    """Common preprocessing + ending stem (the reference's stemmer variant)."""

    def pre_process(self, token):
        return EndingPreProcessor().pre_process(super().pre_process(token))


# --------------------------------------------------------------- tokenizers

class Tokenizer:
    """Iterator over tokens of one string (reference:
    text/tokenization/tokenizer/Tokenizer.java)."""

    def __init__(self, tokens, pre_processor=None):
        self._tokens = list(tokens)
        self._i = 0
        self._pre = pre_processor

    def set_token_pre_processor(self, pre):
        self._pre = pre

    def has_more_tokens(self):
        return self._i < len(self._tokens)

    def count_tokens(self):
        return len(self._tokens)

    def next_token(self):
        t = self._tokens[self._i]
        self._i += 1
        return self._pre.pre_process(t) if self._pre else t

    def get_tokens(self):
        out = []
        while self.has_more_tokens():
            t = self.next_token()
            if t:
                out.append(t)
        return out


_default_split = re.compile(r"\s+")


class DefaultTokenizer(Tokenizer):
    """Whitespace tokenizer (reference: DefaultTokenizer.java wraps Java
    StringTokenizer)."""

    def __init__(self, text, pre_processor=None):
        super().__init__([t for t in _default_split.split(text.strip()) if t],
                         pre_processor)


class NGramTokenizer(Tokenizer):
    """Emits n-grams of the base tokens joined by spaces (reference:
    NGramTokenizer.java, min/max n)."""

    def __init__(self, text, min_n=1, max_n=2, pre_processor=None):
        base = [t for t in _default_split.split(text.strip()) if t]
        grams = []
        for n in range(min_n, max_n + 1):
            for i in range(0, len(base) - n + 1):
                grams.append(" ".join(base[i:i + n]))
        super().__init__(grams, pre_processor)


# ---------------------------------------------------------------- factories

class TokenizerFactory:
    def create(self, text) -> Tokenizer:
        raise NotImplementedError

    def set_token_pre_processor(self, pre):
        self._pre = pre


class DefaultTokenizerFactory(TokenizerFactory):
    def __init__(self):
        self._pre = None

    def create(self, text):
        return DefaultTokenizer(text, self._pre)


class NGramTokenizerFactory(TokenizerFactory):
    def __init__(self, min_n=1, max_n=2):
        self._pre = None
        self.min_n, self.max_n = min_n, max_n

    def create(self, text):
        return NGramTokenizer(text, self.min_n, self.max_n, self._pre)


# ---------------------------------------------------------------- stopwords

# the reference ships a stopwords resource file; a compact english list stands in
STOP_WORDS = set("""a an and are as at be by for from has he in is it its of on
that the to was were will with this those these i you your me my we our us they
them their it's don't do does did not no nor so than then there here when where
which who whom what why how all any both each few more most other some such only
own same too very s t can just should now""".split())


class StopWords:
    @staticmethod
    def get_stop_words():
        return sorted(STOP_WORDS)
