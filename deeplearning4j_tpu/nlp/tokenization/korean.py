"""Korean tokenizer: eojeol splitting with josa (particle) separation.

Reference: deeplearning4j-nlp-korean/.../KoreanTokenizer.java +
KoreanTokenizerFactory.java (141 LoC) — a thin wrapper over the external
OpenKoreanText analyzer. Here the external dependency is replaced by a
self-contained normalizer/segmenter: whitespace-delimited eojeol are split
into stem + trailing particle using a committed list of the common josa,
guarded so single-syllable stems are never emptied. Hangul-jamo arithmetic
(U+AC00 block decomposition) decides whether a particle form is phonotactically
valid after the stem (e.g. 은/는, 이/가, 을/를 alternate on final consonant).
"""
from __future__ import annotations

import re

from . import Tokenizer, TokenizerFactory

# common particles, longest-first. Each entry: (surface, requires_final)
# requires_final: True -> attaches after a syllable WITH final consonant
# (batchim), False -> after one without, None -> either.
_JOSA = [
    ("에서는", None), ("에게서", None), ("으로는", True), ("로는", False),
    ("은", True), ("는", False), ("이", True), ("가", False),
    ("을", True), ("를", False), ("과", True), ("와", False),
    ("으로", True), ("로", False), ("에서", None), ("에게", None),
    ("한테", None), ("까지", None), ("부터", None), ("처럼", None),
    ("보다", None), ("마다", None), ("조차", None), ("밖에", None),
    ("의", None), ("에", None), ("도", None), ("만", None),
]
_JOSA.sort(key=lambda e: -len(e[0]))

_HANGUL_BASE = 0xAC00


def _has_batchim(ch):
    """True if the hangul syllable has a final consonant (jongseong)."""
    o = ord(ch)
    if not (_HANGUL_BASE <= o <= 0xD7A3):
        return None  # not a hangul syllable
    return (o - _HANGUL_BASE) % 28 != 0


def _split_eojeol(word):
    """Split one space-delimited word into [stem, particle] when a known josa
    matches phonotactically; else [word]."""
    for josa, needs_final in _JOSA:
        if not word.endswith(josa) or len(word) <= len(josa):
            continue
        stem = word[: -len(josa)]
        final = _has_batchim(stem[-1])
        if needs_final is None or final is None or final == needs_final:
            return [stem, josa]
    return [word]


_token_re = re.compile(r"[가-힣]+|[A-Za-z]+|\d+|[^\sA-Za-z\d가-힣]")


def segment(text):
    out = []
    for chunk in _token_re.findall(text):
        if _HANGUL_BASE <= ord(chunk[0]) <= 0xD7A3:
            out.extend(_split_eojeol(chunk))
        else:
            out.append(chunk)
    return out


class KoreanTokenizer(Tokenizer):
    def __init__(self, text, pre_processor=None):
        super().__init__(segment(text), pre_processor)


class KoreanTokenizerFactory(TokenizerFactory):
    def __init__(self):
        self._pre = None

    def create(self, text):
        return KoreanTokenizer(text, self._pre)
