"""Japanese morphological tokenizer: dictionary-lattice Viterbi segmentation.

Reference: deeplearning4j-nlp-japanese vendors the Kuromoji analyzer
(com.atilika.kuromoji/**, ~6.9k LoC: ipadic dictionary + connection-cost
Viterbi lattice + character-class unknown-word handling) behind
JapaneseTokenizerFactory. This is an original, self-contained reimplementation
of the same mechanism at reduced scale: a committed mini-lexicon of common
words/particles with word costs, a lattice built from dictionary prefix
matches plus character-class unknown-word candidates, and a min-cost dynamic
program — no vendored code, no downloads (zero-egress environment).

Segmentation quality tracks the lexicon; for Word2Vec-style downstream use
(the reference's own purpose for the plugin) consistent segmentation matters
more than linguistic perfection, and unknown words fall back to
character-class runs exactly like Kuromoji's UnknownDictionary does.
"""
from __future__ import annotations

from . import Tokenizer, TokenizerFactory

# ---------------------------------------------------------------- lexicon
# (surface, cost) — lower cost wins. Particles/copulas get low costs so they
# split off; content words moderate; the table mixes hiragana function words,
# common kanji compounds, and everyday vocabulary.
_LEXICON_ENTRIES = [
    # particles / copulas / auxiliaries (low cost: prefer splitting these off)
    ("は", 10), ("が", 10), ("を", 10), ("に", 10), ("で", 12), ("と", 12),
    ("も", 12), ("の", 10), ("へ", 12), ("や", 14), ("から", 12), ("まで", 12),
    ("より", 14), ("です", 12), ("でした", 12), ("だ", 14), ("だった", 14),
    ("である", 14), ("ます", 12), ("ました", 12), ("ません", 12), ("ない", 14),
    ("か", 16), ("ね", 16), ("よ", 16), ("な", 18), ("さん", 14), ("たち", 16),
    ("する", 14), ("した", 14), ("して", 14), ("います", 14), ("いる", 14),
    ("ある", 14), ("あり", 16), ("なる", 16), ("れる", 18), ("られる", 18),
    ("こと", 14), ("もの", 16), ("ため", 16), ("よう", 16), ("そう", 18),
    ("これ", 14), ("それ", 14), ("あれ", 16), ("ここ", 14), ("そこ", 16),
    ("この", 14), ("その", 14), ("どの", 16), ("として", 14), ("について", 14),
    ("において", 16), ("により", 16), ("による", 16),
    # pronouns / people
    ("私", 20), ("僕", 20), ("君", 22), ("彼", 22), ("彼女", 22), ("人", 24),
    ("先生", 22), ("学生", 22), ("友達", 22), ("子供", 22), ("家族", 22),
    # places / institutions
    ("日本", 20), ("東京", 20), ("京都", 22), ("大阪", 22), ("学校", 22),
    ("大学", 20), ("会社", 22), ("病院", 24), ("駅", 24), ("店", 26),
    ("国", 26), ("世界", 22), ("家", 26), ("部屋", 24), ("図書館", 22),
    # time
    ("今日", 20), ("明日", 22), ("昨日", 22), ("今", 24), ("時間", 22),
    ("年", 26), ("月", 26), ("日", 28), ("週間", 24), ("毎日", 22),
    ("朝", 26), ("夜", 26), ("午後", 24), ("午前", 24),
    # nouns (tech/study/daily)
    ("言語", 22), ("日本語", 20), ("英語", 22), ("勉強", 22), ("研究", 22),
    ("仕事", 22), ("電話", 24), ("電車", 22), ("車", 26), ("本", 26),
    ("映画", 22), ("音楽", 22), ("写真", 22), ("料理", 22), ("水", 26),
    ("お金", 24), ("問題", 22), ("質問", 22), ("答え", 24), ("意味", 22),
    ("名前", 22), ("情報", 22), ("計算", 22), ("機械", 22), ("学習", 22),
    ("機械学習", 18), ("人工知能", 18), ("自然", 24), ("処理", 24),
    ("自然言語処理", 16), ("データ", 20), ("モデル", 20), ("コンピュータ", 20),
    ("ニュース", 22), ("インターネット", 20), ("プログラム", 20),
    # verbs / adjectives (dictionary + common conjugations)
    ("行く", 22), ("行き", 24), ("来る", 22), ("来て", 24), ("見る", 22),
    ("見て", 24), ("食べる", 22), ("食べて", 24), ("飲む", 24), ("読む", 22),
    ("読んで", 24), ("書く", 22), ("書いて", 24), ("話す", 22), ("話して", 24),
    ("聞く", 24), ("買う", 24), ("使う", 22), ("使って", 24), ("作る", 22),
    ("思う", 22), ("思います", 22), ("知る", 24), ("分かる", 22),
    ("分かります", 22), ("好き", 22), ("嫌い", 24), ("大きい", 22),
    ("小さい", 22), ("新しい", 22), ("古い", 24), ("高い", 24), ("安い", 24),
    ("良い", 24), ("いい", 22), ("悪い", 24), ("早い", 24), ("楽しい", 22),
    ("難しい", 22), ("簡単", 24), ("きれい", 24), ("元気", 24),
]

_LEXICON = {}
for _s, _c in _LEXICON_ENTRIES:
    _LEXICON[_s] = min(_c, _LEXICON.get(_s, 1 << 30))
_MAX_WORD = max(len(s) for s in _LEXICON)


def _char_class(ch):
    o = ord(ch)
    if 0x3040 <= o <= 0x309F:
        return "hiragana"
    if 0x30A0 <= o <= 0x30FF or ch == "ー":
        return "katakana"
    if 0x4E00 <= o <= 0x9FFF or ch in "々〆ヶ":
        return "kanji"
    if ch.isdigit() or 0xFF10 <= o <= 0xFF19:
        return "digit"
    if ch.isalpha():
        return "latin"
    if ch.isspace():
        return "space"
    return "symbol"


# unknown-word base costs per character class (katakana runs are usually one
# loanword -> cheap to keep whole; lone hiragana is usually a particle the
# lexicon should have matched -> expensive)
_UNK_BASE = {"katakana": 30, "latin": 30, "digit": 30, "kanji": 40,
             "hiragana": 60, "symbol": 20, "space": 0}
_UNK_PER_CHAR = 6


def segment(text):
    """Min-cost lattice segmentation. Returns the token list (spaces dropped,
    symbols kept as their own tokens)."""
    n = len(text)
    INF = float("inf")
    best = [INF] * (n + 1)
    back = [0] * (n + 1)   # start index of the word ending at i
    best[0] = 0.0
    for i in range(n):
        if best[i] == INF:
            continue
        # dictionary candidates
        for L in range(1, min(_MAX_WORD, n - i) + 1):
            w = text[i:i + L]
            c = _LEXICON.get(w)
            if c is not None and best[i] + c < best[i + L]:
                best[i + L] = best[i] + c
                back[i + L] = i
        # unknown candidate: maximal run of the character class at i
        cls = _char_class(text[i])
        j = i + 1
        while j < n and _char_class(text[j]) == cls:
            j += 1
        run_len = j - i
        # offer every prefix of the run (kanji compounds may split mid-run)
        max_unk = run_len if cls != "kanji" else min(run_len, 3)
        for L in range(1, max_unk + 1):
            cost = _UNK_BASE[cls] + _UNK_PER_CHAR * L
            if best[i] + cost < best[i + L]:
                best[i + L] = best[i] + cost
                back[i + L] = i
    # backtrack
    out = []
    i = n
    while i > 0:
        s = back[i]
        out.append(text[s:i])
        i = s
    out.reverse()
    return [t for t in out if not t.isspace()]


class JapaneseTokenizer(Tokenizer):
    """(reference: org.deeplearning4j.text.tokenization.tokenizer
    .JapaneseTokenizer wrapping Kuromoji's Tokenizer)."""

    def __init__(self, text, pre_processor=None):
        super().__init__(segment(text), pre_processor)


class JapaneseTokenizerFactory(TokenizerFactory):
    """(reference: tokenizerfactory.JapaneseTokenizerFactory)."""

    def __init__(self):
        self._pre = None

    def create(self, text):
        return JapaneseTokenizer(text, self._pre)
