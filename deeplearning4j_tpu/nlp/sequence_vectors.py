"""SequenceVectors engine + Word2Vec / ParagraphVectors facades.

Reference: models/sequencevectors/SequenceVectors.java (1190 LoC; fit() :181,
buildVocab() :98, worker threads :267-271), models/word2vec/Word2Vec.java,
models/paragraphvectors/ParagraphVectors.java, learning algos
models/embeddings/learning/impl/{elements/{SkipGram,CBOW},sequence/{DBOW,DM}}.java.

Redesign (see embeddings.py): Hogwild worker threads become device-batched
scatter-add steps. Pair generation (host, numpy) streams into fixed-size
batches; learning rate decays linearly from learning_rate to min_learning_rate
over total expected pairs like word2vec/the reference's alpha schedule.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .vocab import VocabConstructor, VocabCache, Huffman
from .embeddings import (InMemoryLookupTable, skipgram_ns_step, skipgram_hs_step,
                         cbow_ns_step, cbow_hs_step)
from .tokenization import DefaultTokenizerFactory


class WordVectors:
    """Query API (reference: models/embeddings/wordvectors/WordVectors.java —
    similarity, wordsNearest, getWordVectorMatrix)."""

    vocab: VocabCache
    lookup_table: InMemoryLookupTable

    def has_word(self, word):
        return self.vocab.contains_word(word)

    def get_word_vector(self, word):
        return self.lookup_table.vector(word)

    def get_word_vector_matrix(self, word):
        return self.get_word_vector(word)

    def similarity(self, w1, w2):
        v1, v2 = self.get_word_vector(w1), self.get_word_vector(w2)
        if v1 is None or v2 is None:
            return float("nan")
        n1, n2 = np.linalg.norm(v1), np.linalg.norm(v2)
        if n1 == 0 or n2 == 0:
            return 0.0
        return float(np.dot(v1, v2) / (n1 * n2))

    def words_nearest(self, word_or_vec, n=10):
        if isinstance(word_or_vec, str):
            v = self.get_word_vector(word_or_vec)
            exclude = {word_or_vec}
        else:
            v = np.asarray(word_or_vec)
            exclude = set()
        if v is None:
            return []
        W = self.lookup_table.get_weights()
        norms = np.linalg.norm(W, axis=1) * (np.linalg.norm(v) or 1.0)
        sims = W @ v / np.maximum(norms, 1e-12)
        order = np.argsort(-sims)
        out = []
        for i in order:
            w = self.vocab.word_at_index(int(i))
            if w not in exclude:
                out.append(w)
            if len(out) >= n:
                break
        return out


class SequenceVectors(WordVectors):
    """Generic sequence-embedding trainer (reference: SequenceVectors.java)."""

    def __init__(self, *, layer_size=100, window=5, negative=5, use_hs=False,
                 learning_rate=0.025, min_learning_rate=1e-4, epochs=1,
                 min_word_frequency=1, subsampling=0.0, seed=12345,
                 batch_size=2048, tokenizer_factory=None, stop_words=None,
                 elements_algo="skipgram"):
        self.layer_size = layer_size
        self.window = window
        self.negative = negative
        self.use_hs = use_hs or negative == 0
        self.learning_rate = learning_rate
        self.min_learning_rate = min_learning_rate
        self.epochs = epochs
        self.min_word_frequency = min_word_frequency
        self.subsampling = subsampling
        self.seed = seed
        self.batch_size = batch_size
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.stop_words = stop_words
        self.elements_algo = elements_algo
        self.vocab = None
        self.lookup_table = None
        self._np_rng = np.random.default_rng(seed)
        self._key = jax.random.PRNGKey(seed)

    # ------------------------------------------------------------- vocab
    def build_vocab(self, sentences):
        """(reference: SequenceVectors.buildVocab :98 → VocabConstructor)"""
        self.vocab = VocabConstructor(
            self.tokenizer_factory, self.min_word_frequency,
            self.stop_words).build_vocab(sentences, build_huffman=True)
        self.lookup_table = InMemoryLookupTable(
            self.vocab, self.layer_size, self.seed, self.negative, self.use_hs)
        self.lookup_table.reset_weights(n_extra_rows=self._n_extra_rows())
        if self.use_hs:
            self._prepare_hs_tables()
        return self

    def _n_extra_rows(self):
        return 0

    def _prepare_hs_tables(self):
        words = self.vocab.vocab_words()
        L = max((len(w.codes) for w in words), default=1)
        V = len(words)
        codes = np.zeros((V, L), np.float32)
        points = np.zeros((V, L), np.int32)
        mask = np.zeros((V, L), np.float32)
        for w in words:
            l = len(w.codes)
            codes[w.index, :l] = w.codes
            points[w.index, :l] = w.points
            mask[w.index, :l] = 1.0
        self._hs_codes = jnp.asarray(codes)
        self._hs_points = jnp.asarray(points)
        self._hs_mask = jnp.asarray(mask)

    # ----------------------------------------------------------- sentences
    def _to_indices(self, sentence):
        """Tokenize, vocab-filter, subsample (reference: the subsampling
        transformer; word2vec formula keep-prob = sqrt(t/f) + t/f)."""
        toks = self.tokenizer_factory.create(sentence).get_tokens()
        idxs = []
        total = max(self.vocab.total_word_count, 1)
        for t in toks:
            vw = self.vocab.word_for(t)
            if vw is None:
                continue
            if self.subsampling > 0:
                f = vw.count / total
                keep = (np.sqrt(f / self.subsampling) + 1) * (self.subsampling / f)
                if self._np_rng.random() > keep:
                    continue
            idxs.append(vw.index)
        return idxs

    def _gen_pairs(self, sentences):
        """(center, context) pairs with word2vec random window reduction."""
        for s in sentences:
            idxs = self._to_indices(s)
            n = len(idxs)
            for i, c in enumerate(idxs):
                b = self._np_rng.integers(1, self.window + 1)
                for j in range(max(0, i - b), min(n, i + b + 1)):
                    if j != i:
                        yield c, idxs[j]

    # ------------------------------------------------------------- training
    def fit(self, sentences):
        """(reference: SequenceVectors.fit :181)"""
        sentences = list(sentences)
        if self.vocab is None:
            self.build_vocab(sentences)
        # estimate total pairs for the linear lr schedule
        est_pairs = max(1, self.vocab.total_word_count * self.window * self.epochs)
        seen = 0
        lt = self.lookup_table
        for _ in range(self.epochs):
            batch_c, batch_o = [], []
            for c, o in self._gen_pairs(sentences):
                batch_c.append(c)
                batch_o.append(o)
                if len(batch_c) >= self.batch_size:
                    seen += len(batch_c)
                    self._train_batch(batch_c, batch_o, self._lr(seen, est_pairs))
                    batch_c, batch_o = [], []
            if batch_c:
                seen += len(batch_c)
                self._train_batch(batch_c, batch_o, self._lr(seen, est_pairs))
        return self

    def _lr(self, seen, total):
        frac = min(1.0, seen / total)
        return max(self.min_learning_rate,
                   self.learning_rate * (1.0 - frac))

    @staticmethod
    def _pad_chunk(*arrays):
        """Pad [B,...] arrays to a multiple of embeddings.CHUNK; returns padded
        arrays + float validity mask."""
        from .embeddings import CHUNK
        B = len(arrays[0])
        P = (-B) % CHUNK
        valid = np.ones(B + P, np.float32)
        valid[B:] = 0.0
        out = []
        for a in arrays:
            a = np.asarray(a)
            if P:
                pad_shape = (P,) + a.shape[1:]
                a = np.concatenate([a, np.zeros(pad_shape, a.dtype)])
            out.append(jnp.asarray(a))
        return out + [jnp.asarray(valid)]

    def _train_batch(self, centers, contexts, lr):
        lt = self.lookup_table
        c_np = np.asarray(centers, np.int32)
        o_np = np.asarray(contexts, np.int32)
        if self.elements_algo == "cbow":
            # regroup: treat each pair's context as a width-1 window
            c, o, valid = self._pad_chunk(c_np, o_np)
            ctx = o[:, None]
            cm = jnp.ones_like(ctx, jnp.float32)
            if self.use_hs:
                lt.syn0, lt.syn1 = cbow_hs_step(
                    lt.syn0, lt.syn1, ctx, cm, self._hs_codes[c],
                    self._hs_points[c], self._hs_mask[c], valid, lr)
            else:
                self._key, sub = jax.random.split(self._key)
                lt.syn0, lt.syn1neg = cbow_ns_step(
                    lt.syn0, lt.syn1neg, lt._unigram, ctx, cm, c, valid, lr,
                    sub, self.negative)
        elif self.use_hs:
            c, o, valid = self._pad_chunk(c_np, o_np)
            lt.syn0, lt.syn1 = skipgram_hs_step(
                lt.syn0, lt.syn1, c, self._hs_codes[o], self._hs_points[o],
                self._hs_mask[o], valid, lr)
        else:
            c, o, valid = self._pad_chunk(c_np, o_np)
            self._key, sub = jax.random.split(self._key)
            lt.syn0, lt.syn1neg = skipgram_ns_step(
                lt.syn0, lt.syn1neg, lt._unigram, c, o, valid, lr, sub,
                self.negative)


class Word2Vec(SequenceVectors):
    """(reference: models/word2vec/Word2Vec.java — Builder facade over
    SequenceVectors)."""

    class Builder:
        def __init__(self):
            self._kw = {}
            self._iter = None

        def layer_size(self, n):
            self._kw["layer_size"] = n
            return self

        def window_size(self, n):
            self._kw["window"] = n
            return self

        def negative_sample(self, n):
            self._kw["negative"] = n
            return self

        def use_hierarchic_softmax(self, b=True):
            self._kw["use_hs"] = b
            return self

        def learning_rate(self, lr):
            self._kw["learning_rate"] = lr
            return self

        def min_learning_rate(self, lr):
            self._kw["min_learning_rate"] = lr
            return self

        def epochs(self, n):
            self._kw["epochs"] = n
            return self

        iterations = epochs

        def min_word_frequency(self, n):
            self._kw["min_word_frequency"] = n
            return self

        def sampling(self, s):
            self._kw["subsampling"] = s
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def batch_size(self, b):
            self._kw["batch_size"] = b
            return self

        def tokenizer_factory(self, tf):
            self._kw["tokenizer_factory"] = tf
            return self

        def stop_words(self, sw):
            self._kw["stop_words"] = sw
            return self

        def elements_learning_algorithm(self, name):
            self._kw["elements_algo"] = str(name).lower()
            return self

        def iterate(self, sentence_iterator):
            self._iter = sentence_iterator
            return self

        def build(self):
            w = Word2Vec(**self._kw)
            w._sentence_iter = self._iter
            return w

    @staticmethod
    def builder():
        return Word2Vec.Builder()

    def __init__(self, **kw):
        super().__init__(**kw)
        self._sentence_iter = None

    def fit(self, sentences=None):
        if sentences is None:
            sentences = list(self._sentence_iter)
        return super().fit(sentences)


class ParagraphVectors(SequenceVectors):
    """Doc embeddings (reference: models/paragraphvectors/ParagraphVectors.java;
    sequence algos DBOW/DM at models/embeddings/learning/impl/sequence/).
    Label vectors live in extra syn0 rows after the vocab rows."""

    def __init__(self, *, sequence_algo="dbow", **kw):
        super().__init__(**kw)
        self.sequence_algo = sequence_algo  # "dbow" | "dm"
        self.labels = []
        self._label_index = {}

    class Builder(Word2Vec.Builder):
        def __init__(self):
            super().__init__()
            self._docs = None

        def sequence_learning_algorithm(self, name):
            name = str(name).lower()
            self._kw["sequence_algo"] = "dm" if "dm" in name else "dbow"
            return self

        def iterate_documents(self, label_aware_iterator):
            self._docs = label_aware_iterator
            return self

        def build(self):
            p = ParagraphVectors(**self._kw)
            p._doc_iter = self._docs
            return p

    @staticmethod
    def builder():
        return ParagraphVectors.Builder()

    def _n_extra_rows(self):
        return len(self.labels)

    def fit(self, documents=None):
        """documents: LabelAwareIterator or [(text, label)] pairs."""
        from .text import LabelAwareIterator, SimpleLabelAwareIterator
        if documents is None:
            documents = self._doc_iter
        if isinstance(documents, (list, tuple)):
            documents = SimpleLabelAwareIterator(documents)
        docs = list(documents)
        self.labels = sorted({l for d in docs for l in d.labels})
        self._label_index = {l: i for i, l in enumerate(self.labels)}
        self.build_vocab([d.content for d in docs])

        V = self.vocab.num_words()
        est_pairs = max(1, self.vocab.total_word_count * self.epochs *
                        (self.window if self.sequence_algo == "dm" else 1))
        seen = 0
        for _ in range(self.epochs):
            bc, bo, bctx = [], [], []
            for d in docs:
                idxs = self._to_indices(d.content)
                rows = [V + self._label_index[l] for l in d.labels]
                if self.sequence_algo == "dbow":
                    # label vector predicts each word (reference: DBOW.java)
                    for r in rows:
                        for w in idxs:
                            bc.append(r)
                            bo.append(w)
                else:
                    # DM: window + label rows predict center (reference: DM.java)
                    n = len(idxs)
                    for i, c in enumerate(idxs):
                        b = self._np_rng.integers(1, self.window + 1)
                        ctx = [idxs[j] for j in range(max(0, i - b), min(n, i + b + 1))
                               if j != i] + rows
                        bc.append(c)
                        bctx.append(ctx)
                while len(bc) >= self.batch_size:
                    take = self.batch_size
                    seen += take
                    lr = self._lr(seen, est_pairs)
                    if self.sequence_algo == "dbow":
                        self._train_batch(bc[:take], bo[:take], lr)
                        bc, bo = bc[take:], bo[take:]
                    else:
                        self._train_dm_batch(bc[:take], bctx[:take], lr)
                        bc, bctx = bc[take:], bctx[take:]
            if bc:
                seen += len(bc)
                lr = self._lr(seen, est_pairs)
                if self.sequence_algo == "dbow":
                    self._train_batch(bc, bo, lr)
                else:
                    self._train_dm_batch(bc, bctx, lr)
        return self

    def _train_dm_batch(self, centers, contexts, lr):
        W = max(len(c) for c in contexts)
        B = len(centers)
        ctx_np = np.zeros((B, W), np.int32)
        cm_np = np.zeros((B, W), np.float32)
        for i, c in enumerate(contexts):
            ctx_np[i, :len(c)] = c
            cm_np[i, :len(c)] = 1.0
        lt = self.lookup_table
        c, ctx, cm, valid = self._pad_chunk(
            np.asarray(centers, np.int32), ctx_np, cm_np)
        if self.use_hs:
            lt.syn0, lt.syn1 = cbow_hs_step(
                lt.syn0, lt.syn1, ctx, cm, self._hs_codes[c],
                self._hs_points[c], self._hs_mask[c], valid, lr)
        else:
            self._key, sub = jax.random.split(self._key)
            lt.syn0, lt.syn1neg = cbow_ns_step(
                lt.syn0, lt.syn1neg, lt._unigram, ctx, cm, c, valid, lr, sub,
                self.negative)

    # ------------------------------------------------------------- queries
    def get_label_vector(self, label):
        i = self._label_index.get(label)
        if i is None:
            return None
        return np.asarray(self.lookup_table.syn0[self.vocab.num_words() + i])

    def similarity_to_label(self, text, label):
        v = self.infer_vector(text)
        lv = self.get_label_vector(label)
        n1, n2 = np.linalg.norm(v), np.linalg.norm(lv)
        if n1 == 0 or n2 == 0:
            return 0.0
        return float(np.dot(v, lv) / (n1 * n2))

    def infer_vector(self, text, steps=20, lr=0.05):
        """Gradient-fit a fresh doc vector against frozen word/output weights
        (reference: ParagraphVectors.inferVector)."""
        idxs = self._to_indices(text)
        if not idxs:
            return np.zeros(self.layer_size, np.float32)
        lt = self.lookup_table
        d = self.layer_size
        import hashlib
        digest = hashlib.md5(text.encode("utf-8")).digest()
        key = jax.random.PRNGKey(int.from_bytes(digest[:4], "little"))
        # zero init: the first step already moves toward the words' output
        # vectors; avoids unlucky random inits on short texts
        vec = jnp.zeros((d,), jnp.float32)
        words = jnp.asarray(np.asarray(idxs, np.int32))
        for s in range(steps):
            key, sub = jax.random.split(key)
            vec = _infer_step(vec, lt.syn1neg, lt._unigram, words,
                              jnp.float32(lr * (1 - s / steps)), sub,
                              self.negative)
        return np.asarray(vec)


import functools


@functools.partial(jax.jit, static_argnames=("n_neg",))
def _infer_step(vec, syn1neg, unigram, words, lr, key, n_neg):
    """One DBOW inference step: update only the doc vector against frozen
    output weights (negative sampling)."""
    negs = unigram[jax.random.randint(key, (words.shape[0], n_neg), 0,
                                      unigram.shape[0])]
    uo = syn1neg[words]                                  # N,D
    un = syn1neg[negs]                                   # N,K,D
    pos_f = jax.nn.sigmoid(uo @ vec)                     # N
    g_pos = (1.0 - pos_f) * lr
    neg_f = jax.nn.sigmoid(jnp.einsum("d,nkd->nk", vec, un))
    g_neg = -neg_f * lr
    dv = g_pos @ uo + jnp.einsum("nk,nkd->d", g_neg, un)
    return vec + dv
