"""Text → CNN input tensors.

Reference: deeplearning4j-nlp iterator/CnnSentenceDataSetIterator.java — maps
labelled sentences to [batch, 1, maxLength, vectorSize] (CNN1D-style) tensors
of stacked word vectors + one-hot labels, with sentence truncation/padding and
feature masks.
"""
from __future__ import annotations

import numpy as np

from ..datasets.dataset import DataSet


class CnnSentenceDataSetIterator:
    def __init__(self, word_vectors, labeled_sentences, labels, batch_size=32,
                 max_sentence_length=64, tokenizer_factory=None,
                 channels_last=True):
        """labeled_sentences: [(sentence, label)] — the reference takes a
        LabeledSentenceProvider; word_vectors: any WordVectors."""
        from .tokenization import DefaultTokenizerFactory
        self.wv = word_vectors
        self.data = list(labeled_sentences)
        self.labels = list(labels)
        self.label_index = {l: i for i, l in enumerate(self.labels)}
        self.batch_size = batch_size
        self.max_len = max_sentence_length
        self.tf = tokenizer_factory or DefaultTokenizerFactory()
        self.channels_last = channels_last
        self._i = 0

    def reset(self):
        self._i = 0
        return self

    def has_next(self):
        return self._i < len(self.data)

    def next(self):
        batch = self.data[self._i:self._i + self.batch_size]
        self._i += len(batch)
        D = self.wv.lookup_table.layer_size()
        B = len(batch)
        feats = np.zeros((B, self.max_len, D, 1), np.float32)
        mask = np.zeros((B, self.max_len), np.float32)
        labels = np.zeros((B, len(self.labels)), np.float32)
        for bi, (sent, lab) in enumerate(batch):
            toks = [t for t in self.tf.create(sent).get_tokens()
                    if self.wv.has_word(t)][: self.max_len]
            for ti, t in enumerate(toks):
                feats[bi, ti, :, 0] = self.wv.get_word_vector(t)
                mask[bi, ti] = 1.0
            labels[bi, self.label_index[lab]] = 1.0
        if not self.channels_last:  # NCHW variant
            feats = feats.transpose(0, 3, 1, 2)
        return DataSet(feats, labels, features_mask=mask)

    def __iter__(self):
        while self.has_next():
            yield self.next()
