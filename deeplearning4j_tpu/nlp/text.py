"""Sentence / document iterators and label sources.

Reference: deeplearning4j-nlp text/sentenceiterator/ (SentenceIterator,
BasicLineIterator, CollectionSentenceIterator, FileSentenceIterator,
LineSentenceIterator, SentencePreProcessor), text/documentiterator/
(DocumentIterator, LabelAwareIterator, LabelledDocument, LabelsSource).
"""
from __future__ import annotations

import os


class SentencePreProcessor:
    def pre_process(self, sentence: str) -> str:
        raise NotImplementedError


class SentenceIterator:
    """(reference: text/sentenceiterator/SentenceIterator.java)"""

    def __init__(self):
        self.pre_processor = None

    def next_sentence(self) -> str:
        raise NotImplementedError

    def has_next(self) -> bool:
        raise NotImplementedError

    def reset(self):
        raise NotImplementedError

    def _apply(self, s):
        return self.pre_processor.pre_process(s) if self.pre_processor else s

    def __iter__(self):
        self.reset()
        while self.has_next():
            yield self.next_sentence()


class CollectionSentenceIterator(SentenceIterator):
    def __init__(self, sentences):
        super().__init__()
        self.sentences = list(sentences)
        self._i = 0

    def next_sentence(self):
        s = self.sentences[self._i]
        self._i += 1
        return self._apply(s)

    def has_next(self):
        return self._i < len(self.sentences)

    def reset(self):
        self._i = 0


class BasicLineIterator(SentenceIterator):
    """One sentence per line from a file (reference: BasicLineIterator.java)."""

    def __init__(self, path):
        super().__init__()
        self.path = str(path)
        self._fh = None

    def reset(self):
        if self._fh:
            self._fh.close()
        self._fh = open(self.path, "r", encoding="utf-8")
        self._peek = None

    def _advance(self):
        line = self._fh.readline()
        self._peek = line if line else None

    def has_next(self):
        if self._fh is None:
            self.reset()
        if self._peek is None:
            self._advance()
        return self._peek is not None

    def next_sentence(self):
        if not self.has_next():
            raise StopIteration
        s = self._peek.rstrip("\n")
        self._peek = None
        return self._apply(s)


LineSentenceIterator = BasicLineIterator


class FileSentenceIterator(SentenceIterator):
    """Iterates lines of every file under a directory (reference:
    FileSentenceIterator.java)."""

    def __init__(self, directory):
        super().__init__()
        self.directory = str(directory)
        self.reset()

    def reset(self):
        self._files = sorted(
            os.path.join(r, f)
            for r, _, fs in os.walk(self.directory) for f in fs)
        self._lines = []
        self._fi = 0

    def _fill(self):
        while not self._lines and self._fi < len(self._files):
            with open(self._files[self._fi], "r", encoding="utf-8",
                      errors="replace") as fh:
                self._lines = [l.rstrip("\n") for l in fh if l.strip()]
            self._fi += 1

    def has_next(self):
        self._fill()
        return bool(self._lines)

    def next_sentence(self):
        if not self.has_next():
            raise StopIteration
        return self._apply(self._lines.pop(0))


# -------------------------------------------------------------- documents

class LabelledDocument:
    """(reference: text/documentiterator/LabelledDocument.java)"""

    def __init__(self, content="", labels=None):
        self.content = content
        self.labels = list(labels or [])

    @property
    def label(self):
        return self.labels[0] if self.labels else None


class LabelsSource:
    """Generates/stores document labels (reference:
    text/documentiterator/LabelsSource.java — template mode DOC_%d or
    user-supplied list)."""

    def __init__(self, template="DOC_%d", labels=None):
        self.template = template
        self._labels = list(labels) if labels else []
        self._counter = 0
        self._set = set(self._labels)

    def next_label(self):
        label = self.template % self._counter
        self._counter += 1
        if label not in self._set:
            self._labels.append(label)
            self._set.add(label)
        return label

    def store_label(self, label):
        if label not in self._set:
            self._labels.append(label)
            self._set.add(label)

    def get_labels(self):
        return list(self._labels)

    def index_of(self, label):
        return self._labels.index(label)

    def size(self):
        return len(self._labels)


class LabelAwareIterator:
    """Iterator of LabelledDocuments (reference:
    text/documentiterator/LabelAwareIterator.java)."""

    def __init__(self, documents, labels_source=None):
        self.documents = list(documents)
        self.labels_source = labels_source or LabelsSource()
        for d in self.documents:
            for l in d.labels:
                self.labels_source.store_label(l)
        self._i = 0

    def has_next_document(self):
        return self._i < len(self.documents)

    def next_document(self):
        d = self.documents[self._i]
        self._i += 1
        return d

    def reset(self):
        self._i = 0

    def get_labels_source(self):
        return self.labels_source

    def __iter__(self):
        self.reset()
        while self.has_next_document():
            yield self.next_document()


class SimpleLabelAwareIterator(LabelAwareIterator):
    """Build from (text, label) pairs."""

    def __init__(self, pairs):
        docs = [LabelledDocument(t, [l]) for t, l in pairs]
        super().__init__(docs)
