"""GloVe embeddings.

Reference: models/glove/Glove.java (438 LoC) + models/glove/count/ —
co-occurrence counting with 1/distance weighting, then AdaGrad-optimized
weighted-least-squares on log co-occurrence.

TPU redesign: co-occurrence counting on host (hash map, like the reference's
count package), training as batched jitted steps over the co-occurrence
triples: per batch gather word/context rows + biases, compute
f(X)(w·w̃ + b + b̃ − log X) gradients, AdaGrad scale, scatter-add back.
"""
from __future__ import annotations

import functools
from collections import defaultdict

import numpy as np
import jax
import jax.numpy as jnp

from .vocab import VocabConstructor
from .sequence_vectors import WordVectors
from .embeddings import InMemoryLookupTable
from .tokenization import DefaultTokenizerFactory


@functools.partial(jax.jit, donate_argnums=(0, 1, 2, 3, 4, 5))
def _glove_step(W, Wc, b, bc, hW, hWc, hb, hbc, wi, ci, logx, fx, lr):
    """AdaGrad GloVe update on a batch of (word, ctx, log co-occurrence,
    weight) triples."""
    d = W.shape[1]
    w = W[wi]
    c = Wc[ci]
    diff = jnp.sum(w * c, -1) + b[wi] + bc[ci] - logx       # B
    g = fx * diff                                            # B
    gw = g[:, None] * c
    gc = g[:, None] * w
    # adagrad accumulators
    hW = hW.at[wi].add(gw ** 2)
    hWc = hWc.at[ci].add(gc ** 2)
    hb = hb.at[wi].add(g ** 2)
    hbc = hbc.at[ci].add(g ** 2)
    W = W.at[wi].add(-lr * gw / jnp.sqrt(hW[wi] + 1e-8))
    Wc = Wc.at[ci].add(-lr * gc / jnp.sqrt(hWc[ci] + 1e-8))
    b = b.at[wi].add(-lr * g / jnp.sqrt(hb[wi] + 1e-8))
    bc = bc.at[ci].add(-lr * g / jnp.sqrt(hbc[ci] + 1e-8))
    loss = 0.5 * jnp.sum(fx * diff ** 2)
    return W, Wc, b, bc, hW, hWc, hb, hbc, loss


class Glove(WordVectors):
    def __init__(self, *, layer_size=100, window=5, learning_rate=0.05,
                 epochs=5, min_word_frequency=1, x_max=100.0, alpha=0.75,
                 seed=12345, batch_size=8192, tokenizer_factory=None,
                 symmetric=True):
        self.layer_size = layer_size
        self.window = window
        self.learning_rate = learning_rate
        self.epochs = epochs
        self.min_word_frequency = min_word_frequency
        self.x_max = x_max
        self.alpha = alpha
        self.seed = seed
        self.batch_size = batch_size
        self.symmetric = symmetric
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.vocab = None
        self.lookup_table = None
        self.loss_history = []

    class Builder:
        def __init__(self):
            self._kw = {}

        def layer_size(self, n):
            self._kw["layer_size"] = n
            return self

        def window_size(self, n):
            self._kw["window"] = n
            return self

        def learning_rate(self, lr):
            self._kw["learning_rate"] = lr
            return self

        def epochs(self, n):
            self._kw["epochs"] = n
            return self

        def min_word_frequency(self, n):
            self._kw["min_word_frequency"] = n
            return self

        def x_max(self, x):
            self._kw["x_max"] = x
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def build(self):
            return Glove(**self._kw)

    @staticmethod
    def builder():
        return Glove.Builder()

    def _cooccurrence(self, sentences):
        """(reference: glove/count/ — 1/distance-weighted counts)"""
        counts = defaultdict(float)
        for s in sentences:
            toks = self.tokenizer_factory.create(s).get_tokens()
            idxs = [self.vocab.index_of(t) for t in toks]
            idxs = [i for i in idxs if i >= 0]
            for i, wi in enumerate(idxs):
                for j in range(max(0, i - self.window), i):
                    ci = idxs[j]
                    weight = 1.0 / (i - j)
                    counts[(wi, ci)] += weight
                    if self.symmetric:
                        counts[(ci, wi)] += weight
        return counts

    def fit(self, sentences):
        sentences = list(sentences)
        self.vocab = VocabConstructor(
            self.tokenizer_factory,
            self.min_word_frequency).build_vocab(sentences, build_huffman=False)
        V, D = self.vocab.num_words(), self.layer_size
        counts = self._cooccurrence(sentences)
        triples = np.array([(w, c, x) for (w, c), x in counts.items()],
                           np.float64).reshape(-1, 3)
        wi_all = triples[:, 0].astype(np.int32)
        ci_all = triples[:, 1].astype(np.int32)
        x_all = triples[:, 2]
        logx_all = np.log(x_all).astype(np.float32)
        fx_all = np.minimum(1.0, (x_all / self.x_max) ** self.alpha).astype(np.float32)

        key = jax.random.PRNGKey(self.seed)
        k1, k2 = jax.random.split(key)
        W = (jax.random.uniform(k1, (V, D)) - 0.5) / D
        Wc = (jax.random.uniform(k2, (V, D)) - 0.5) / D
        b = jnp.zeros((V,))
        bc = jnp.zeros((V,))
        hW, hWc = jnp.zeros((V, D)), jnp.zeros((V, D))
        hb, hbc = jnp.zeros((V,)), jnp.zeros((V,))

        n = len(wi_all)
        rng = np.random.default_rng(self.seed)
        for _ in range(self.epochs):
            order = rng.permutation(n)
            total = 0.0
            for s in range(0, n, self.batch_size):
                sel = order[s:s + self.batch_size]
                W, Wc, b, bc, hW, hWc, hb, hbc, loss = _glove_step(
                    W, Wc, b, bc, hW, hWc, hb, hbc,
                    jnp.asarray(wi_all[sel]), jnp.asarray(ci_all[sel]),
                    jnp.asarray(logx_all[sel]), jnp.asarray(fx_all[sel]),
                    jnp.float32(self.learning_rate))
                total += float(loss)
            self.loss_history.append(total / max(n, 1))

        # final vectors = W + Wc (standard GloVe)
        self.lookup_table = InMemoryLookupTable(self.vocab, D, self.seed, 0)
        self.lookup_table.syn0 = W + Wc
        self.lookup_table.syn1 = jnp.zeros((1, D))
        self.lookup_table.syn1neg = jnp.zeros((V, D))
        return self
