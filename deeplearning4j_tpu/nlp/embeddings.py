"""Embedding lookup tables + batched XLA learning kernels.

Reference: models/embeddings/inmemory/InMemoryLookupTable.java (734 LoC; syn0,
syn1 for hierarchical softmax, syn1neg + unigram table for negative sampling,
expTable) and models/embeddings/learning/impl/elements/{SkipGram.java,
CBOW.java}.

TPU-first redesign: the reference trains Hogwild-style — N Java threads doing
lock-free axpy on shared syn0/syn1 rows (SequenceVectors.java:267-271, P7 in
SURVEY §2.4). Here a training *batch* of (center, context) pairs becomes ONE
jitted XLA computation: gather rows → sigmoid dot products → scatter-add
updates (`.at[].add` accumulates duplicate indices, which is exactly the
sequential-consistency Hogwild approximates). Negative sampling draws from the
unigram^0.75 table on device via jax.random.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp


class WeightLookupTable:
    """API surface of the reference's WeightLookupTable.java."""

    def vector(self, word):
        raise NotImplementedError

    def layer_size(self):
        raise NotImplementedError


class InMemoryLookupTable(WeightLookupTable):
    def __init__(self, vocab, vector_length=100, seed=12345, negative=5,
                 use_hs=False, dtype=jnp.float32):
        self.vocab = vocab
        self.vector_length = int(vector_length)
        self.seed = seed
        self.negative = int(negative)
        self.use_hs = use_hs
        self.dtype = dtype
        self.syn0 = None
        self.syn1 = None       # HS inner-node weights
        self.syn1neg = None    # negative-sampling output weights
        self._unigram = None   # int32 sampling table (word2vec unigram^0.75)

    def reset_weights(self, n_extra_rows=0):
        """syn0 ~ U(-0.5,0.5)/dim like word2vec; syn1/syn1neg zeros.
        n_extra_rows reserves label rows for ParagraphVectors."""
        v = self.vocab.num_words() + n_extra_rows
        d = self.vector_length
        key = jax.random.PRNGKey(self.seed)
        self.syn0 = (jax.random.uniform(key, (v, d), self.dtype) - 0.5) / d
        self.syn1 = jnp.zeros((max(v - 1, 1), d), self.dtype)
        self.syn1neg = jnp.zeros((v, d), self.dtype)
        self._build_unigram_table()
        return self

    def _build_unigram_table(self, table_size=1_000_000, power=0.75):
        """word2vec-style unigram table (reference: InMemoryLookupTable
        makeTable)."""
        counts = np.array([w.count for w in self.vocab.vocab_words()], np.float64)
        if counts.size == 0:
            self._unigram = jnp.zeros((1,), jnp.int32)
            return
        probs = counts ** power
        probs /= probs.sum()
        table = np.repeat(np.arange(len(counts)),
                          np.maximum(1, np.round(probs * table_size).astype(int)))
        self._unigram = jnp.asarray(table, jnp.int32)

    # ------------------------------------------------------------- access
    def layer_size(self):
        return self.vector_length

    def vector(self, word):
        idx = self.vocab.index_of(word)
        if idx < 0:
            return None
        return np.asarray(self.syn0[idx])

    def get_weights(self):
        return np.asarray(self.syn0[: self.vocab.num_words()])


# ------------------------------------------------------------ XLA kernels
#
# Batching note: the reference applies each pair's update sequentially
# (Hogwild, SequenceVectors.java:267-271). Summing a whole batch of updates
# computed at stale weights diverges when rows repeat many times per batch
# (small vocab); a pure scatter-mean is stable but gives each row only one
# effective update per batch. The middle ground used here: lax.scan over
# fixed-size CHUNKS of pairs — within a chunk updates are scatter-MEANed
# (stable), between chunks weights refresh (sequential-like convergence).
# One jitted XLA computation per batch either way.

CHUNK = 128


def _inv_counts(size, idx, weights=None):
    """1/max(count,1) per table row, gathered back for scatter-mean scaling."""
    ones = jnp.ones(idx.shape, jnp.float32) if weights is None else weights
    cnt = jnp.zeros((size,), jnp.float32).at[idx].add(ones)
    return 1.0 / jnp.maximum(cnt, 1.0)


def _chunked(*arrays):
    """Reshape [B,...] arrays to [S, CHUNK, ...] for lax.scan."""
    out = []
    for a in arrays:
        out.append(a.reshape((-1, CHUNK) + a.shape[1:]))
    return out


@functools.partial(jax.jit, donate_argnums=(0, 1), static_argnames=("n_neg",))
def skipgram_ns_step(syn0, syn1neg, unigram, centers, contexts, valid, lr, key,
                     n_neg):
    """Skip-gram negative sampling (reference: SkipGram.java iterateSample,
    negative-sampling branch). centers/contexts: int32[B] padded to a multiple
    of CHUNK; valid: float32[B] 0/1 pair validity."""
    B = centers.shape[0]
    d = syn0.shape[1]
    negs = unigram[jax.random.randint(key, (B, n_neg), 0, unigram.shape[0])]
    cs, os_, vs, ns = _chunked(centers, contexts, valid, negs)

    def body(carry, args):
        syn0, syn1neg = carry
        c, o, val, neg = args
        v = syn0[c]                                     # C,D
        uo = syn1neg[o]                                 # C,D
        un = syn1neg[neg]                               # C,K,D
        pos_f = jax.nn.sigmoid(jnp.sum(v * uo, -1))
        g_pos = (1.0 - pos_f) * lr * val
        neg_f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", v, un))
        # word2vec skips a negative that equals the positive target word
        not_target = (neg != o[:, None]).astype(jnp.float32)
        g_neg = -neg_f * lr * val[:, None] * not_target  # label 0
        dv = g_pos[:, None] * uo + jnp.einsum("bk,bkd->bd", g_neg, un)
        duo = g_pos[:, None] * v
        dun = (g_neg[..., None] * v[:, None, :]).reshape(-1, d)
        neg_flat = neg.reshape(-1)
        inv0 = _inv_counts(syn0.shape[0], c, val)
        inv1 = _inv_counts(syn1neg.shape[0], jnp.concatenate([o, neg_flat]))
        syn0 = syn0.at[c].add(dv * inv0[c][:, None])
        syn1neg = syn1neg.at[o].add(duo * inv1[o][:, None])
        syn1neg = syn1neg.at[neg_flat].add(dun * inv1[neg_flat][:, None])
        return (syn0, syn1neg), None

    (syn0, syn1neg), _ = jax.lax.scan(body, (syn0, syn1neg), (cs, os_, vs, ns))
    return syn0, syn1neg


@functools.partial(jax.jit, donate_argnums=(0, 1))
def skipgram_hs_step(syn0, syn1, centers, codes, points, mask, valid, lr):
    """Hierarchical-softmax branch (reference: SkipGram.java iterateSample HS
    loop). codes/points/mask: [B, L] padded to max code length."""
    d = syn0.shape[1]
    cs, cds, pts, ms, vs = _chunked(centers, codes, points, mask, valid)

    def body(carry, args):
        syn0, syn1 = carry
        c, code, point, m, val = args
        m = m * val[:, None]
        v = syn0[c]                                     # C,D
        u = syn1[point]                                 # C,L,D
        f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", v, u))
        g = (1.0 - code - f) * lr * m                   # word2vec HS gradient
        dv = jnp.einsum("bl,bld->bd", g, u)
        du = (g[..., None] * v[:, None, :]).reshape(-1, d)
        pts_flat = point.reshape(-1)
        inv0 = _inv_counts(syn0.shape[0], c, val)
        inv1 = _inv_counts(syn1.shape[0], pts_flat, m.reshape(-1))
        syn0 = syn0.at[c].add(dv * inv0[c][:, None])
        syn1 = syn1.at[pts_flat].add(du * inv1[pts_flat][:, None])
        return (syn0, syn1), None

    (syn0, syn1), _ = jax.lax.scan(body, (syn0, syn1), (cs, cds, pts, ms, vs))
    return syn0, syn1


@functools.partial(jax.jit, donate_argnums=(0, 1), static_argnames=("n_neg",))
def cbow_ns_step(syn0, syn1neg, unigram, context_idx, context_mask, centers,
                 valid, lr, key, n_neg):
    """CBOW negative sampling (reference: CBOW.java — mean of window vectors
    predicts the center; gradient spread back over the window).
    context_idx: int32[B, W] (padded), context_mask: [B, W]."""
    B, W = context_idx.shape
    d = syn0.shape[1]
    negs = unigram[jax.random.randint(key, (B, n_neg), 0, unigram.shape[0])]
    ctxs, cms, cs, vs, ns = _chunked(context_idx, context_mask, centers, valid,
                                     negs)

    def body(carry, args):
        syn0, syn1neg = carry
        context_idx, context_mask, centers, val, neg = args
        context_mask = context_mask * val[:, None]
        ctx = syn0[context_idx]                         # C,W,D
        denom = jnp.maximum(context_mask.sum(-1, keepdims=True), 1.0)
        h = jnp.einsum("bwd,bw->bd", ctx, context_mask) / denom
        uo = syn1neg[centers]
        un = syn1neg[neg]
        pos_f = jax.nn.sigmoid(jnp.sum(h * uo, -1))
        g_pos = (1.0 - pos_f) * lr * val
        neg_f = jax.nn.sigmoid(jnp.einsum("bd,bkd->bk", h, un))
        # word2vec skips a negative that equals the positive target word
        not_target = (neg != centers[:, None]).astype(jnp.float32)
        g_neg = -neg_f * lr * val[:, None] * not_target
        dh = g_pos[:, None] * uo + jnp.einsum("bk,bkd->bd", g_neg, un)
        duo = g_pos[:, None] * h
        dun = (g_neg[..., None] * h[:, None, :]).reshape(-1, d)
        dctx = ((dh / denom)[:, None, :] * context_mask[..., None]).reshape(-1, d)
        ctx_flat = context_idx.reshape(-1)
        neg_flat = neg.reshape(-1)
        inv0 = _inv_counts(syn0.shape[0], ctx_flat, context_mask.reshape(-1))
        inv1 = _inv_counts(syn1neg.shape[0], jnp.concatenate([centers, neg_flat]))
        syn0 = syn0.at[ctx_flat].add(dctx * inv0[ctx_flat][:, None])
        syn1neg = syn1neg.at[centers].add(duo * inv1[centers][:, None])
        syn1neg = syn1neg.at[neg_flat].add(dun * inv1[neg_flat][:, None])
        return (syn0, syn1neg), None

    (syn0, syn1neg), _ = jax.lax.scan(body, (syn0, syn1neg),
                                      (ctxs, cms, cs, vs, ns))
    return syn0, syn1neg


@functools.partial(jax.jit, donate_argnums=(0, 1))
def cbow_hs_step(syn0, syn1, context_idx, context_mask, codes, points, mask,
                 valid, lr):
    d = syn0.shape[1]
    ctxs, cms, cds, pts, ms, vs = _chunked(context_idx, context_mask, codes,
                                           points, mask, valid)

    def body(carry, args):
        syn0, syn1 = carry
        context_idx, context_mask, code, point, m, val = args
        context_mask = context_mask * val[:, None]
        m = m * val[:, None]
        ctx = syn0[context_idx]
        denom = jnp.maximum(context_mask.sum(-1, keepdims=True), 1.0)
        h = jnp.einsum("bwd,bw->bd", ctx, context_mask) / denom
        u = syn1[point]
        f = jax.nn.sigmoid(jnp.einsum("bd,bld->bl", h, u))
        g = (1.0 - code - f) * lr * m
        dh = jnp.einsum("bl,bld->bd", g, u)
        du = (g[..., None] * h[:, None, :]).reshape(-1, d)
        dctx = ((dh / denom)[:, None, :] * context_mask[..., None]).reshape(-1, d)
        ctx_flat = context_idx.reshape(-1)
        pts_flat = point.reshape(-1)
        inv0 = _inv_counts(syn0.shape[0], ctx_flat, context_mask.reshape(-1))
        inv1 = _inv_counts(syn1.shape[0], pts_flat, m.reshape(-1))
        syn0 = syn0.at[ctx_flat].add(dctx * inv0[ctx_flat][:, None])
        syn1 = syn1.at[pts_flat].add(du * inv1[pts_flat][:, None])
        return (syn0, syn1), None

    (syn0, syn1), _ = jax.lax.scan(body, (syn0, syn1),
                                   (ctxs, cms, cds, pts, ms, vs))
    return syn0, syn1
