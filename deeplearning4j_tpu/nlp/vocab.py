"""Vocabulary construction + Huffman coding.

Reference: models/word2vec/wordstore/ — VocabWord (word + count + huffman
code/points), VocabConstructor.java (parallel tokenize+count, min word
frequency filter, special-token handling), HuffmanNode.java / Huffman tree
building that assigns each vocab word a binary code and inner-node point path
(used by hierarchical softmax).
"""
from __future__ import annotations

import heapq
from collections import Counter


class VocabWord:
    __slots__ = ("word", "count", "index", "codes", "points")

    def __init__(self, word, count=1):
        self.word = word
        self.count = count
        self.index = -1
        self.codes = []    # binary Huffman code (list of 0/1), root->leaf
        self.points = []   # inner-node indices along the path

    def __repr__(self):
        return f"VocabWord({self.word!r}, count={self.count})"


class VocabCache:
    """In-memory vocab (reference: wordstore/inmemory/AbstractCache.java)."""

    def __init__(self):
        self._words = {}          # word -> VocabWord
        self._by_index = []
        self.total_word_count = 0

    def add_token(self, vw: VocabWord):
        self._words[vw.word] = vw

    def contains_word(self, word):
        return word in self._words

    def word_for(self, word):
        return self._words.get(word)

    def word_frequency(self, word):
        vw = self._words.get(word)
        return vw.count if vw else 0

    def index_of(self, word):
        vw = self._words.get(word)
        return vw.index if vw else -1

    def word_at_index(self, idx):
        return self._by_index[idx].word

    def vocab_words(self):
        return list(self._by_index)

    def num_words(self):
        return len(self._words)

    def finalize_indices(self):
        """Sort by descending frequency and assign indices (the reference's
        convention: frequent words get low indices, which also drives the
        unigram-table negative sampler)."""
        self._by_index = sorted(self._words.values(),
                                key=lambda w: (-w.count, w.word))
        for i, vw in enumerate(self._by_index):
            vw.index = i
        self.total_word_count = sum(w.count for w in self._by_index)

    def __len__(self):
        return len(self._words)

    def __contains__(self, w):
        return w in self._words


class Huffman:
    """Builds the Huffman tree over vocab words and writes codes/points into
    each VocabWord (reference: models/word2vec/Huffman.java, HuffmanNode)."""

    MAX_CODE_LENGTH = 40

    def __init__(self, words):
        self.words = list(words)

    def build(self):
        n = len(self.words)
        if n == 0:
            return
        # classic two-array word2vec construction via heap
        heap = [(vw.count, i) for i, vw in enumerate(self.words)]
        heapq.heapify(heap)
        parent = {}
        binary = {}
        next_id = n
        while len(heap) > 1:
            c1, i1 = heapq.heappop(heap)
            c2, i2 = heapq.heappop(heap)
            parent[i1] = next_id
            parent[i2] = next_id
            binary[i1] = 0
            binary[i2] = 1
            heapq.heappush(heap, (c1 + c2, next_id))
            next_id += 1
        root = heap[0][1] if heap else None
        for i, vw in enumerate(self.words):
            code, points = [], []
            node = i
            while node != root:
                code.append(binary[node])
                node = parent[node]
                points.append(node - n)  # inner-node id, 0-based
            code.reverse()
            points.reverse()
            vw.codes = code[: self.MAX_CODE_LENGTH]
            vw.points = points[: self.MAX_CODE_LENGTH]
        return self


class VocabConstructor:
    """Tokenize + count + filter (reference:
    wordstore/VocabConstructor.java — buildJointVocabulary; the reference
    parallelizes counting over threads, here a single Counter pass is already
    IO-bound)."""

    def __init__(self, tokenizer_factory=None, min_word_frequency=1,
                 stop_words=None):
        from .tokenization import DefaultTokenizerFactory
        self.tokenizer_factory = tokenizer_factory or DefaultTokenizerFactory()
        self.min_word_frequency = int(min_word_frequency)
        self.stop_words = set(stop_words or [])

    def build_vocab(self, sentences, build_huffman=True):
        counts = Counter()
        n_sentences = 0
        for s in sentences:
            n_sentences += 1
            for t in self.tokenizer_factory.create(s).get_tokens():
                if t and t not in self.stop_words:
                    counts[t] += 1
        cache = VocabCache()
        for w, c in counts.items():
            if c >= self.min_word_frequency:
                cache.add_token(VocabWord(w, c))
        cache.finalize_indices()
        if build_huffman:
            Huffman(cache.vocab_words()).build()
        return cache
