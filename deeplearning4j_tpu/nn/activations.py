"""Activation functions.

Capability parity with the reference's IActivation set (reference:
nd4j `org.nd4j.linalg.activations.Activation`, consumed throughout
deeplearning4j-nn — see e.g. nn/conf/NeuralNetConfiguration.java builder
`.activation(...)`). TPU-first design: plain jnp functions; derivatives come
from JAX autodiff rather than hand-written `backprop(in, epsilon)` pairs.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_REGISTRY: dict = {}


def register_activation(name):
    def deco(fn):
        _REGISTRY[name.lower()] = fn
        return fn
    return deco


def get_activation(name):
    """Resolve an activation by name (case-insensitive) or pass a callable through."""
    if callable(name):
        return name
    key = str(name).lower()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown activation '{name}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]


def activation_names():
    return sorted(_REGISTRY)


@register_activation("identity")
@register_activation("linear")
def identity(x):
    return x


@register_activation("relu")
def relu(x):
    return jax.nn.relu(x)


@register_activation("leakyrelu")
def leakyrelu(x, alpha=0.01):
    return jax.nn.leaky_relu(x, negative_slope=alpha)


@register_activation("tanh")
def tanh(x):
    return jnp.tanh(x)


@register_activation("sigmoid")
def sigmoid(x):
    return jax.nn.sigmoid(x)


@register_activation("softmax")
def softmax(x):
    return jax.nn.softmax(x, axis=-1)


@register_activation("softplus")
def softplus(x):
    return jax.nn.softplus(x)


@register_activation("softsign")
def softsign(x):
    return jax.nn.soft_sign(x)


@register_activation("elu")
def elu(x, alpha=1.0):
    return jax.nn.elu(x, alpha=alpha)


@register_activation("selu")
def selu(x):
    return jax.nn.selu(x)


@register_activation("hardtanh")
def hardtanh(x):
    return jnp.clip(x, -1.0, 1.0)


@register_activation("hardsigmoid")
def hardsigmoid(x):
    return jnp.clip(0.2 * x + 0.5, 0.0, 1.0)


@register_activation("cube")
def cube(x):
    return x ** 3


@register_activation("rationaltanh")
def rationaltanh(x):
    # 1.7159 * tanh(2x/3) approximated rationally; the reference's RationalTanh
    # uses f(x) = 1.7159 * softsign-style rational approximation.
    a = jnp.abs(2.0 * x / 3.0)
    approx = jnp.sign(x) * (1.0 - 1.0 / (1.0 + a + a ** 2 + 1.41645 * a ** 4))
    return 1.7159 * approx


@register_activation("rectifiedtanh")
def rectifiedtanh(x):
    return jnp.maximum(0.0, jnp.tanh(x))


@register_activation("swish")
def swish(x):
    return jax.nn.silu(x)


@register_activation("gelu")
def gelu(x):
    return jax.nn.gelu(x)


@register_activation("relu6")
def relu6(x):
    return jax.nn.relu6(x)


@register_activation("mish")
def mish(x):
    return x * jnp.tanh(jax.nn.softplus(x))
