"""steps_per_execution: K optimizer steps inside ONE compiled executable.

The reference's training loop is a Java per-minibatch host loop
(optimize/solvers/StochasticGradientDescent.java:51-72 — fetch batch, one
gradient step, repeat), which SURVEY §7 marks as the thing to compile away.
Round 4 measured why: through a remote PJRT relay, per-step host dispatch
phases swing 1.3 ms ↔ 21 ms hours apart, so any small-model number timed
across K separate dispatches measures the relay, not the model.

This mixin rolls the loop INSIDE the executable: `lax.scan` over K
pre-staged device batches with the (params, opt_state, states, rng) carry
donated, so training pays ONE dispatch per K steps and the whole chain —
forward, backward, updater, BN stat update, rng split — stays on device.
Semantics are identical to K fit_batch calls: the rng chain splits the same
way, per-layer states thread sequentially, and scores come back per step.

TBPTT batches scan too (MultiLayerNetwork): each batch's windows flatten
into the scan with a per-window carry that resets at batch boundaries, and
a precomputed rng table replays exactly the splits the per-batch path would
have drawn. Configs the scan can't honor (non-SGD solvers, ragged TBPTT
windows, gradient-hungry listeners, mismatched shapes within a group) fall
back to per-batch steps.

Each class provides:
  _prep_batch(ds)    -> per-step pytree of device arrays (masks may be None)
  _scan_loss(p, states, x, y, rng, mask, lmask) -> (score, new_states)
  _multi_step_mode(prepped) -> "std" | "tbptt" | None

Listeners fire once per execution with the advanced iteration count — a
well-defined K-step cadence; per-step scores stay available on device as
`last_scores`.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import optax


class MultiStepTrainable:
    def set_update_sharding(self, zero):
        """Install (or with None, remove) a ZeRO-1 sharded update
        (parallel.zero.ZeroUpdater): updater state and the parameter update
        partition over the mesh's data axis — reduce-scatter grads,
        per-shard optax update, all-gather fresh params into the forward
        (arXiv 2004.13336; ROADMAP item 4). Existing updater state carries
        over exactly (canonical<->sharded conversion), so enabling,
        resuming from a checkpoint, or changing replica count never resets
        momentum. Clears the jit cache so every train path — including the
        scanned multi-step executables this mixin owns — re-traces with the
        sharded update fused. Shared by MultiLayerNetwork and
        ComputationGraph (each contributes its own _build_updater)."""
        old = self._zero
        if old is not None and self.opt_state is not None:
            self.opt_state = old.to_canonical(self.opt_state, self.params)
        self._zero = zero
        if self.params is not None:
            self._build_updater(init_state=False)
            if zero is not None and self.opt_state is not None:
                self.opt_state = zero.from_canonical(self.opt_state,
                                                     self.params)
        self._jit_cache.clear()
        return self

    # ------------------------------------------------- int8 serving weights
    def quantize_weights(self, dtype="int8"):
        """Per-channel symmetric int8 weight quantization for SERVING
        (nn/quant.py, ROADMAP item 3): eligible weight leaves (floating,
        ndim >= 2) are replaced in `self.params` by their int8 codes, and
        every inference executable — output(), the decode engine's
        step/prefill, rnn_time_step — traces a fused dequant
        (`codes * per-channel scale`) on the way into the matmul, so HBM
        holds and reads ~4x fewer weight bytes. The f32 originals are kept
        as a host-side numpy backup (`dequantize_weights` restores them;
        serializers write f32 zips). Training paths refuse a quantized
        model. Shared by MultiLayerNetwork and ComputationGraph."""
        if getattr(self, "_wq", None) is not None:
            return self
        if self.params is None:
            self.init()
        from .quant import WeightQuant
        self._wq, self.params = WeightQuant.build(self.params, dtype=dtype)
        self._jit_cache.clear()
        return self

    def dequantize_weights(self):
        """Undo quantize_weights from the host-side f32 backup (used when a
        deploy-time parity gate breaches)."""
        wq = getattr(self, "_wq", None)
        if wq is None:
            return self
        self.params = wq.restore_params(self.params)
        self._wq = None
        self._jit_cache.clear()
        return self

    def _dequant_params(self, params):
        """Traced at the top of every inference executable: int8 code
        leaves widen through their per-channel scales (closure constants);
        identity for unquantized models."""
        wq = getattr(self, "_wq", None)
        return params if wq is None else wq.dequant(params)

    def _check_trainable(self):
        if getattr(self, "_wq", None) is not None:
            raise RuntimeError(
                "weights are int8-quantized (serving-only); call "
                "dequantize_weights() before training")

    def generate(self, prompt_ids, max_new_tokens=20, stop_id=None,
                 max_len=None, sampler=None):
        """KV-cache autoregressive decode (decode/engine.py): feeds
        `prompt_ids` (token ids; one-hot happens inside the compiled
        prefill), then emits up to `max_new_tokens` ids one fixed-shape
        decode step at a time — greedy by default, token-for-token identical
        to re-running `output` on the growing sequence, without the O(T²)
        re-forward. `sampler` (a decode.SamplerConfig) switches to seeded
        temperature/top-k/top-p sampling; the params ride as array operands
        of the SAME executable, so swinging them between calls never
        recompiles. The engine (and its compiled executables) is cached on
        the model; pass `max_len` to size the cache (default: prompt + new
        tokens, rounded up). Shared by MultiLayerNetwork and
        ComputationGraph (single-input/single-output sequence graphs;
        anything without per-token semantics raises
        decode.DecodeUnsupported)."""
        from ..decode.engine import DecodeEngine, bucket_for_len
        n = len(list(prompt_ids))
        need = n + int(max_new_tokens) + 1
        eng = getattr(self, "_decode_engine", None)
        if eng is None or eng.capacity < need or eng.model is not self:
            cap = int(max_len) if max_len is not None \
                else bucket_for_len(need, 1 << 30)
            eng = self._decode_engine = DecodeEngine(self, slots=1,
                                                     max_len=cap)
        return eng.generate(prompt_ids, max_new_tokens, stop_id=stop_id,
                            sampler=sampler)

    def _make_multi_step(self):
        tx = self._tx

        def multi_step(params, opt_state, states, rng, stacked):
            def body(carry, batch):
                params, opt_state, states, rng = carry
                x, y, mask, lmask = batch
                rng, step_rng = jax.random.split(rng)
                (score, new_states), grads = jax.value_and_grad(
                    self._scan_loss, has_aux=True)(
                        params, states, x, y, step_rng, mask, lmask)
                grads = self._normalize_grads(grads)
                updates, opt_state = tx.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                return (params, opt_state, new_states, rng), score

            (params, opt_state, states, rng), scores = jax.lax.scan(
                body, (params, opt_state, states, rng), stacked)
            return params, opt_state, states, rng, scores

        # the batch stack is NOT donated: callers may reuse prepared groups
        return jax.jit(multi_step, donate_argnums=(0, 1, 2, 3))

    def prepare_steps(self, group):
        """Stack a list of same-shaped DataSets into one device-resident
        execution plan for `fit_prepared`, or None when this group can't
        scan. The plan is reusable: its batch leaves are never donated
        (re-running a TBPTT plan replays the same rng table; the std plan
        draws fresh rngs from the carried chain)."""
        if self.params is None:
            self.init()
        self._check_trainable()
        # decide eligibility from the FIRST batch alone before paying the
        # host->device transfer for the whole group — an ineligible config
        # would otherwise re-prep (and re-transfer) every batch in the
        # fit_batch fallback
        first = self._prep_batch(group[0])
        mode = self._multi_step_mode(first)
        if mode is None:
            return None
        prepped = [first] + [self._prep_batch(ds) for ds in group[1:]]
        try:
            if mode == "std":
                stacked = jax.tree_util.tree_map(lambda *a: jnp.stack(a),
                                                 *prepped)
                return "std", stacked, len(group)
            return self._prepare_tbptt(prepped)   # MLN-only; may be None
        except ValueError:
            return None  # shape or mask-structure mismatch within the group

    def fit_prepared(self, prepared):
        """Run one compiled multi-step execution over a `prepare_steps`
        plan."""
        mode, stacked, K = prepared
        if mode == "std":
            if "multi" not in self._jit_cache:
                from ..telemetry.xla import timed_first_call
                self._jit_cache["multi"] = timed_first_call(
                    self._make_multi_step(), "multi_step:std")
            (self.params, self.opt_state, self.states, self._rng,
             scores) = self._jit_cache["multi"](
                self.params, self.opt_state, self.states, self._rng, stacked)
        else:
            scores = self._run_prepared_tbptt(stacked, K)
        self.last_scores = scores          # [K] device array
        self.score_value = scores[-1]      # device scalar; syncs lazily
        self.iteration_count += int(K)
        B = jax.tree_util.tree_leaves(stacked)[0].shape[1]
        for listener in self.listeners:
            if hasattr(listener, "record_batch_size"):
                listener.record_batch_size(int(K) * int(B))
            listener.iteration_done(self, self.iteration_count)
        return self

    def _fit_grouped(self, it, K, prepare=None, run=None, fallback=None):
        """One epoch: full groups of K go through the compiled scan; ragged
        tails and incompatible groups fall back to per-batch steps. The
        prepare/run/fallback hooks default to this model's own methods;
        ShardedTrainer reuses the same accumulation loop with its sharded
        prepare and mesh-scoped run."""
        prepare = prepare or self.prepare_steps
        run = run or (lambda prepared, group: self.fit_prepared(prepared))
        fallback = fallback or self.fit_batch
        group = []

        def flush(group):
            prepared = prepare(group) if len(group) == K else None
            if prepared is not None:
                run(prepared, group)
            else:
                for ds in group:
                    fallback(ds)

        for ds in it:
            group.append(ds)
            if len(group) == K:
                flush(group)
                group = []
        if group:
            flush(group)

    def _listeners_need_gradients(self):
        return any(getattr(l, "wants_gradients", False) for l in self.listeners)

    def _prepare_tbptt(self, prepped):
        return None  # ComputationGraph: TBPTT groups fall back to fit_batch
