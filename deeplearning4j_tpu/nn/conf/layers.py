"""Layer configuration classes (the builder-DSL vocabulary).

Capability parity with reference nn/conf/layers/* (25 config classes; see
SURVEY.md §2.1). Each config is a serializable dataclass; hyperparameters left
as None inherit the global values set on the NeuralNetConfiguration builder
(reference behavior: per-layer override of global hyperparams,
nn/conf/NeuralNetConfiguration.java:484 Builder).

Runtime semantics live in deeplearning4j_tpu/nn/layers/* — configs only carry
hyperparameters and shape logic (get_output_type / infer n_in), mirroring the
reference's config/impl split.
"""
from __future__ import annotations

from dataclasses import dataclass, field, asdict, fields as dc_fields

from .inputs import (InputType, FeedForwardInputType, RecurrentInputType,
                     ConvolutionalInputType, ConvolutionalFlatInputType)

_LAYER_REGISTRY: dict = {}


def register_layer_conf(cls):
    _LAYER_REGISTRY[cls.__name__] = cls
    return cls


def layer_conf_from_dict(d):
    d = dict(d)
    cls = _LAYER_REGISTRY[d.pop("type")]
    kw = {}
    names = {f.name for f in dc_fields(cls)}
    for k, v in d.items():
        if k in names:
            kw[k] = v
    obj = cls(**kw)
    if "updater" in d and d["updater"] is not None and isinstance(d["updater"], dict):
        from ..updaters import updater_from_dict
        obj.updater = updater_from_dict(d["updater"])
    return obj


# Global hyperparameters a layer can override (reference: NeuralNetConfiguration
# Builder fields cloned into each layer conf).
_INHERITED = ("activation", "weight_init", "bias_init", "l1", "l2", "l1_bias",
              "l2_bias", "dropout", "updater", "gradient_normalization",
              "gradient_normalization_threshold", "dist")


@dataclass
class BaseLayerConf:
    name: str | None = None
    activation: str | None = None
    weight_init: str | None = None
    bias_init: float | None = None
    dist: dict | None = None
    l1: float | None = None
    l2: float | None = None
    l1_bias: float | None = None
    l2_bias: float | None = None
    dropout: float | None = None
    updater: object | None = None
    gradient_normalization: str | None = None
    gradient_normalization_threshold: float | None = None

    def apply_global_defaults(self, g: dict):
        for k in _INHERITED:
            if getattr(self, k, None) is None and g.get(k) is not None:
                setattr(self, k, g[k])
        if self.activation is None:
            self.activation = "sigmoid"
        if self.weight_init is None:
            self.weight_init = "xavier"
        if self.bias_init is None:
            self.bias_init = 0.0
        for k in ("l1", "l2", "l1_bias", "l2_bias"):
            if getattr(self, k) is None:
                setattr(self, k, 0.0)
        if self.dropout is None:
            self.dropout = 0.0

    # ---- shape logic ------------------------------------------------------
    def get_output_type(self, input_type):
        raise NotImplementedError

    def set_n_in(self, input_type):
        """Infer n_in from the incoming InputType when unset."""
        if hasattr(self, "n_in") and getattr(self, "n_in", None) in (None, 0):
            self.n_in = input_type.flat_size()

    # ---- serde ------------------------------------------------------------
    def to_dict(self):
        d = {}
        for f in dc_fields(self):
            v = getattr(self, f.name)
            if v is None:
                continue
            if hasattr(v, "to_dict"):
                v = v.to_dict()
            d[f.name] = v
        d["type"] = type(self).__name__
        return d


@dataclass
class FeedForwardLayerConf(BaseLayerConf):
    n_in: int | None = None
    n_out: int | None = None

    def get_output_type(self, input_type):
        return InputType.feed_forward(self.n_out)


@register_layer_conf
@dataclass
class DenseLayer(FeedForwardLayerConf):
    """Fully connected layer (reference: nn/conf/layers/DenseLayer.java).
    On [b, t, f] input it applies per-timestep (time-distributed; one batched
    gemm) and stays recurrent — beyond the reference, which demands
    RnnToFeedForward wrapping."""

    def get_output_type(self, input_type):
        if isinstance(input_type, RecurrentInputType):
            return InputType.recurrent(self.n_out)
        return InputType.feed_forward(self.n_out)


@register_layer_conf
@dataclass
class OutputLayer(FeedForwardLayerConf):
    """Output layer with integrated loss (reference: nn/conf/layers/OutputLayer.java)."""
    loss: str = "MCXENT"


@register_layer_conf
@dataclass
class RnnOutputLayer(FeedForwardLayerConf):
    """Per-timestep output layer for sequences [b,t,f]
    (reference: nn/conf/layers/RnnOutputLayer.java)."""
    loss: str = "MCXENT"

    def get_output_type(self, input_type):
        return InputType.recurrent(self.n_out)


@register_layer_conf
@dataclass
class LossLayer(BaseLayerConf):
    """Parameterless loss layer (reference: nn/conf/layers/LossLayer.java)."""
    loss: str = "MSE"
    n_in: int | None = None
    n_out: int | None = None

    def get_output_type(self, input_type):
        return input_type


@register_layer_conf
@dataclass
class CenterLossOutputLayer(FeedForwardLayerConf):
    """Output layer + center loss on penultimate features
    (reference: nn/conf/layers/CenterLossOutputLayer.java,
    nn/layers/training/CenterLossOutputLayer.java)."""
    loss: str = "MCXENT"
    alpha: float = 0.05
    lambda_: float = 2e-4


@register_layer_conf
@dataclass
class EmbeddingLayer(FeedForwardLayerConf):
    """Index -> vector lookup (reference: nn/conf/layers/EmbeddingLayer.java).
    Input: integer indices [b] or one-hot [b, n_in]."""
    has_bias: bool = True


@register_layer_conf
@dataclass
class ConvolutionLayer(FeedForwardLayerConf):
    """2-D convolution, NHWC (reference: nn/conf/layers/ConvolutionLayer.java;
    runtime im2col path at nn/layers/convolution/ConvolutionLayer.java:265-310 is
    replaced by a single XLA conv_general_dilated that maps onto the MXU)."""
    kernel_size: tuple = (5, 5)
    stride: tuple = (1, 1)
    padding: tuple = (0, 0)
    convolution_mode: str = "truncate"  # truncate | same | strict
    dilation: tuple = (1, 1)
    has_bias: bool = True

    def set_n_in(self, input_type):
        if self.n_in in (None, 0) and isinstance(input_type, (ConvolutionalInputType, ConvolutionalFlatInputType)):
            self.n_in = input_type.channels

    def get_output_type(self, input_type):
        h, w = input_type.height, input_type.width
        oh, ow = conv_output_size(h, w, self.kernel_size, self.stride, self.padding,
                                  self.convolution_mode, self.dilation)
        return InputType.convolutional(oh, ow, self.n_out)


@dataclass
class _NoActivationConf(BaseLayerConf):
    """Layers with no activation of their own ignore the global activation."""

    def apply_global_defaults(self, g):
        explicit = self.activation
        super().apply_global_defaults(g)
        if explicit is None:
            self.activation = "identity"


@register_layer_conf
@dataclass
class SubsamplingLayer(_NoActivationConf):
    """Spatial pooling (reference: nn/conf/layers/SubsamplingLayer.java)."""
    pooling_type: str = "max"  # max | avg | sum | pnorm
    kernel_size: tuple = (2, 2)
    stride: tuple = (2, 2)
    padding: tuple = (0, 0)
    convolution_mode: str = "truncate"
    pnorm: int = 2

    def get_output_type(self, input_type):
        h, w = input_type.height, input_type.width
        oh, ow = conv_output_size(h, w, self.kernel_size, self.stride, self.padding,
                                  self.convolution_mode)
        return InputType.convolutional(oh, ow, input_type.channels)


def _norm_set_n_in(self, input_type):
    """Shared n_in inference for the normalization confs: channel count for
    CNN activations, feature size otherwise; n_out mirrors n_in."""
    if self.n_in in (None, 0):
        if isinstance(input_type, ConvolutionalInputType):
            self.n_in = input_type.channels
        else:
            self.n_in = input_type.flat_size()
    self.n_out = self.n_in


@register_layer_conf
@dataclass
class LayerNormalization(BaseLayerConf):
    """Layer norm over the feature (last) axis — NEW capability beyond the
    reference's 2017 layer set (no LayerNormalization.java exists at v0.7.3);
    added because the transformer family (zoo.transformer_lm) needs it.
    Stateless (no running statistics), works on [b,f], [b,t,f], [b,h,w,c]."""
    n_in: int | None = None
    n_out: int | None = None
    eps: float = 1e-5

    def apply_global_defaults(self, g):
        explicit = self.activation
        super().apply_global_defaults(g)
        if explicit is None:
            self.activation = "identity"

    set_n_in = _norm_set_n_in

    def get_output_type(self, input_type):
        return input_type


@register_layer_conf
@dataclass
class BatchNormalization(BaseLayerConf):
    """Batch norm over feature/channel axis (reference:
    nn/conf/layers/BatchNormalization.java, runtime
    nn/layers/normalization/BatchNormalization.java:55)."""
    n_in: int | None = None
    n_out: int | None = None
    decay: float = 0.9
    eps: float = 1e-5
    gamma: float = 1.0
    beta: float = 0.0
    lock_gamma_beta: bool = False

    def apply_global_defaults(self, g):
        explicit = self.activation
        super().apply_global_defaults(g)
        if explicit is None:
            self.activation = "identity"

    set_n_in = _norm_set_n_in

    def get_output_type(self, input_type):
        return input_type


@register_layer_conf
@dataclass
class LocalResponseNormalization(_NoActivationConf):
    """Cross-channel LRN (reference: nn/conf/layers/LocalResponseNormalization.java)."""
    k: float = 2.0
    n: float = 5.0
    alpha: float = 1e-4
    beta: float = 0.75

    def get_output_type(self, input_type):
        return input_type


@dataclass
class BaseRecurrentConf(FeedForwardLayerConf):
    def get_output_type(self, input_type):
        return InputType.recurrent(self.n_out)


@register_layer_conf
@dataclass
class SelfAttentionLayer(BaseRecurrentConf):
    """Multi-head self-attention over a sequence [b,t,f] — NEW capability with
    no reference counterpart (SURVEY.md §5: the reference has no attention).
    Runs flash-style blockwise attention on one device; the sequence-parallel
    long-context variant is parallel.ring_attention.ring_attention, applied to
    the same Q/K/V projections. use_pallas=True routes the unmasked forward
    through the hand-tiled Pallas kernel (kernels/flash_attention.py;
    interpret mode on CPU, Mosaic on TPU)."""
    n_heads: int = 4
    causal: bool = False
    block_size: int = 256
    use_pallas: bool = False
    # dropout on the attention OUTPUT (post-softmax·V, pre-Wo) — the layer's
    # inherited `dropout` drops the INPUT like every reference layer
    attention_dropout: float = 0.0


@register_layer_conf
@dataclass
class GravesLSTM(BaseRecurrentConf):
    """LSTM with peephole connections (reference: nn/conf/layers/GravesLSTM.java,
    runtime nn/layers/recurrent/LSTMHelpers.java — the per-timestep Java gemm
    loop at :172-174 becomes one lax.scan whose body is a single fused gemm)."""
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"


@register_layer_conf
@dataclass
class LSTM(BaseRecurrentConf):
    """LSTM without peepholes (cuDNN-compatible formulation)."""
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"


@register_layer_conf
@dataclass
class GravesBidirectionalLSTM(BaseRecurrentConf):
    """Bidirectional peephole LSTM (reference:
    nn/conf/layers/GravesBidirectionalLSTM.java). Output = concat(fwd, bwd) so
    output size is 2*n_out? No — reference sums into n_out via separate
    directions each of size n_out and adds; here we follow the reference:
    forward and backward nets each produce n_out and outputs are summed."""
    forget_gate_bias_init: float = 1.0
    gate_activation: str = "sigmoid"


@register_layer_conf
@dataclass
class ActivationLayer(BaseLayerConf):
    """Applies an activation only (reference: nn/conf/layers/ActivationLayer.java)."""

    def get_output_type(self, input_type):
        return input_type


@register_layer_conf
@dataclass
class DropoutLayer(_NoActivationConf):
    """Dropout as its own layer (reference: nn/conf/layers/DropoutLayer.java)."""

    def get_output_type(self, input_type):
        return input_type


@register_layer_conf
@dataclass
class MixtureOfExpertsLayer(FeedForwardLayerConf):
    """Mixture-of-experts feed-forward block — NEW capability beyond the
    reference (no MoE exists at v0.7.3; SURVEY.md §2.4 lists expert
    parallelism as absent upstream). Router: softmax top-k gating over
    n_experts; each expert is a 2-layer FFN (n_in -> hidden -> n_out).
    Compute is dense over the expert axis (every expert runs, gates weight
    the mix) so the whole block is one einsum chain that GSPMD partitions
    over a mesh axis when the expert-indexed weights are sharded
    P("model", ...) — that sharding IS expert parallelism. Works on [b, f]
    and time-distributed [b, t, f]."""
    n_experts: int = 4
    hidden_mult: int = 2
    top_k: int = 2  # gates outside top-k are zeroed (renormalized)

    def get_output_type(self, input_type):
        if isinstance(input_type, RecurrentInputType):
            return InputType.recurrent(self.n_out)
        return InputType.feed_forward(self.n_out)


@register_layer_conf
@dataclass
class GlobalPoolingLayer(_NoActivationConf):
    """Pool over time (rnn) or space (cnn) to fixed-size vectors
    (reference: nn/conf/layers/GlobalPoolingLayer.java, runtime
    nn/layers/pooling/GlobalPoolingLayer.java). Mask-aware."""
    pooling_type: str = "max"  # max | avg | sum | pnorm
    pnorm: int = 2
    collapse_dimensions: bool = True

    def get_output_type(self, input_type):
        if isinstance(input_type, RecurrentInputType):
            return InputType.feed_forward(input_type.size)
        if isinstance(input_type, ConvolutionalInputType):
            return InputType.feed_forward(input_type.channels)
        return input_type


@register_layer_conf
@dataclass
class ZeroPaddingLayer(_NoActivationConf):
    """Spatial zero padding (reference: nn/conf/layers/ZeroPaddingLayer.java)."""
    pad_top: int = 0
    pad_bottom: int = 0
    pad_left: int = 0
    pad_right: int = 0

    def get_output_type(self, input_type):
        return InputType.convolutional(input_type.height + self.pad_top + self.pad_bottom,
                                       input_type.width + self.pad_left + self.pad_right,
                                       input_type.channels)


@register_layer_conf
@dataclass
class AutoEncoder(FeedForwardLayerConf):
    """Denoising autoencoder (reference: nn/conf/layers/AutoEncoder.java,
    runtime nn/layers/feedforward/autoencoder/AutoEncoder.java).
    Pretrain layer: reconstruction via tied decoder params."""
    corruption_level: float = 0.3
    sparsity: float = 0.0
    loss: str = "MSE"


@register_layer_conf
@dataclass
class RBM(FeedForwardLayerConf):
    """Restricted Boltzmann machine trained by contrastive divergence
    (reference: nn/conf/layers/RBM.java, runtime
    nn/layers/feedforward/rbm/RBM.java)."""
    visible_unit: str = "binary"   # binary | gaussian
    hidden_unit: str = "binary"    # binary | rectified | gaussian | softmax
    k: int = 1
    sparsity: float = 0.0
    loss: str = "MSE"


@register_layer_conf
@dataclass
class VariationalAutoencoder(FeedForwardLayerConf):
    """VAE (reference: nn/conf/layers/variational/VariationalAutoencoder.java,
    runtime nn/layers/variational/VariationalAutoencoder.java, 1063 LoC).
    n_out = latent size. Supervised use: forward = encoder mean (matches the
    reference where the VAE acts as a feedforward layer outputting z-mean)."""
    encoder_layer_sizes: tuple = (100,)
    decoder_layer_sizes: tuple = (100,)
    reconstruction_distribution: str = "gaussian"  # gaussian | bernoulli
    pzx_activation: str = "identity"
    num_samples: int = 1


# ---------------------------------------------------------------------------


def conv_output_size(h, w, kernel, stride, padding, mode="truncate", dilation=(1, 1)):
    kh = kernel[0] + (kernel[0] - 1) * (dilation[0] - 1)
    kw = kernel[1] + (kernel[1] - 1) * (dilation[1] - 1)
    if mode == "same":
        return ((h + stride[0] - 1) // stride[0], (w + stride[1] - 1) // stride[1])
    oh = (h + 2 * padding[0] - kh) // stride[0] + 1
    ow = (w + 2 * padding[1] - kw) // stride[1] + 1
    if mode == "strict" and ((h + 2 * padding[0] - kh) % stride[0] != 0 or
                             (w + 2 * padding[1] - kw) % stride[1] != 0):
        raise ValueError("ConvolutionMode.Strict: input size does not tile exactly "
                         f"(h={h}, w={w}, kernel={kernel}, stride={stride}, padding={padding})")
    return oh, ow
