"""ComputationGraph configuration: GraphBuilder DSL + graph vertices.

Capability parity with reference nn/conf/ComputationGraphConfiguration.java
(GraphBuilder :406, addLayer :517, addInputs :553) and the vertex configs in
nn/conf/graph/: ElementWiseVertex, MergeVertex, SubsetVertex, StackVertex,
UnstackVertex, ScaleVertex, L2NormalizeVertex, L2Vertex, PreprocessorVertex,
LayerVertex, plus rnn/{LastTimeStepVertex, DuplicateToTimeSeriesVertex}.

Vertices are pure functions over lists of input arrays — they trace into the
same XLA computation as the layers.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field

import jax.numpy as jnp

from . import layers as L
from .inputs import InputType
from .configuration import (BackpropType, OptimizationAlgorithm, default_preprocessor,
                            type_after_preprocessor)
from .preprocessors import preprocessor_from_dict
from ..updaters import Sgd

_VERTEX_REGISTRY: dict = {}


def register_vertex(cls):
    _VERTEX_REGISTRY[cls.__name__] = cls
    return cls


def vertex_from_dict(d):
    d = dict(d)
    cls = _VERTEX_REGISTRY[d.pop("type")]
    return cls(**d)


class BaseVertexConf:
    """Non-layer DAG node (reference: nn/conf/graph/GraphVertex.java)."""

    def n_params(self):
        return 0

    def apply(self, inputs, masks=None):
        raise NotImplementedError

    def output_type(self, input_types):
        raise NotImplementedError

    def output_mask(self, masks):
        for m in (masks or []):
            if m is not None:
                return m
        return None

    def to_dict(self):
        d = dict(self.__dict__)
        d["type"] = type(self).__name__
        return d


@register_vertex
class ElementWiseVertex(BaseVertexConf):
    """Add/Subtract/Product/Average/Max of equal-shaped inputs
    (reference: nn/conf/graph/ElementWiseVertex.java)."""

    def __init__(self, op="add"):
        self.op = op

    def apply(self, inputs, masks=None):
        op = self.op
        if op == "add":
            out = inputs[0]
            for x in inputs[1:]:
                out = out + x
            return out
        if op == "subtract":
            return inputs[0] - inputs[1]
        if op == "product":
            out = inputs[0]
            for x in inputs[1:]:
                out = out * x
            return out
        if op == "average":
            return sum(inputs) / len(inputs)
        if op == "max":
            out = inputs[0]
            for x in inputs[1:]:
                out = jnp.maximum(out, x)
            return out
        raise ValueError(f"Unknown elementwise op {self.op}")

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
class MergeVertex(BaseVertexConf):
    """Concatenate along the feature/channel (last) axis
    (reference: nn/conf/graph/MergeVertex.java)."""

    def __init__(self):
        pass

    def apply(self, inputs, masks=None):
        return jnp.concatenate(inputs, axis=-1)

    def output_type(self, input_types):
        t0 = input_types[0]
        if t0.kind == "ff":
            return InputType.feed_forward(sum(t.size for t in input_types))
        if t0.kind == "recurrent":
            return InputType.recurrent(sum(t.size for t in input_types))
        if t0.kind == "cnn":
            return InputType.convolutional(t0.height, t0.width,
                                           sum(t.channels for t in input_types))
        return t0


@register_vertex
class SubsetVertex(BaseVertexConf):
    """Select feature range [from, to] inclusive (reference:
    nn/conf/graph/SubsetVertex.java)."""

    def __init__(self, from_index, to_index):
        self.from_index = int(from_index)
        self.to_index = int(to_index)

    def apply(self, inputs, masks=None):
        return inputs[0][..., self.from_index:self.to_index + 1]

    def output_type(self, input_types):
        n = self.to_index - self.from_index + 1
        t = input_types[0]
        if t.kind == "recurrent":
            return InputType.recurrent(n)
        return InputType.feed_forward(n)


@register_vertex
class StackVertex(BaseVertexConf):
    """Stack inputs along the batch axis (reference: nn/conf/graph/StackVertex.java)."""

    def __init__(self):
        pass

    def apply(self, inputs, masks=None):
        return jnp.concatenate(inputs, axis=0)

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
class UnstackVertex(BaseVertexConf):
    """Take the i-th of n equal batch slices (reference:
    nn/conf/graph/UnstackVertex.java)."""

    def __init__(self, from_index, stack_size):
        self.from_index = int(from_index)
        self.stack_size = int(stack_size)

    def apply(self, inputs, masks=None):
        x = inputs[0]
        n = x.shape[0] // self.stack_size
        return x[self.from_index * n:(self.from_index + 1) * n]

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
class ScaleVertex(BaseVertexConf):
    """Multiply by a fixed scalar (reference: nn/conf/graph/ScaleVertex.java)."""

    def __init__(self, scale_factor=1.0):
        self.scale_factor = float(scale_factor)

    def apply(self, inputs, masks=None):
        return inputs[0] * self.scale_factor

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
class L2NormalizeVertex(BaseVertexConf):
    """x / ||x||_2 over the feature axis (reference:
    nn/conf/graph/L2NormalizeVertex.java)."""

    def __init__(self, eps=1e-8):
        self.eps = float(eps)

    def apply(self, inputs, masks=None):
        x = inputs[0]
        n = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True) + self.eps)
        return x / n

    def output_type(self, input_types):
        return input_types[0]


@register_vertex
class L2Vertex(BaseVertexConf):
    """Pairwise L2 distance between two inputs -> [b, 1]
    (reference: nn/conf/graph/L2Vertex.java)."""

    def __init__(self, eps=1e-8):
        self.eps = float(eps)

    def apply(self, inputs, masks=None):
        a, b = inputs[0], inputs[1]
        d = jnp.sqrt(jnp.sum((a - b) ** 2, axis=-1, keepdims=True) + self.eps)
        return d

    def output_type(self, input_types):
        return InputType.feed_forward(1)


@register_vertex
class PreprocessorVertex(BaseVertexConf):
    """Wraps an InputPreProcessor as a standalone vertex (reference:
    nn/conf/graph/PreprocessorVertex.java)."""

    def __init__(self, preprocessor=None):
        self.preprocessor = preprocessor if not isinstance(preprocessor, dict) \
            else preprocessor_from_dict(preprocessor)

    def apply(self, inputs, masks=None):
        m = masks[0] if masks else None
        return self.preprocessor(inputs[0], m)

    def output_type(self, input_types):
        return self.preprocessor.output_type(input_types[0])

    def to_dict(self):
        return {"type": "PreprocessorVertex",
                "preprocessor": self.preprocessor.to_dict()}


@register_vertex
class LastTimeStepVertex(BaseVertexConf):
    """[b,t,f] -> [b,f] taking the last unmasked step (reference:
    nn/conf/graph/rnn/LastTimeStepVertex.java)."""

    def __init__(self, mask_input=None):
        self.mask_input = mask_input

    def apply(self, inputs, masks=None):
        x = inputs[0]
        m = masks[0] if masks and masks[0] is not None else None
        if m is None:
            return x[:, -1]
        idx = jnp.maximum(jnp.sum(m > 0, axis=1).astype(jnp.int32) - 1, 0)
        return jnp.take_along_axis(x, idx[:, None, None], axis=1)[:, 0]

    def output_type(self, input_types):
        return InputType.feed_forward(input_types[0].size)

    def output_mask(self, masks):
        return None


@register_vertex
class DuplicateToTimeSeriesVertex(BaseVertexConf):
    """[b,f] -> [b,t,f] broadcast over the timesteps of a reference input
    (reference: nn/conf/graph/rnn/DuplicateToTimeSeriesVertex.java)."""

    def __init__(self, reference_input=None):
        self.reference_input = reference_input
        self._timesteps = None  # bound at runtime by the graph

    def apply(self, inputs, masks=None, timesteps=None):
        x = inputs[0]
        t = timesteps if timesteps is not None else self._timesteps
        return jnp.broadcast_to(x[:, None, :], (x.shape[0], t, x.shape[-1]))

    def output_type(self, input_types):
        return InputType.recurrent(input_types[0].flat_size())

    def to_dict(self):
        return {"type": "DuplicateToTimeSeriesVertex",
                "reference_input": self.reference_input}


# ---------------------------------------------------------------------------


@dataclass
class GraphVertexSpec:
    name: str
    kind: str                       # "input" | "layer" | "vertex"
    layer_conf: object = None       # for kind == "layer"
    vertex_conf: object = None      # for kind == "vertex"
    inputs: list = field(default_factory=list)
    preprocessor: object = None     # optional InputPreProcessor before a layer


@dataclass
class ComputationGraphConfiguration:
    vertices: dict = field(default_factory=dict)     # name -> GraphVertexSpec
    network_inputs: list = field(default_factory=list)
    network_outputs: list = field(default_factory=list)
    input_types: list = None
    backprop_type: str = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    seed: int = 12345
    dtype: str = "float32"
    compute_dtype: object = None   # mixed precision (see MultiLayerConfiguration)
    remat: object = None           # rematerialization (see MultiLayerConfiguration)
    optimization_algo: str = "sgd"
    max_num_line_search_iterations: int = 5
    topological_order: list = None

    def topo_sort(self):
        """Kahn's algorithm (reference: ComputationGraph.topologicalSortOrder :850)."""
        if self.topological_order is not None:
            return self.topological_order
        indeg = {n: len(s.inputs) for n, s in self.vertices.items()}
        out_edges = {n: [] for n in self.vertices}
        for n, s in self.vertices.items():
            for i in s.inputs:
                out_edges[i].append(n)
        queue = [n for n, d in indeg.items() if d == 0]
        order = []
        while queue:
            n = queue.pop(0)
            order.append(n)
            for m in out_edges[n]:
                indeg[m] -= 1
                if indeg[m] == 0:
                    queue.append(m)
        if len(order) != len(self.vertices):
            raise ValueError("Graph has a cycle")
        self.topological_order = order
        return order

    def to_dict(self):
        verts = {}
        for n, s in self.vertices.items():
            verts[n] = {
                "kind": s.kind,
                "inputs": s.inputs,
                "layer_conf": s.layer_conf.to_dict() if s.layer_conf else None,
                "vertex_conf": s.vertex_conf.to_dict() if s.vertex_conf else None,
                "preprocessor": s.preprocessor.to_dict() if s.preprocessor else None,
            }
        return {
            "format": "deeplearning4j-tpu/ComputationGraphConfiguration",
            "version": 1,
            "vertices": verts,
            "network_inputs": self.network_inputs,
            "network_outputs": self.network_outputs,
            "input_types": [t.to_dict() for t in self.input_types] if self.input_types else None,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "seed": self.seed,
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "remat": self.remat,
            "optimization_algo": self.optimization_algo,
            "max_num_line_search_iterations": self.max_num_line_search_iterations,
        }

    def to_json(self):
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_dict(d):
        conf = ComputationGraphConfiguration()
        for n, sd in d["vertices"].items():
            conf.vertices[n] = GraphVertexSpec(
                name=n, kind=sd["kind"],
                layer_conf=L.layer_conf_from_dict(sd["layer_conf"]) if sd.get("layer_conf") else None,
                vertex_conf=vertex_from_dict(sd["vertex_conf"]) if sd.get("vertex_conf") else None,
                inputs=list(sd.get("inputs", [])),
                preprocessor=preprocessor_from_dict(sd["preprocessor"]) if sd.get("preprocessor") else None)
        conf.network_inputs = list(d["network_inputs"])
        conf.network_outputs = list(d["network_outputs"])
        if d.get("input_types"):
            conf.input_types = [InputType.from_dict(t) for t in d["input_types"]]
        for k in ("backprop_type", "tbptt_fwd_length", "tbptt_back_length", "seed",
                  "dtype", "compute_dtype", "remat", "optimization_algo",
                  "max_num_line_search_iterations"):
            if k in d:
                setattr(conf, k, d[k])
        return conf

    @staticmethod
    def from_json(s):
        return ComputationGraphConfiguration.from_dict(json.loads(s))


class GraphBuilder:
    """(reference: ComputationGraphConfiguration.GraphBuilder :406)"""

    def __init__(self, global_conf):
        self._global = global_conf
        self._conf = ComputationGraphConfiguration(
            seed=global_conf.get("seed", 12345),
            dtype=global_conf.get("dtype", "float32"),
            compute_dtype=global_conf.get("compute_dtype"),
            remat=global_conf.get("remat"),
            optimization_algo=global_conf.get("optimization_algo", "sgd"),
            max_num_line_search_iterations=global_conf.get(
                "max_num_line_search_iterations", 5))

    def add_inputs(self, *names):
        for n in names:
            self._conf.network_inputs.append(n)
            self._conf.vertices[n] = GraphVertexSpec(name=n, kind="input")
        return self

    def add_layer(self, name, layer_conf, *inputs, preprocessor=None):
        self._conf.vertices[name] = GraphVertexSpec(
            name=name, kind="layer", layer_conf=layer_conf, inputs=list(inputs),
            preprocessor=preprocessor)
        return self

    def add_vertex(self, name, vertex_conf, *inputs):
        self._conf.vertices[name] = GraphVertexSpec(
            name=name, kind="vertex", vertex_conf=vertex_conf, inputs=list(inputs))
        return self

    def set_outputs(self, *names):
        self._conf.network_outputs = list(names)
        return self

    def set_input_types(self, *types):
        self._conf.input_types = list(types)
        return self

    def backprop_type(self, t):
        self._conf.backprop_type = t
        return self

    def tbptt_fwd_length(self, n):
        self._conf.tbptt_fwd_length = int(n)
        return self

    def tbptt_back_length(self, n):
        self._conf.tbptt_back_length = int(n)
        return self

    def build(self):
        conf = self._conf
        g = self._global
        order = conf.topo_sort()
        # finalize layer confs + shape inference
        types = {}
        if conf.input_types:
            for name, t in zip(conf.network_inputs, conf.input_types):
                types[name] = t
        for name in order:
            spec = conf.vertices[name]
            if spec.kind == "input":
                continue
            in_types = [types.get(i) for i in spec.inputs]
            if spec.kind == "layer":
                lc = spec.layer_conf
                lc.apply_global_defaults(g)
                if lc.updater is None:
                    lc.updater = g.get("updater") or Sgd(learning_rate=g.get("learning_rate", 0.1))
                t = in_types[0]
                if t is not None:
                    if spec.preprocessor is None:
                        spec.preprocessor = default_preprocessor(t, lc)
                    t = type_after_preprocessor(t, spec.preprocessor)
                    lc.set_n_in(t)
                    types[name] = lc.get_output_type(t)
            else:
                if all(t is not None for t in in_types):
                    types[name] = spec.vertex_conf.output_type(in_types)
        return conf
