"""NeuralNetConfiguration builder DSL -> MultiLayerConfiguration.

Capability parity with reference nn/conf/NeuralNetConfiguration.java (Builder at
:484), nn/conf/MultiLayerConfiguration.java (setInputType at :412 drives
automatic preprocessor insertion + nIn inference). JSON round-trip of configs is
the serialization contract (reference stores `configuration.json` inside model
zips, util/ModelSerializer.java:94); unlike the reference's Jackson classpath
scan (registerSubtypes :376), subtypes live in an explicit registry.
"""
from __future__ import annotations

import json
from dataclasses import dataclass, field

from . import layers as L
from .inputs import (InputType, FeedForwardInputType, RecurrentInputType,
                     ConvolutionalInputType, ConvolutionalFlatInputType)
from .preprocessors import (CnnToFeedForwardPreProcessor, CnnToRnnPreProcessor,
                            FeedForwardToCnnPreProcessor, FeedForwardToRnnPreProcessor,
                            RnnToCnnPreProcessor, RnnToFeedForwardPreProcessor,
                            preprocessor_from_dict)
from ..updaters import Sgd, updater_from_dict


class BackpropType:
    STANDARD = "standard"
    TRUNCATED_BPTT = "truncated_bptt"


class OptimizationAlgorithm:
    STOCHASTIC_GRADIENT_DESCENT = "sgd"
    LINE_GRADIENT_DESCENT = "line_gradient_descent"
    CONJUGATE_GRADIENT = "conjugate_gradient"
    LBFGS = "lbfgs"


def expected_input_kind(conf):
    """Which InputType family a layer consumes: 'ff' | 'cnn' | 'recurrent' | 'any'."""
    if isinstance(conf, (L.ConvolutionLayer, L.SubsamplingLayer, L.ZeroPaddingLayer,
                         L.LocalResponseNormalization)):
        return "cnn"
    if isinstance(conf, (L.BaseRecurrentConf, L.RnnOutputLayer)):
        # GravesLSTM/LSTM/GravesBidirectionalLSTM/SelfAttentionLayer all
        # consume [b, t, f]
        return "recurrent"
    if isinstance(conf, (L.ActivationLayer, L.DropoutLayer, L.LossLayer,
                         L.GlobalPoolingLayer, L.BatchNormalization,
                         L.LayerNormalization)):
        return "any"
    if type(conf) is L.DenseLayer:
        # Dense is time-distributed on [b, t, f] (no RnnToFeedForward needed)
        # and self-flattens rank-4 CNN input; only cnn_flat still reshapes
        return "any"
    return "ff"


def default_preprocessor(prev_type, conf):
    """Auto preprocessor between layer families (reference:
    InputType-driven insertion in MultiLayerConfiguration.Builder.setInputType +
    per-InputType getPreProcessorForInputType)."""
    want = expected_input_kind(conf)
    kind = prev_type.kind
    if want == "any" or want == kind or (want == "ff" and kind == "ff"):
        if kind == "cnn_flat" and want == "cnn":
            return FeedForwardToCnnPreProcessor(prev_type.height, prev_type.width, prev_type.channels)
        return None
    if kind in ("cnn",):
        if want == "ff":
            return CnnToFeedForwardPreProcessor(prev_type.height, prev_type.width, prev_type.channels)
        if want == "recurrent":
            return CnnToRnnPreProcessor(prev_type.height, prev_type.width, prev_type.channels)
    if kind == "cnn_flat":
        if want == "cnn":
            return FeedForwardToCnnPreProcessor(prev_type.height, prev_type.width, prev_type.channels)
        if want == "ff":
            return None
        if want == "recurrent":
            return FeedForwardToRnnPreProcessor()
    if kind == "ff":
        if want == "cnn":
            raise ValueError("Cannot infer CNN dims from feed-forward input; "
                             "use InputType.convolutional_flat or an explicit "
                             "FeedForwardToCnnPreProcessor")
        if want == "recurrent":
            return FeedForwardToRnnPreProcessor()
    if kind == "recurrent":
        if want == "ff":
            return RnnToFeedForwardPreProcessor()
        if want == "cnn":
            raise ValueError("RnnToCnn requires explicit dims; add RnnToCnnPreProcessor manually")
    return None


def type_after_preprocessor(prev_type, pre):
    return pre.output_type(prev_type) if pre is not None else (
        InputType.feed_forward(prev_type.flat_size())
        if prev_type.kind == "cnn_flat" else prev_type)


@dataclass
class MultiLayerConfiguration:
    layers: list = field(default_factory=list)
    input_preprocessors: dict = field(default_factory=dict)
    input_type: object = None
    backprop_type: str = BackpropType.STANDARD
    tbptt_fwd_length: int = 20
    tbptt_back_length: int = 20
    seed: int = 12345
    dtype: str = "float32"
    # compute (activation/matmul) dtype for mixed precision; None = same as
    # dtype. "bfloat16" keeps f32 master params + BN stats + loss while the
    # MXU-bound forward/backward runs in bf16 (TPU-native mixed precision —
    # the reference's analog is the fp16 cuDNN bypass, ConvolutionLayer.java:158)
    compute_dtype: object = None
    # rematerialization (gradient checkpointing): recompute activations in
    # the backward instead of storing them (jax.checkpoint over the
    # forward; modes in nn/remat.py). None = off; "convs_and_dots" saves
    # conv+matmul outputs and recomputes the elementwise/BN chains (the
    # recommended memory dial: ResNet-50 measured −24% temp for −22%
    # throughput, PERF.md §3); "dots" saves matmul outputs only (convs
    # recompute too); "dots_no_batch" the jax variant thereof; "full"
    # saves only inputs. The reference has no analog (its workspace memory
    # manager reuses buffers but never recomputes).
    remat: object = None
    optimization_algo: str = OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT
    max_num_line_search_iterations: int = 5
    pretrain: bool = False
    backprop: bool = True

    # ---- serde (the checkpoint `configuration.json` contract) -------------
    def to_dict(self):
        return {
            "format": "deeplearning4j-tpu/MultiLayerConfiguration",
            "version": 1,
            "layers": [l.to_dict() for l in self.layers],
            "input_preprocessors": {str(k): v.to_dict() for k, v in self.input_preprocessors.items()},
            "input_type": self.input_type.to_dict() if self.input_type else None,
            "backprop_type": self.backprop_type,
            "tbptt_fwd_length": self.tbptt_fwd_length,
            "tbptt_back_length": self.tbptt_back_length,
            "seed": self.seed,
            "dtype": self.dtype,
            "compute_dtype": self.compute_dtype,
            "remat": self.remat,
            "optimization_algo": self.optimization_algo,
            "max_num_line_search_iterations": self.max_num_line_search_iterations,
            "pretrain": self.pretrain,
            "backprop": self.backprop,
        }

    def to_json(self):
        return json.dumps(self.to_dict(), indent=2)

    @staticmethod
    def from_dict(d):
        conf = MultiLayerConfiguration()
        conf.layers = [L.layer_conf_from_dict(ld) for ld in d["layers"]]
        conf.input_preprocessors = {int(k): preprocessor_from_dict(v)
                                    for k, v in d.get("input_preprocessors", {}).items()}
        it = d.get("input_type")
        conf.input_type = InputType.from_dict(it) if it else None
        for k in ("backprop_type", "tbptt_fwd_length", "tbptt_back_length", "seed",
                  "dtype", "compute_dtype", "remat", "optimization_algo",
                  "max_num_line_search_iterations", "pretrain", "backprop"):
            if k in d:
                setattr(conf, k, d[k])
        return conf

    @staticmethod
    def from_json(s):
        return MultiLayerConfiguration.from_dict(json.loads(s))


class ListBuilder:
    """The `.list()` stage of the DSL (reference:
    NeuralNetConfiguration.ListBuilder)."""

    def __init__(self, global_conf):
        self._global = global_conf
        self._layers = []
        self._preprocessors = {}
        self._input_type = None
        self._backprop_type = BackpropType.STANDARD
        self._tbptt_fwd = 20
        self._tbptt_back = 20
        self._pretrain = False
        self._backprop = True

    def layer(self, index_or_conf, conf=None):
        """Accepts .layer(conf) or .layer(i, conf) like the reference."""
        if conf is None:
            self._layers.append(index_or_conf)
        else:
            idx = int(index_or_conf)
            while len(self._layers) <= idx:
                self._layers.append(None)
            self._layers[idx] = conf
        return self

    def input_preprocessor(self, index, pre):
        self._preprocessors[int(index)] = pre
        return self

    def set_input_type(self, input_type):
        self._input_type = input_type
        return self

    input_type = set_input_type

    def backprop_type(self, bptype):
        self._backprop_type = bptype
        return self

    def tbptt_fwd_length(self, n):
        self._tbptt_fwd = int(n)
        return self

    def tbptt_back_length(self, n):
        self._tbptt_back = int(n)
        return self

    def pretrain(self, flag):
        self._pretrain = bool(flag)
        return self

    def backprop(self, flag):
        self._backprop = bool(flag)
        return self

    def build(self):
        g = self._global
        conf = MultiLayerConfiguration(
            layers=list(self._layers),
            input_preprocessors=dict(self._preprocessors),
            input_type=self._input_type,
            backprop_type=self._backprop_type,
            tbptt_fwd_length=self._tbptt_fwd,
            tbptt_back_length=self._tbptt_back,
            seed=g.get("seed", 12345),
            dtype=g.get("dtype", "float32"),
            compute_dtype=g.get("compute_dtype"),
            remat=g.get("remat"),
            optimization_algo=g.get("optimization_algo",
                                    OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT),
            max_num_line_search_iterations=g.get("max_num_line_search_iterations", 5),
            pretrain=self._pretrain,
            backprop=self._backprop,
        )
        for i, lc in enumerate(conf.layers):
            if lc is None:
                raise ValueError(f"Layer {i} was never set")
            lc.apply_global_defaults(g)
            if lc.updater is None:
                lc.updater = g.get("updater") or Sgd(learning_rate=g.get("learning_rate", 0.1))
        # shape inference + auto preprocessors
        cur = conf.input_type
        if cur is not None:
            for i, lc in enumerate(conf.layers):
                pre = conf.input_preprocessors.get(i)
                if pre is None:
                    pre = default_preprocessor(cur, lc)
                    if pre is not None:
                        conf.input_preprocessors[i] = pre
                cur = type_after_preprocessor(cur, pre)
                lc.set_n_in(cur)
                cur = lc.get_output_type(cur)
        return conf


class NeuralNetConfigurationBuilder:
    """Global-hyperparameter stage of the DSL (reference: Builder :484)."""

    def __init__(self):
        self._g = {}

    def seed(self, s):
        self._g["seed"] = int(s)
        return self

    def activation(self, a):
        self._g["activation"] = a
        return self

    def weight_init(self, w):
        self._g["weight_init"] = w
        return self

    def dist(self, d):
        self._g["dist"] = d
        self._g["weight_init"] = "distribution"
        return self

    def bias_init(self, b):
        self._g["bias_init"] = float(b)
        return self

    def l1(self, v):
        self._g["l1"] = float(v)
        return self

    def l2(self, v):
        self._g["l2"] = float(v)
        return self

    def l1_bias(self, v):
        self._g["l1_bias"] = float(v)
        return self

    def l2_bias(self, v):
        self._g["l2_bias"] = float(v)
        return self

    def dropout(self, v):
        self._g["dropout"] = float(v)
        return self

    def learning_rate(self, v):
        self._g["learning_rate"] = float(v)
        if "updater" in self._g and self._g["updater"] is not None:
            self._g["updater"].learning_rate = float(v)
        return self

    def updater(self, u):
        if "learning_rate" in self._g and u is not None:
            # .learning_rate() set before .updater(): honor it unless the
            # updater carries an explicit non-default lr
            pass
        self._g["updater"] = u
        return self

    def optimization_algo(self, algo):
        self._g["optimization_algo"] = algo
        return self

    def max_num_line_search_iterations(self, n):
        self._g["max_num_line_search_iterations"] = int(n)
        return self

    def gradient_normalization(self, mode, threshold=1.0):
        self._g["gradient_normalization"] = mode
        self._g["gradient_normalization_threshold"] = float(threshold)
        return self

    def dtype(self, dt):
        self._g["dtype"] = str(dt)
        return self

    def remat(self, mode):
        """Rematerialization: None / "convs_and_dots" (recommended memory
        dial) / "dots" / "dots_no_batch" / "full" — see
        MultiLayerConfiguration.remat and nn/remat.py."""
        self._g["remat"] = mode
        return self

    def compute_dtype(self, dt):
        """Mixed precision: run forward/backward math in `dt` (e.g. "bfloat16")
        while parameters, optimizer state, BatchNorm statistics, and the loss
        stay in `dtype`."""
        self._g["compute_dtype"] = None if dt is None else str(dt)
        return self

    def regularization(self, flag):
        # reference has a use-regularization toggle; here l1/l2=0 mean off.
        return self

    def mini_batch(self, flag):
        return self

    def list(self):
        return ListBuilder(dict(self._g))

    def graph_builder(self):
        from .graph_configuration import GraphBuilder
        return GraphBuilder(dict(self._g))


class NeuralNetConfiguration:
    @staticmethod
    def builder():
        return NeuralNetConfigurationBuilder()
