"""Input preprocessors: shape adapters inserted between layer families.

Capability parity with reference nn/conf/preprocessor/* (12 classes):
CnnToFeedForward, CnnToRnn, FeedForwardToCnn, FeedForwardToRnn, RnnToCnn,
RnnToFeedForward, UnitVariance, ZeroMeanAndUnitVariance, ZeroMean,
BinomialSampling, Composable.

TPU-first: preprocessors are pure reshape/normalise functions traced into the
same XLA computation as the layers (free fusion), not separate op dispatches.
Layouts: CNN activations are NHWC, recurrent activations are [b, t, f].
In the reference these classes also implement `backprop(epsilon)`; here the
backward pass falls out of autodiff.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_REGISTRY: dict = {}


def register_preprocessor(cls):
    _REGISTRY[cls.__name__] = cls
    return cls


def preprocessor_from_dict(d):
    d = dict(d)
    cls = _REGISTRY[d.pop("type")]
    return cls(**d)


class BasePreprocessor:
    def __call__(self, x, mask=None, rng=None):
        raise NotImplementedError

    def output_type(self, input_type):
        raise NotImplementedError

    def feed_forward_mask(self, mask):
        return mask

    def to_dict(self):
        d = dict(self.__dict__)
        d["type"] = type(self).__name__
        return d


@register_preprocessor
class CnnToFeedForwardPreProcessor(BasePreprocessor):
    """[b,h,w,c] -> [b, h*w*c] (reference: CnnToFeedForwardPreProcessor)."""

    def __init__(self, height=None, width=None, channels=None):
        self.height, self.width, self.channels = height, width, channels

    def __call__(self, x, mask=None, rng=None):
        return x.reshape(x.shape[0], -1)

    def output_type(self, input_type):
        from .inputs import InputType
        return InputType.feed_forward(input_type.flat_size())


@register_preprocessor
class FeedForwardToCnnPreProcessor(BasePreprocessor):
    """[b, h*w*c] -> [b,h,w,c]."""

    def __init__(self, height, width, channels):
        self.height, self.width, self.channels = int(height), int(width), int(channels)

    def __call__(self, x, mask=None, rng=None):
        if x.ndim == 4:
            return x
        return x.reshape(x.shape[0], self.height, self.width, self.channels)

    def output_type(self, input_type):
        from .inputs import InputType
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor
class CnnToRnnPreProcessor(BasePreprocessor):
    """[b*t,h,w,c] flattened conv activations -> [b,t,h*w*c] sequences.
    The time dimension comes from the mask, or from an explicit `timesteps`
    when the pipeline is unmasked."""

    def __init__(self, height, width, channels, timesteps=None):
        self.height, self.width, self.channels = int(height), int(width), int(channels)
        self.timesteps = None if timesteps is None else int(timesteps)

    def __call__(self, x, mask=None, rng=None):
        if x.ndim == 3:
            return x
        b_t = x.shape[0]
        feat = self.height * self.width * self.channels
        t = mask.shape[1] if mask is not None else self.timesteps
        if t is None:
            raise ValueError(
                "CnnToRnnPreProcessor cannot recover the time dimension: "
                "provide a feature mask or construct with timesteps=...")
        return x.reshape(b_t // t, t, feat)

    def output_type(self, input_type):
        from .inputs import InputType
        return InputType.recurrent(self.height * self.width * self.channels)


@register_preprocessor
class RnnToCnnPreProcessor(BasePreprocessor):
    """[b,t,f] -> [b*t,h,w,c]."""

    def __init__(self, height, width, channels):
        self.height, self.width, self.channels = int(height), int(width), int(channels)

    def __call__(self, x, mask=None, rng=None):
        b, t = x.shape[0], x.shape[1]
        return x.reshape(b * t, self.height, self.width, self.channels)

    def output_type(self, input_type):
        from .inputs import InputType
        return InputType.convolutional(self.height, self.width, self.channels)


@register_preprocessor
class FeedForwardToRnnPreProcessor(BasePreprocessor):
    """[b*t, f] or [b, f] -> [b, t, f]; with no mask treats input as t=1."""

    def __init__(self):
        pass

    def __call__(self, x, mask=None, rng=None):
        if x.ndim == 3:
            return x
        if mask is not None:
            t = mask.shape[1]
            return x.reshape(x.shape[0] // t, t, x.shape[-1])
        return x[:, None, :]

    def output_type(self, input_type):
        from .inputs import InputType
        return InputType.recurrent(input_type.flat_size())


@register_preprocessor
class RnnToFeedForwardPreProcessor(BasePreprocessor):
    """[b,t,f] -> [b*t, f] (time steps become independent rows)."""

    def __init__(self):
        pass

    def __call__(self, x, mask=None, rng=None):
        if x.ndim == 2:
            return x
        return x.reshape(-1, x.shape[-1])

    def output_type(self, input_type):
        from .inputs import InputType
        return InputType.feed_forward(input_type.flat_size())

    def feed_forward_mask(self, mask):
        return None if mask is None else mask.reshape(-1)


@register_preprocessor
class UnitVarianceProcessor(BasePreprocessor):
    def __init__(self):
        pass

    def __call__(self, x, mask=None, rng=None):
        std = jnp.std(x, axis=0, keepdims=True) + 1e-8
        return x / std

    def output_type(self, input_type):
        return input_type


@register_preprocessor
class ZeroMeanPrePreProcessor(BasePreprocessor):
    def __init__(self):
        pass

    def __call__(self, x, mask=None, rng=None):
        return x - jnp.mean(x, axis=0, keepdims=True)

    def output_type(self, input_type):
        return input_type


@register_preprocessor
class ZeroMeanAndUnitVariancePreProcessor(BasePreprocessor):
    def __init__(self):
        pass

    def __call__(self, x, mask=None, rng=None):
        mu = jnp.mean(x, axis=0, keepdims=True)
        std = jnp.std(x, axis=0, keepdims=True) + 1e-8
        return (x - mu) / std

    def output_type(self, input_type):
        return input_type


@register_preprocessor
class BinomialSamplingPreProcessor(BasePreprocessor):
    """Samples Bernoulli(x) — used historically for RBM pretraining pipelines."""

    def __init__(self, seed=0):
        self.seed = int(seed)

    def __call__(self, x, mask=None, rng=None):
        if rng is None:
            # inference path without a step rng: derive a key from batch
            # content so distinct batches still get distinct noise
            salt = jax.lax.bitcast_convert_type(jnp.sum(x).astype(jnp.float32),
                                                jnp.int32)
            rng = jax.random.fold_in(jax.random.PRNGKey(self.seed), salt)
        return jax.random.bernoulli(rng, jnp.clip(x, 0.0, 1.0)).astype(x.dtype)

    def output_type(self, input_type):
        return input_type


@register_preprocessor
class ImageScalerPreProcessor(BasePreprocessor):
    """On-device image normalization: integer pixels (uint8 on the wire —
    4× less host→device traffic than f32) are cast to the compute dtype and
    scaled to [min_range, max_range] INSIDE the jitted step.

    TPU-native analog of nd4j's ImagePreProcessingScaler (which rescales on
    the host before transfer); here the cheap cast/scale runs on-chip so the
    PCIe/DCN link carries 1 byte/pixel (VERDICT r2 weak #2: ship uint8 NHWC,
    normalize on device)."""

    def __init__(self, min_range=0.0, max_range=1.0, max_pixel=255.0):
        self.min_range = float(min_range)
        self.max_range = float(max_range)
        self.max_pixel = float(max_pixel)

    def __call__(self, x, mask=None, rng=None):
        # keep the compute dtype if the harness already cast the raw pixels
        # (bf16 under mixed precision); fall back to f32 for integer input
        dt = x.dtype if jnp.issubdtype(x.dtype, jnp.floating) else jnp.float32
        span = self.max_range - self.min_range
        return x.astype(dt) * (span / self.max_pixel) + self.min_range

    def output_type(self, input_type):
        return input_type


class ComposableInputPreProcessor(BasePreprocessor):
    """Chains preprocessors (reference: ComposableInputPreProcessor)."""

    def __init__(self, *processors):
        self.processors = list(processors)

    def __call__(self, x, mask=None, rng=None):
        for p in self.processors:
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            x = p(x, mask, rng=sub)
        return x

    def output_type(self, input_type):
        for p in self.processors:
            input_type = p.output_type(input_type)
        return input_type

    def to_dict(self):
        return {"type": "ComposableInputPreProcessor",
                "processors": [p.to_dict() for p in self.processors]}


_REGISTRY["ComposableInputPreProcessor"] = ComposableInputPreProcessor


def _composable_from_dict(d):
    procs = [preprocessor_from_dict(p) for p in d["processors"]]
    return ComposableInputPreProcessor(*procs)
