"""InputType system: drives automatic shape inference (nIn) and automatic
insertion of input preprocessors between layer families.

Capability parity with reference nn/conf/inputs/InputType.java:60-92 and the
setInputType plumbing at nn/conf/MultiLayerConfiguration.java:412-421.

TPU-first layout conventions (differ from the reference deliberately):
- convolutional: NHWC [batch, height, width, channels]  (reference: NCHW)
- recurrent:     [batch, time, features]                 (reference: [b, size, t])
NHWC + channel-last is the layout XLA prefers on TPU (MXU tiling of the
channel dim); time-major-second keeps lax.scan over axis 1 contiguous.
"""
from __future__ import annotations

from dataclasses import dataclass


class InputType:
    """Factory for input type descriptors."""

    @staticmethod
    def feed_forward(size):
        return FeedForwardInputType(int(size))

    @staticmethod
    def recurrent(size, timesteps=None):
        return RecurrentInputType(int(size), None if timesteps is None else int(timesteps))

    @staticmethod
    def convolutional(height, width, channels):
        return ConvolutionalInputType(int(height), int(width), int(channels))

    @staticmethod
    def convolutional_flat(height, width, channels):
        return ConvolutionalFlatInputType(int(height), int(width), int(channels))

    @staticmethod
    def from_dict(d):
        t = d["kind"]
        if t == "ff":
            return FeedForwardInputType(d["size"])
        if t == "recurrent":
            return RecurrentInputType(d["size"], d.get("timesteps"))
        if t == "cnn":
            return ConvolutionalInputType(d["height"], d["width"], d["channels"])
        if t == "cnn_flat":
            return ConvolutionalFlatInputType(d["height"], d["width"], d["channels"])
        raise ValueError(f"Unknown input type kind {t}")


@dataclass(frozen=True)
class FeedForwardInputType:
    size: int
    kind: str = "ff"

    def flat_size(self):
        return self.size

    def to_dict(self):
        return {"kind": "ff", "size": self.size}


@dataclass(frozen=True)
class RecurrentInputType:
    size: int
    timesteps: int | None = None
    kind: str = "recurrent"

    def flat_size(self):
        return self.size

    def to_dict(self):
        return {"kind": "recurrent", "size": self.size, "timesteps": self.timesteps}


@dataclass(frozen=True)
class ConvolutionalInputType:
    height: int
    width: int
    channels: int
    kind: str = "cnn"

    def flat_size(self):
        return self.height * self.width * self.channels

    def to_dict(self):
        return {"kind": "cnn", "height": self.height, "width": self.width,
                "channels": self.channels}


@dataclass(frozen=True)
class ConvolutionalFlatInputType:
    height: int
    width: int
    channels: int
    kind: str = "cnn_flat"

    def flat_size(self):
        return self.height * self.width * self.channels

    def to_dict(self):
        return {"kind": "cnn_flat", "height": self.height, "width": self.width,
                "channels": self.channels}
