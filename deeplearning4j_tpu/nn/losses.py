"""Loss functions with per-example mask and per-output weight support.

Capability parity with the reference's ILossFunction implementations (reference:
nd4j `org.nd4j.linalg.lossfunctions.impl.*`, exercised exhaustively by
deeplearning4j-core/src/test/java/org/deeplearning4j/gradientcheck/LossFunctionGradientCheck.java).

TPU-first: each loss is a pure function (labels, preoutput, activation_fn, mask)
-> scalar mean score. Gradients come from autodiff of the fused
activation+loss composition, which lets XLA fuse the softmax/sigmoid with the
loss instead of materialising the activated output (the reference computes
`computeGradient` by hand per loss class).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .activations import get_activation

_EPS = 1e-10

_REGISTRY: dict = {}


def register_loss(name):
    def deco(cls_or_fn):
        _REGISTRY[name.upper()] = cls_or_fn
        return cls_or_fn
    return deco


def get_loss(name):
    if isinstance(name, BaseLoss):
        return name
    if callable(name) and not isinstance(name, str):
        return name() if isinstance(name, type) else name
    key = str(name).upper()
    if key not in _REGISTRY:
        raise ValueError(f"Unknown loss '{name}'. Known: {sorted(_REGISTRY)}")
    return _REGISTRY[key]()


def loss_names():
    return sorted(_REGISTRY)


def _masked_score(per_elem, mask, sum_features=True):
    """per_elem: [batch, features...] element-wise loss; returns mean over batch of
    summed feature loss, honoring an optional [batch]- or element-shaped mask."""
    b = per_elem.shape[0]
    flat = per_elem.reshape(b, -1)
    if mask is None:
        return jnp.mean(jnp.sum(flat, axis=-1) if sum_features else jnp.mean(flat, axis=-1))
    mask = jnp.asarray(mask, per_elem.dtype)
    if mask.ndim == 1:
        per_ex = jnp.sum(flat, axis=-1) if sum_features else jnp.mean(flat, axis=-1)
        return jnp.sum(per_ex * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    if mask.ndim >= 2 and mask.shape == per_elem.shape[:-1]:
        # per-position mask (e.g. [b, t] over [b, t, c]): average over active
        # positions, matching the RnnOutputLayer reshape semantics
        pos = jnp.sum(per_elem, axis=-1) if sum_features else jnp.mean(per_elem, axis=-1)
        return jnp.sum(pos * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    bmask = jnp.broadcast_to(mask.reshape(b, -1), flat.shape) if mask.size != flat.size else mask.reshape(b, -1)
    masked = flat * bmask
    if sum_features:
        # number of active examples = rows with any active element
        row_active = jnp.max(bmask, axis=-1)
        return jnp.sum(masked) / jnp.maximum(jnp.sum(row_active), 1.0)
    return jnp.sum(masked) / jnp.maximum(jnp.sum(bmask), 1.0)


class BaseLoss:
    """Loss SPI: score(labels, preoutput, activation, mask) -> scalar.

    `weights` (per-output-dimension) mirrors the reference's weighted loss
    constructors (e.g. LossMCXENT(INDArray weights))."""

    def __init__(self, weights=None):
        self.weights = None if weights is None else jnp.asarray(weights)

    def _w(self, per_elem):
        if self.weights is not None:
            return per_elem * self.weights
        return per_elem

    def score(self, labels, preoutput, activation="identity", mask=None):
        raise NotImplementedError

    def __call__(self, labels, preoutput, activation="identity", mask=None):
        return self.score(labels, preoutput, activation, mask)


@register_loss("MSE")
class LossMSE(BaseLoss):
    def score(self, labels, preoutput, activation="identity", mask=None):
        out = get_activation(activation)(preoutput)
        per = self._w((labels - out) ** 2) / labels.shape[-1]
        return _masked_score(per, mask)


@register_loss("L2")
class LossL2(BaseLoss):
    def score(self, labels, preoutput, activation="identity", mask=None):
        out = get_activation(activation)(preoutput)
        per = self._w((labels - out) ** 2)
        return _masked_score(per, mask)


@register_loss("L1")
class LossL1(BaseLoss):
    def score(self, labels, preoutput, activation="identity", mask=None):
        out = get_activation(activation)(preoutput)
        per = self._w(jnp.abs(labels - out))
        return _masked_score(per, mask)


@register_loss("MAE")
class LossMAE(BaseLoss):
    def score(self, labels, preoutput, activation="identity", mask=None):
        out = get_activation(activation)(preoutput)
        per = self._w(jnp.abs(labels - out)) / labels.shape[-1]
        return _masked_score(per, mask)


@register_loss("MCXENT")
@register_loss("NEGATIVELOGLIKELIHOOD")
class LossMCXENT(BaseLoss):
    """Multi-class cross entropy. When the activation is softmax the
    composition is computed via log_softmax for numerical stability (XLA fuses
    this into one kernel — the TPU-friendly alternative to the reference's
    special-cased softmax gradient path)."""

    def score(self, labels, preoutput, activation="softmax", mask=None):
        act_name = activation if isinstance(activation, str) else getattr(activation, "__name__", "")
        if str(act_name).lower() == "softmax":
            logp = jax.nn.log_softmax(preoutput, axis=-1)
        else:
            out = get_activation(activation)(preoutput)
            logp = jnp.log(jnp.maximum(out, _EPS))
        per = self._w(-labels * logp)
        return _masked_score(per, mask)


@register_loss("XENT")
class LossBinaryXENT(BaseLoss):
    def score(self, labels, preoutput, activation="sigmoid", mask=None):
        act_name = activation if isinstance(activation, str) else getattr(activation, "__name__", "")
        if str(act_name).lower() == "sigmoid":
            # stable: log(sigmoid(x)) = -softplus(-x)
            logp = -jax.nn.softplus(-preoutput)
            log1mp = -jax.nn.softplus(preoutput)
        else:
            out = get_activation(activation)(preoutput)
            out = jnp.clip(out, _EPS, 1.0 - _EPS)
            logp, log1mp = jnp.log(out), jnp.log1p(-out)
        per = self._w(-(labels * logp + (1.0 - labels) * log1mp))
        return _masked_score(per, mask)


@register_loss("HINGE")
class LossHinge(BaseLoss):
    def score(self, labels, preoutput, activation="identity", mask=None):
        out = get_activation(activation)(preoutput)
        per = self._w(jnp.maximum(0.0, 1.0 - labels * out))
        return _masked_score(per, mask)


@register_loss("SQUARED_HINGE")
class LossSquaredHinge(BaseLoss):
    def score(self, labels, preoutput, activation="identity", mask=None):
        out = get_activation(activation)(preoutput)
        per = self._w(jnp.maximum(0.0, 1.0 - labels * out) ** 2)
        return _masked_score(per, mask)


@register_loss("KL_DIVERGENCE")
@register_loss("KLD")
class LossKLD(BaseLoss):
    def score(self, labels, preoutput, activation="softmax", mask=None):
        out = get_activation(activation)(preoutput)
        out = jnp.clip(out, _EPS, 1.0)
        lab = jnp.clip(labels, _EPS, 1.0)
        per = self._w(labels * (jnp.log(lab) - jnp.log(out)))
        return _masked_score(per, mask)


@register_loss("MEAN_ABSOLUTE_PERCENTAGE_ERROR")
@register_loss("MAPE")
class LossMAPE(BaseLoss):
    def score(self, labels, preoutput, activation="identity", mask=None):
        out = get_activation(activation)(preoutput)
        per = self._w(100.0 * jnp.abs((labels - out) / jnp.where(jnp.abs(labels) < _EPS, _EPS, labels))) / labels.shape[-1]
        return _masked_score(per, mask)


@register_loss("MEAN_SQUARED_LOGARITHMIC_ERROR")
@register_loss("MSLE")
class LossMSLE(BaseLoss):
    def score(self, labels, preoutput, activation="identity", mask=None):
        out = get_activation(activation)(preoutput)
        per = self._w((jnp.log1p(jnp.maximum(labels, -1 + _EPS)) - jnp.log1p(jnp.maximum(out, -1 + _EPS))) ** 2) / labels.shape[-1]
        return _masked_score(per, mask)


@register_loss("POISSON")
class LossPoisson(BaseLoss):
    def score(self, labels, preoutput, activation="identity", mask=None):
        out = get_activation(activation)(preoutput)
        per = self._w(out - labels * jnp.log(jnp.maximum(out, _EPS)))
        return _masked_score(per, mask)


@register_loss("COSINE_PROXIMITY")
class LossCosineProximity(BaseLoss):
    def score(self, labels, preoutput, activation="identity", mask=None):
        out = get_activation(activation)(preoutput)
        ln = jnp.linalg.norm(labels, axis=-1, keepdims=True)
        on = jnp.linalg.norm(out, axis=-1, keepdims=True)
        cos = jnp.sum(labels * out, axis=-1, keepdims=True) / jnp.maximum(ln * on, _EPS)
        per = -cos
        return _masked_score(per, mask)
