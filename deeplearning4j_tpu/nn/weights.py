"""Weight initialization schemes.

Capability parity with the reference's WeightInit enum + WeightInitUtil
(reference: nn/weights/WeightInit.java, nn/weights/WeightInitUtil.java).
fan_in/fan_out semantics follow the reference: for a [nOut, nIn] dense kernel
fanIn = nIn, fanOut = nOut; for conv kernels fan includes the receptive field.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class WeightInit:
    ZERO = "zero"
    ONES = "ones"
    UNIFORM = "uniform"
    NORMAL = "normal"
    XAVIER = "xavier"
    XAVIER_UNIFORM = "xavier_uniform"
    XAVIER_FAN_IN = "xavier_fan_in"
    XAVIER_LEGACY = "xavier_legacy"
    RELU = "relu"
    RELU_UNIFORM = "relu_uniform"
    SIGMOID_UNIFORM = "sigmoid_uniform"
    LECUN_NORMAL = "lecun_normal"
    LECUN_UNIFORM = "lecun_uniform"
    IDENTITY = "identity"
    VAR_SCALING_NORMAL_FAN_IN = "var_scaling_normal_fan_in"
    DISTRIBUTION = "distribution"


def init_weights(rng, shape, scheme=WeightInit.XAVIER, fan_in=None, fan_out=None,
                 distribution=None, dtype=jnp.float32):
    """Initialize a weight tensor.

    `distribution` is a dict like {"type": "normal"|"uniform", ...} used with
    WeightInit.DISTRIBUTION (mirrors the reference's Distribution configs)."""
    shape = tuple(int(s) for s in shape)
    if fan_in is None or fan_out is None:
        if len(shape) == 2:
            fan_out_d, fan_in_d = shape
        elif len(shape) > 2:
            receptive = 1
            for s in shape[2:]:
                receptive *= s
            fan_in_d = shape[1] * receptive
            fan_out_d = shape[0] * receptive
        else:
            fan_in_d = fan_out_d = shape[0] if shape else 1
        fan_in = fan_in if fan_in is not None else fan_in_d
        fan_out = fan_out if fan_out is not None else fan_out_d
    fan_in = max(float(fan_in), 1.0)
    fan_out = max(float(fan_out), 1.0)

    s = str(scheme).lower()
    if s == WeightInit.ZERO:
        return jnp.zeros(shape, dtype)
    if s == WeightInit.ONES:
        return jnp.ones(shape, dtype)
    if s == WeightInit.UNIFORM:
        a = 1.0 / jnp.sqrt(fan_in)
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if s == WeightInit.NORMAL:
        return jax.random.normal(rng, shape, dtype) / jnp.sqrt(fan_in)
    if s in (WeightInit.XAVIER, WeightInit.XAVIER_LEGACY):
        std = jnp.sqrt(2.0 / (fan_in + fan_out))
        return std * jax.random.normal(rng, shape, dtype)
    if s == WeightInit.XAVIER_UNIFORM:
        a = jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if s in (WeightInit.XAVIER_FAN_IN, WeightInit.LECUN_NORMAL, WeightInit.VAR_SCALING_NORMAL_FAN_IN):
        return jax.random.normal(rng, shape, dtype) * jnp.sqrt(1.0 / fan_in)
    if s == WeightInit.RELU:
        return jax.random.normal(rng, shape, dtype) * jnp.sqrt(2.0 / fan_in)
    if s == WeightInit.RELU_UNIFORM:
        a = jnp.sqrt(6.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if s == WeightInit.SIGMOID_UNIFORM:
        a = 4.0 * jnp.sqrt(6.0 / (fan_in + fan_out))
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if s == WeightInit.LECUN_UNIFORM:
        a = jnp.sqrt(3.0 / fan_in)
        return jax.random.uniform(rng, shape, dtype, -a, a)
    if s == WeightInit.IDENTITY:
        if len(shape) == 2 and shape[0] == shape[1]:
            return jnp.eye(shape[0], dtype=dtype)
        raise ValueError("IDENTITY init requires a square 2-D shape")
    if s == WeightInit.DISTRIBUTION:
        d = distribution or {"type": "normal", "mean": 0.0, "std": 1.0}
        t = d.get("type", "normal")
        if t == "normal" or t == "gaussian":
            return d.get("mean", 0.0) + d.get("std", 1.0) * jax.random.normal(rng, shape, dtype)
        if t == "uniform":
            return jax.random.uniform(rng, shape, dtype, d.get("lower", -1.0), d.get("upper", 1.0))
        if t == "binomial":
            return jax.random.bernoulli(rng, d.get("p", 0.5), shape).astype(dtype) * d.get("n", 1)
        raise ValueError(f"Unknown distribution type {t}")
    raise ValueError(f"Unknown weight init scheme '{scheme}'")
