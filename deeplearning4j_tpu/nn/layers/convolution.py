"""Convolution family runtime: Conv2D, Subsampling (pooling), ZeroPadding,
LocalResponseNormalization, BatchNormalization, GlobalPooling.

Reference counterparts: nn/layers/convolution/ConvolutionLayer.java (im2col+gemm
path :265-310, cuDNN helper hook :71), subsampling/SubsamplingLayer.java,
normalization/{BatchNormalization,LocalResponseNormalization}.java,
pooling/GlobalPoolingLayer.java.

TPU-first: the reference's helper SPI (cuDNN vs Java path) collapses into a
single XLA lowering — lax.conv_general_dilated / lax.reduce_window ARE the
accelerated path, tiled onto the MXU by the compiler. Layout NHWC; kernels HWIO.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .base import BaseLayerModule, register_impl, apply_dropout
from ..weights import init_weights
from ..conf.inputs import InputType, RecurrentInputType, ConvolutionalInputType


def _conv_padding(conf, kernel=None):
    if conf.convolution_mode == "same":
        return "SAME"
    p = conf.padding
    return ((int(p[0]), int(p[0])), (int(p[1]), int(p[1])))


@register_impl("ConvolutionLayer")
class ConvolutionLayerModule(BaseLayerModule):
    def init(self, rng, input_type, dtype=jnp.float32):
        c = self.conf
        kh, kw = int(c.kernel_size[0]), int(c.kernel_size[1])
        n_in, n_out = int(c.n_in), int(c.n_out)
        fan_in = n_in * kh * kw
        fan_out = n_out * kh * kw
        params = {
            "W": init_weights(rng, (kh, kw, n_in, n_out), c.weight_init,
                              fan_in=fan_in, fan_out=fan_out, distribution=c.dist,
                              dtype=dtype),
        }
        if getattr(c, "has_bias", True):
            params["b"] = jnp.full((n_out,), c.bias_init or 0.0, dtype)
        return params, {}, c.get_output_type(input_type)

    def preoutput(self, params, x):
        c = self.conf
        z = lax.conv_general_dilated(
            x, params["W"],
            window_strides=tuple(int(s) for s in c.stride),
            padding=_conv_padding(c),
            rhs_dilation=tuple(int(d) for d in getattr(c, "dilation", (1, 1))),
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if "b" in params:
            z = z + params["b"]
        return z

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        x = apply_dropout(x, self.conf.dropout, train, rng)
        return self.activation_fn()(self.preoutput(params, x)), state, mask


@register_impl("SubsamplingLayer")
class SubsamplingLayerModule(BaseLayerModule):
    def init(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, self.conf.get_output_type(input_type)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        kh, kw = int(c.kernel_size[0]), int(c.kernel_size[1])
        sh, sw = int(c.stride[0]), int(c.stride[1])
        if c.convolution_mode == "same":
            pad = "SAME"
        else:
            ph, pw = int(c.padding[0]), int(c.padding[1])
            pad = ((0, 0), (ph, ph), (pw, pw), (0, 0))
        window = (1, kh, kw, 1)
        strides = (1, sh, sw, 1)
        pt = c.pooling_type
        if pt == "max":
            init_val = -jnp.inf
            y = lax.reduce_window(x, init_val, lax.max, window, strides, pad)
        elif pt in ("avg", "sum"):
            y = lax.reduce_window(x, 0.0, lax.add, window, strides, pad)
            if pt == "avg":
                y = y / (kh * kw)
        elif pt == "pnorm":
            p = float(c.pnorm)
            y = lax.reduce_window(jnp.abs(x) ** p, 0.0, lax.add, window, strides, pad) ** (1.0 / p)
        else:
            raise ValueError(f"Unknown pooling type {pt}")
        return y, state, mask


@register_impl("ZeroPaddingLayer")
class ZeroPaddingLayerModule(BaseLayerModule):
    def init(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, self.conf.get_output_type(input_type)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        y = jnp.pad(x, ((0, 0), (c.pad_top, c.pad_bottom),
                        (c.pad_left, c.pad_right), (0, 0)))
        return y, state, mask


@register_impl("LocalResponseNormalization")
class LocalResponseNormalizationModule(BaseLayerModule):
    """Cross-channel LRN on NHWC; the reduce_window over the channel axis fuses
    into one XLA kernel (reference runtime:
    nn/layers/normalization/LocalResponseNormalization.java, cuDNN helper
    deeplearning4j-cuda/.../CudnnLocalResponseNormalizationHelper.java)."""

    def init(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        n = int(c.n)
        half = n // 2
        sq = x * x
        win = lax.reduce_window(sq, 0.0, lax.add, (1, 1, 1, n), (1, 1, 1, 1),
                                ((0, 0), (0, 0), (0, 0), (half, n - 1 - half)))
        denom = (c.k + c.alpha * win) ** c.beta
        return x / denom, state, mask


@register_impl("LayerNormalization")
class LayerNormalizationModule(BaseLayerModule):
    """Layer norm over the last axis (stateless; NEW — the reference's 2017
    layer set has no LayerNormalization). Per-position mean/variance keep
    transformer activations stable regardless of batch composition."""

    def init(self, rng, input_type, dtype=jnp.float32):
        c = self.conf
        n = int(c.n_in)
        params = {"gamma": jnp.ones((n,), dtype),
                  "beta": jnp.zeros((n,), dtype)}
        return params, {}, input_type

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        mu = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.mean(jnp.square(x - mu), axis=-1, keepdims=True)
        y = (x - mu) * lax.rsqrt(var + c.eps)
        y = y * params["gamma"] + params["beta"]
        return self.activation_fn()(y), state, mask


@register_impl("BatchNormalization")
class BatchNormalizationModule(BaseLayerModule):
    """Batch normalization over the channel (last) axis for NHWC or the feature
    axis for [b,f] (reference runtime: nn/layers/normalization/BatchNormalization.java:55,
    cuDNN helper CudnnBatchNormalizationHelper.java). Running stats live in
    layer state and are updated functionally inside the compiled train step."""

    def init(self, rng, input_type, dtype=jnp.float32):
        c = self.conf
        n = int(c.n_in)
        params = {}
        if not c.lock_gamma_beta:
            params["gamma"] = jnp.full((n,), c.gamma, dtype)
            params["beta"] = jnp.full((n,), c.beta, dtype)
        state = {"mean": jnp.zeros((n,), dtype), "var": jnp.ones((n,), dtype)}
        return params, state, input_type

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        axes = tuple(range(x.ndim - 1))  # all but channel/feature
        # statistics ACCUMULATE in the state dtype (f32 under bf16 mixed
        # precision — bf16 accumulation loses the small batch-to-batch deltas
        # the running stats depend on), but the per-element normalization
        # stays in the input dtype so the channel-sized scale/shift fuses into
        # the surrounding bf16 elementwise chain without f32 HBM traffic
        in_dt = x.dtype
        stat_dt = state["mean"].dtype
        if train:
            mean = jnp.mean(x, axis=axes, dtype=stat_dt)
            if in_dt == stat_dt:
                # full-precision path: two-pass variance (gradient-check exact)
                var = jnp.mean(jnp.square(x - mean), axis=axes, dtype=stat_dt)
            else:
                # mixed-precision path: one-pass shifted variance
                # E[(x−μ₀)²] − (E[x]−μ₀)² so both reductions fuse into a
                # single read of the bf16 activation (the two-pass form
                # re-reads x; ~40 ms/step across ResNet-50's 53 BN layers).
                # μ₀ is the mean of a strided subsample of THIS batch — it
                # lands within O(std/√n_sub) of the true mean, so the shifted
                # second moment has the same magnitude as the variance itself
                # and f32 rounding stays relative to var (a μ₀ far from the
                # data — e.g. the running mean at step 0, zeros — degenerates
                # to E[x²]−E[x]² and cancels catastrophically when
                # |mean| >> std). The subsample is a slice, so its reduction
                # reads a fraction of x and fuses alongside the main pass.
                sub = x[(slice(None),) + tuple(
                    slice(None, None, max(1, x.shape[a] // 8))
                    for a in range(1, x.ndim - 1))]
                mu0 = lax.stop_gradient(
                    jnp.mean(sub, axis=tuple(range(sub.ndim - 1)),
                             dtype=stat_dt))
                d = x.astype(stat_dt) - mu0
                ex2c = jnp.mean(jnp.square(d), axis=axes, dtype=stat_dt)
                var = jnp.maximum(ex2c - jnp.square(mean - mu0), 0.0)
            decay = c.decay
            new_state = {
                "mean": decay * state["mean"] + (1 - decay) * mean,
                "var": decay * state["var"] + (1 - decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + c.eps)          # f32, channel-sized
        if "gamma" in params:
            scale = params["gamma"].astype(stat_dt) * inv
            shift = params["beta"].astype(stat_dt) - mean * scale
        else:
            scale = c.gamma * inv
            shift = c.beta - mean * scale
        y = x * scale.astype(in_dt) + shift.astype(in_dt)
        return self.activation_fn()(y), new_state, mask


@register_impl("GlobalPoolingLayer")
class GlobalPoolingLayerModule(BaseLayerModule):
    """Mask-aware global pooling over time ([b,t,f] -> [b,f]) or space
    ([b,h,w,c] -> [b,c]) (reference: nn/layers/pooling/GlobalPoolingLayer.java,
    masked reductions via util/MaskedReductionUtil.java)."""

    def init(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, self.conf.get_output_type(input_type)

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        pt = c.pooling_type
        if x.ndim == 3:  # [b, t, f] with optional mask [b, t]
            if mask is not None:
                m = mask[:, :, None].astype(x.dtype)
                if pt == "max":
                    y = jnp.max(jnp.where(m > 0, x, -jnp.inf), axis=1)
                elif pt == "sum":
                    y = jnp.sum(x * m, axis=1)
                elif pt == "avg":
                    y = jnp.sum(x * m, axis=1) / jnp.maximum(jnp.sum(m, axis=1), 1.0)
                elif pt == "pnorm":
                    p = float(c.pnorm)
                    y = jnp.sum((jnp.abs(x) * m) ** p, axis=1) ** (1.0 / p)
                else:
                    raise ValueError(pt)
                return y, state, None
            axis = (1,)
        elif x.ndim == 4:  # [b, h, w, c]
            axis = (1, 2)
        else:
            raise ValueError(f"GlobalPooling expects rank-3 or rank-4 input, got {x.shape}")
        if pt == "max":
            y = jnp.max(x, axis=axis)
        elif pt == "avg":
            y = jnp.mean(x, axis=axis)
        elif pt == "sum":
            y = jnp.sum(x, axis=axis)
        elif pt == "pnorm":
            p = float(c.pnorm)
            y = jnp.sum(jnp.abs(x) ** p, axis=axis) ** (1.0 / p)
        else:
            raise ValueError(pt)
        return y, state, None
