"""Runtime layer SPI.

Mirrors the reference's Layer contract (nn/api/Layer.java:37 — activate :202,
backpropGradient :119, feedForwardMaskArray :309) with a TPU-first twist:
layers are pure functions of (params, state, input); the backward pass is
derived by JAX autodiff instead of hand-written backpropGradient, and the whole
network's forward+backward+update traces into a single XLA computation.

A custom layer can still provide its own gradient by wrapping its forward in
jax.custom_vjp — that is the analog of the reference's hand-written layers.

State = non-trainable per-layer variables (e.g. batch-norm running stats,
center-loss centers). Mask = per-timestep validity [batch, time] for
variable-length sequences (reference: Layer.feedForwardMaskArray).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..activations import get_activation

LAYER_IMPL_REGISTRY: dict = {}


def register_impl(conf_cls_name):
    def deco(cls):
        LAYER_IMPL_REGISTRY[conf_cls_name] = cls
        return cls
    return deco


def create_layer(conf):
    cls = LAYER_IMPL_REGISTRY.get(type(conf).__name__)
    if cls is None:
        raise ValueError(f"No runtime implementation for layer config {type(conf).__name__}")
    return cls(conf)


def apply_dropout(x, rate, train, rng):
    """Inverted dropout on the layer *input*, matching the reference
    (nn/conf dropout semantics, util/Dropout.java: applied to input at train time)."""
    if not train or rate is None or rate <= 0.0 or rng is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(rng, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


class BaseLayerModule:
    """One instantiated layer: shape-aware param init + pure forward."""

    def __init__(self, conf):
        self.conf = conf

    # -- init ---------------------------------------------------------------
    def init(self, rng, input_type, dtype=jnp.float32):
        """Returns (params: dict, state: dict, output_type)."""
        raise NotImplementedError

    # -- forward ------------------------------------------------------------
    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        """Returns (activations, new_state, out_mask)."""
        raise NotImplementedError

    # -- optional: output-layer protocol -------------------------------------
    def is_output_layer(self):
        return False

    # -- optional: pretrainable protocol (AE/RBM/VAE) -------------------------
    def is_pretrainable(self):
        return False

    def activation_fn(self):
        return get_activation(self.conf.activation or "identity")

    def num_params(self, params):
        return sum(int(p.size) for p in jax.tree_util.tree_leaves(params))
