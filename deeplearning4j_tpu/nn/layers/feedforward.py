"""Dense / Embedding / output-layer family / AutoEncoder / RBM runtime.

Reference counterparts: nn/layers/feedforward/dense/DenseLayer.java,
feedforward/embedding/EmbeddingLayer.java, BaseOutputLayer.java, LossLayer.java,
training/CenterLossOutputLayer.java, feedforward/autoencoder/AutoEncoder.java,
feedforward/rbm/RBM.java.

Param keys follow the reference's DefaultParamInitializer ("W", "b") so the
flattened-view checkpoint layout is recognizable.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import BaseLayerModule, register_impl, apply_dropout
from ..activations import get_activation
from ..losses import get_loss
from ..weights import init_weights
from ..conf.inputs import InputType


class _DenseCore(BaseLayerModule):
    def init(self, rng, input_type, dtype=jnp.float32):
        c = self.conf
        n_in, n_out = int(c.n_in), int(c.n_out)
        k1, _ = jax.random.split(rng)
        # Kernel stored [n_in, n_out]: row-major activations @ W hits the MXU
        # directly (the reference stores [n_out, n_in] and transposes in gemm).
        params = {
            "W": init_weights(k1, (n_in, n_out), c.weight_init, fan_in=n_in,
                              fan_out=n_out, distribution=c.dist, dtype=dtype),
            "b": jnp.full((n_out,), c.bias_init or 0.0, dtype),
        }
        from ..conf.inputs import RecurrentInputType
        out_t = (InputType.recurrent(n_out)
                 if isinstance(input_type, RecurrentInputType)
                 else InputType.feed_forward(n_out))
        return params, {}, out_t

    def preoutput(self, params, x):
        # rank-3 [b, t, f] stays time-distributed (one batched gemm — beyond
        # the reference, which needs RnnToFeedForward wrapping); only rank-4
        # CNN activations flatten
        if x.ndim > 3:
            x = x.reshape(x.shape[0], -1)
        return x @ params["W"] + params["b"]

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        x = apply_dropout(x, self.conf.dropout, train, rng)
        z = self.preoutput(params, x)
        return self.activation_fn()(z), state, mask


@register_impl("DenseLayer")
class DenseLayerModule(_DenseCore):
    pass


@register_impl("EmbeddingLayer")
class EmbeddingLayerModule(BaseLayerModule):
    """Index lookup: mathematically a one-hot matmul, implemented as a gather
    (reference: feedforward/embedding/EmbeddingLayer.java)."""

    def init(self, rng, input_type, dtype=jnp.float32):
        c = self.conf
        params = {"W": init_weights(rng, (int(c.n_in), int(c.n_out)), c.weight_init,
                                    fan_in=c.n_in, fan_out=c.n_out,
                                    distribution=c.dist, dtype=dtype)}
        if getattr(c, "has_bias", True):
            params["b"] = jnp.full((int(c.n_out),), c.bias_init or 0.0, dtype)
        return params, {}, InputType.feed_forward(int(c.n_out))

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        if x.ndim >= 2 and x.shape[-1] == int(self.conf.n_in) and x.shape[-1] > 1:
            idx = jnp.argmax(x, axis=-1)  # one-hot input accepted like reference
        else:
            idx = x.reshape(x.shape[0]).astype(jnp.int32)
        out = params["W"][idx]
        if "b" in params:
            out = out + params["b"]
        return self.activation_fn()(out), state, mask


class BaseOutputLayerModule(_DenseCore):
    """Dense + integrated loss (reference: BaseOutputLayer.java)."""

    def is_output_layer(self):
        return True

    def loss_fn(self):
        return get_loss(self.conf.loss)

    def score(self, params, x, labels, mask=None, train=False, rng=None):
        x = apply_dropout(x, self.conf.dropout, train, rng)
        z = self.preoutput(params, x)
        return self.loss_fn()(labels, z, self.conf.activation, mask)


@register_impl("OutputLayer")
class OutputLayerModule(BaseOutputLayerModule):
    pass


@register_impl("RnnOutputLayer")
class RnnOutputLayerModule(BaseOutputLayerModule):
    """Applies the dense projection per timestep on [b,t,f]
    (reference: nn/layers/recurrent/RnnOutputLayer.java)."""

    def preoutput(self, params, x):
        return x @ params["W"] + params["b"]

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        z = self.preoutput(params, x)
        return self.activation_fn()(z), state, mask

    def score(self, params, x, labels, mask=None, train=False, rng=None):
        z = self.preoutput(params, x)
        b, t = z.shape[0], z.shape[1]
        z2 = z.reshape(b * t, -1)
        lab2 = labels.reshape(b * t, -1)
        m2 = mask.reshape(b * t) if mask is not None else None
        return self.loss_fn()(lab2, z2, self.conf.activation, m2)


@register_impl("LossLayer")
class LossLayerModule(BaseLayerModule):
    """Parameterless loss on incoming activations (reference: LossLayer.java)."""

    def init(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.activation_fn()(x), state, mask

    def is_output_layer(self):
        return True

    def score(self, params, x, labels, mask=None, train=False, rng=None):
        return get_loss(self.conf.loss)(labels, x, self.conf.activation, mask)


@register_impl("CenterLossOutputLayer")
class CenterLossOutputLayerModule(BaseOutputLayerModule):
    """Softmax output + center loss (reference:
    nn/layers/training/CenterLossOutputLayer.java). Class centers live in
    layer state, updated by exponential moving average toward the masked
    feature means (the reference's alpha update), not by the optimizer."""

    def init(self, rng, input_type, dtype=jnp.float32):
        params, state, out = super().init(rng, input_type, dtype)
        state = dict(state)
        state["centers"] = jnp.zeros((int(self.conf.n_out), int(self.conf.n_in)), dtype)
        return params, state, out

    def score(self, params, x, labels, mask=None, train=False, rng=None, state=None):
        base = super().score(params, x, labels, mask, train, rng)
        centers = state["centers"] if state is not None else jnp.zeros(
            (int(self.conf.n_out), int(self.conf.n_in)), x.dtype)
        assigned = labels @ centers  # [b, n_in] center of each example's class
        center_l = 0.5 * jnp.mean(jnp.sum((x - assigned) ** 2, axis=-1))
        return base + self.conf.lambda_ * center_l

    def update_centers(self, state, x, labels):
        """EMA center update (alpha), called from the train step with
        stop_gradient'd features."""
        centers = state["centers"]
        counts = jnp.sum(labels, axis=0)[:, None] + 1.0
        sums = labels.T @ jax.lax.stop_gradient(x)
        delta = (centers * jnp.sum(labels, axis=0)[:, None] - sums) / counts
        new_centers = centers - self.conf.alpha * delta
        out = dict(state)
        out["centers"] = new_centers
        return out


@register_impl("AutoEncoder")
class AutoEncoderModule(_DenseCore):
    """Denoising autoencoder (reference: feedforward/autoencoder/AutoEncoder.java).
    Supervised forward = encoder; pretrain loss = reconstruction of corrupted
    input through tied-ish decoder (separate visible bias, shared W^T)."""

    def init(self, rng, input_type, dtype=jnp.float32):
        params, state, out = super().init(rng, input_type, dtype)
        params["vb"] = jnp.zeros((int(self.conf.n_in),), dtype)
        return params, state, out

    def is_pretrainable(self):
        return True

    def pretrain_loss(self, params, x, rng):
        c = self.conf
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        corrupted = x
        if c.corruption_level and c.corruption_level > 0 and rng is not None:
            keep = jax.random.bernoulli(rng, 1.0 - c.corruption_level, x.shape)
            corrupted = jnp.where(keep, x, 0.0)
        h = self.activation_fn()(corrupted @ params["W"] + params["b"])
        recon_pre = h @ params["W"].T + params["vb"]
        loss = get_loss(c.loss)(x, recon_pre, c.activation, None)
        if c.sparsity and c.sparsity > 0:
            loss = loss + c.sparsity * jnp.mean(jnp.abs(h))
        return loss


@register_impl("RBM")
class RBMModule(_DenseCore):
    """Restricted Boltzmann machine with CD-k pretraining (reference:
    feedforward/rbm/RBM.java). Supervised forward = propup probabilities."""

    def init(self, rng, input_type, dtype=jnp.float32):
        params, state, out = super().init(rng, input_type, dtype)
        params["vb"] = jnp.zeros((int(self.conf.n_in),), dtype)
        return params, state, out

    def is_pretrainable(self):
        return True

    def _propup(self, params, v):
        pre = v @ params["W"] + params["b"]
        hu = self.conf.hidden_unit
        if hu == "binary" or hu == "softmax":
            return jax.nn.sigmoid(pre) if hu == "binary" else jax.nn.softmax(pre)
        if hu == "rectified":
            return jax.nn.relu(pre)
        return pre  # gaussian

    def _propdown(self, params, h):
        pre = h @ params["W"].T + params["vb"]
        if self.conf.visible_unit == "binary":
            return jax.nn.sigmoid(pre)
        return pre  # gaussian

    def pretrain_loss(self, params, x, rng):
        """CD-k free-energy-difference surrogate: autodiff of
        FE(data) - FE(model sample) reproduces the CD gradient; the Gibbs
        chain itself is stop-gradient'd (the TPU-friendly formulation — the
        reference hand-codes the W/vb/hb gradient from the chain ends)."""
        c = self.conf
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        v0 = x
        vk = v0
        key = rng if rng is not None else jax.random.PRNGKey(0)
        for _ in range(max(1, int(c.k))):
            key, k1, k2 = jax.random.split(key, 3)
            ph = self._propup(params, vk)
            h = jax.random.bernoulli(k1, jnp.clip(ph, 0, 1)).astype(x.dtype) \
                if c.hidden_unit == "binary" else ph
            pv = self._propdown(params, h)
            vk = jax.random.bernoulli(k2, jnp.clip(pv, 0, 1)).astype(x.dtype) \
                if c.visible_unit == "binary" else pv
        vk = jax.lax.stop_gradient(vk)

        def free_energy(v):
            wx_b = v @ params["W"] + params["b"]
            vbias_term = v @ params["vb"]
            hidden_term = jnp.sum(jax.nn.softplus(wx_b), axis=-1)
            return -hidden_term - vbias_term

        return jnp.mean(free_energy(v0) - free_energy(vk))


@register_impl("MixtureOfExpertsLayer")
class MixtureOfExpertsLayerModule(BaseLayerModule):
    """Dense mixture-of-experts FFN (conf: nn/conf/layers.py
    MixtureOfExpertsLayer — NEW, no reference counterpart). Expert weights
    are expert-major [E, ...]; sharding axis 0 over a mesh "model" axis
    yields expert parallelism (GSPMD partitions the einsums and all-reduces
    the gated mix)."""

    def init(self, rng, input_type, dtype=jnp.float32):
        c = self.conf
        n_in, n_out = int(c.n_in), int(c.n_out)
        E = int(c.n_experts)
        hidden = int(c.hidden_mult) * n_out
        k1, k2, k3 = jax.random.split(rng, 3)
        mk = lambda k, shape, fi, fo: init_weights(
            k, shape, c.weight_init, fan_in=fi, fan_out=fo,
            distribution=c.dist, dtype=dtype)
        params = {
            "Wg": mk(k1, (n_in, E), n_in, E),              # router
            "W1": mk(k2, (E, n_in, hidden), n_in, hidden),  # expert up-proj
            "b1": jnp.zeros((E, hidden), dtype),
            "W2": mk(k3, (E, hidden, n_out), hidden, n_out),
            "b2": jnp.zeros((E, n_out), dtype),
        }
        from ..conf.inputs import RecurrentInputType
        out_t = (InputType.recurrent(n_out)
                 if isinstance(input_type, RecurrentInputType)
                 else InputType.feed_forward(n_out))
        return params, {}, out_t

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        x = apply_dropout(x, c.dropout, train, rng)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]                       # [b, 1, f]
        E = int(c.n_experts)
        k = min(int(c.top_k), E)
        gates = jax.nn.softmax(x @ params["Wg"], axis=-1)   # [b, t, E]
        if k < E:
            # zero all but the top-k gates, renormalize (standard MoE)
            thresh = jnp.sort(gates, axis=-1)[..., E - k][..., None]
            gates = jnp.where(gates >= thresh, gates, 0.0)
            gates = gates / jnp.maximum(
                jnp.sum(gates, axis=-1, keepdims=True), 1e-9)
        h = jnp.einsum("btf,efh->beth", x, params["W1"]) \
            + params["b1"][None, :, None, :]
        h = jax.nn.relu(h)
        y = jnp.einsum("beth,eho->beto", h, params["W2"]) \
            + params["b2"][None, :, None, :]
        out = jnp.einsum("bte,beto->bto", gates, y)
        out = self.activation_fn()(out)
        if squeeze:
            out = out[:, 0, :]
        return out, state, mask
