"""Recurrent layers: GravesLSTM (peepholes), LSTM, GravesBidirectionalLSTM.

Reference counterparts: nn/layers/recurrent/GravesLSTM.java,
GravesBidirectionalLSTM.java, and the shared math in LSTMHelpers.java (501 LoC;
forward :58, per-timestep Java gemm loop :172-174).

TPU-first: the per-timestep loop is a lax.scan whose body is ONE fused
[x_t, h_prev] @ W_combined gemm hitting the MXU, with gate nonlinearities fused
by XLA — versus the reference's 4 separate gemms + elementwise ops per step.
Sequence layout [batch, time, features]; scan runs over time with batch-major
carries. Masking: masked steps carry state through and emit zeros (matching the
reference's mask semantics for variable-length series).

Streaming inference (rnnTimeStep, reference MultiLayerNetwork.java ~:2100) is
supported via explicit carry in/out: forward(..., initial_state=..., return_state=True).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from .base import BaseLayerModule, register_impl, apply_dropout
from ..activations import get_activation
from ..weights import init_weights
from ..conf.inputs import InputType

# Gate order in the fused 4*n_out dimension: [input, forget, output, cell-candidate]
I, F, O, G = 0, 1, 2, 3


def _init_lstm_params(rng, n_in, n_out, conf, dtype, peephole):
    k1, k2, k3 = jax.random.split(rng, 3)
    params = {
        # W: input->gates [n_in, 4*n_out]; RW: recurrent [n_out, 4*n_out]
        "W": init_weights(k1, (n_in, 4 * n_out), conf.weight_init,
                          fan_in=n_in, fan_out=n_out, distribution=conf.dist, dtype=dtype),
        "RW": init_weights(k2, (n_out, 4 * n_out), conf.weight_init,
                           fan_in=n_out, fan_out=n_out, distribution=conf.dist, dtype=dtype),
        "b": jnp.zeros((4 * n_out,), dtype).at[F * n_out:(F + 1) * n_out].set(
            conf.forget_gate_bias_init),
    }
    if peephole:
        # peephole weights for input/forget (on c_prev) and output (on c_new)
        params["P"] = init_weights(k3, (3 * n_out,), "uniform", fan_in=n_out,
                                   fan_out=n_out, dtype=dtype)
    return params


def _lstm_scan(params, x, h0, c0, gate_act, cell_act, peephole, mask=None, reverse=False):
    """x: [b,t,n_in] -> outputs [b,t,n_out], final (h,c).

    Mixed precision: under bf16 compute the GEMMs run bf16 on the MXU and
    all gate arithmetic plus the CELL state accumulate in f32 — a bf16 cell
    carry drifts over the sequence (c_new = f*c + i*g compounds rounding
    every step; the reference's tuned LSTM keeps full-precision state for
    the same reason, LSTMHelpers.java). The HIDDEN carry stays in the
    compute dtype: h is fully re-derived from c each step (h = o*tanh(c),
    nothing compounds), and keeping it bf16 feeds the recurrent gemm
    without a per-step cast. Final carries return in the accumulation dtype
    so TBPTT windows see ONE stable carry dtype (no per-window retrace, no
    bf16 quantization of the cell state at window boundaries)."""
    n_out = params["RW"].shape[0]
    gate_fn = get_activation(gate_act)
    act_fn = get_activation(cell_act)
    W, RW, b = params["W"], params["RW"], params["b"]
    P = params.get("P")
    out_dt = x.dtype
    # any sub-32-bit float compute (bf16, and f16 with its 65504 max) gets
    # the f32 accumulation treatment
    acc_dt = (jnp.float32 if jnp.issubdtype(out_dt, jnp.floating)
              and jnp.finfo(out_dt).bits < 32 else out_dt)
    if P is not None:
        P = P.astype(acc_dt)

    def step(carry, inputs):
        h_prev, c_prev = carry            # out_dt, acc_dt
        if mask is not None:
            xz_t, m_t = inputs
        else:
            xz_t, m_t = inputs, None
        # the input projection was hoisted out of the scan (one [b*t, n_in]
        # gemm instead of t small ones — the MXU-friendly schedule); only the
        # recurrent gemm stays sequential (out_dt on the MXU, f32 out)
        z = xz_t.astype(acc_dt) + (h_prev @ RW).astype(acc_dt)
        zi, zf, zo, zg = (z[:, I * n_out:(I + 1) * n_out], z[:, F * n_out:(F + 1) * n_out],
                          z[:, O * n_out:(O + 1) * n_out], z[:, G * n_out:(G + 1) * n_out])
        if P is not None:
            zi = zi + P[:n_out] * c_prev
            zf = zf + P[n_out:2 * n_out] * c_prev
        i_g = gate_fn(zi)
        f_g = gate_fn(zf)
        g = act_fn(zg)
        c_new = f_g * c_prev + i_g * g
        if P is not None:
            zo = zo + P[2 * n_out:] * c_new
        o_g = gate_fn(zo)
        h_new = (o_g * act_fn(c_new)).astype(out_dt)
        if m_t is not None:
            m = m_t[:, None]
            h_out = h_new * m.astype(out_dt)
            h_new = jnp.where(m > 0, h_new, h_prev)
            c_new = jnp.where(m > 0, c_new, c_prev)
        else:
            h_out = h_new
        return (h_new, c_new), h_out

    xz_all = x @ W + b                # [b, t, 4n] single batched gemm
    xs = jnp.swapaxes(xz_all, 0, 1)   # [t, b, 4n]
    seq = (xs, jnp.swapaxes(mask, 0, 1)) if mask is not None else xs
    (h_f, c_f), outs = lax.scan(step, (h0.astype(out_dt), c0.astype(acc_dt)),
                                seq, reverse=reverse)
    return jnp.swapaxes(outs, 0, 1), (h_f.astype(acc_dt), c_f)


class _BaseLSTMModule(BaseLayerModule):
    peephole = True

    def init(self, rng, input_type, dtype=jnp.float32):
        c = self.conf
        params = _init_lstm_params(rng, int(c.n_in), int(c.n_out), c, dtype, self.peephole)
        return params, {}, InputType.recurrent(int(c.n_out))

    def init_carry(self, batch, dtype=jnp.float32):
        n_out = int(self.conf.n_out)
        return (jnp.zeros((batch, n_out), dtype), jnp.zeros((batch, n_out), dtype))

    def forward(self, params, state, x, *, train=False, rng=None, mask=None,
                initial_state=None, return_state=False):
        c = self.conf
        x = apply_dropout(x, c.dropout, train, rng)
        h0, c0 = initial_state if initial_state is not None else self.init_carry(
            x.shape[0], x.dtype)
        outs, final = _lstm_scan(params, x, h0, c0, c.gate_activation, c.activation,
                                 self.peephole, mask)
        if return_state:
            return outs, state, mask, final
        return outs, state, mask


@register_impl("GravesLSTM")
class GravesLSTMModule(_BaseLSTMModule):
    peephole = True


@register_impl("LSTM")
class LSTMModule(_BaseLSTMModule):
    peephole = False


@register_impl("GravesBidirectionalLSTM")
class GravesBidirectionalLSTMModule(BaseLayerModule):
    """Two independent peephole LSTMs over forward/reversed time; outputs are
    summed (reference: nn/layers/recurrent/GravesBidirectionalLSTM.java sums
    forward and backward activations into n_out)."""

    def init(self, rng, input_type, dtype=jnp.float32):
        c = self.conf
        kf, kb = jax.random.split(rng)
        params = {
            "fwd": _init_lstm_params(kf, int(c.n_in), int(c.n_out), c, dtype, True),
            "bwd": _init_lstm_params(kb, int(c.n_in), int(c.n_out), c, dtype, True),
        }
        return params, {}, InputType.recurrent(int(c.n_out))

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        x = apply_dropout(x, c.dropout, train, rng)
        b = x.shape[0]
        n_out = int(c.n_out)
        zeros = (jnp.zeros((b, n_out), x.dtype), jnp.zeros((b, n_out), x.dtype))
        out_f, _ = _lstm_scan(params["fwd"], x, *zeros, c.gate_activation,
                              c.activation, True, mask, reverse=False)
        out_b, _ = _lstm_scan(params["bwd"], x, *zeros, c.gate_activation,
                              c.activation, True, mask, reverse=True)
        return out_f + out_b, state, mask


@register_impl("SelfAttentionLayer")
class SelfAttentionLayerModule(BaseLayerModule):
    """Multi-head self-attention [b,t,f] -> [b,t,n_out] (NEW capability, no
    reference counterpart). QKV + output projections around flash-style
    blockwise attention; a key mask folds the sequence mask into the scores
    and zeroes masked outputs (same convention as the LSTM scan). For
    sequence-parallel long-context attention call
    parallel.ring_attention.ring_attention on the projections directly."""

    def init(self, rng, input_type, dtype=jnp.float32):
        c = self.conf
        n_in, n_out, H = int(c.n_in), int(c.n_out), int(c.n_heads)
        assert n_out % H == 0, "n_heads must evenly divide n_out"
        k1, k2, k3, k4 = jax.random.split(rng, 4)
        mk = lambda k, i, o: init_weights(k, (i, o), c.weight_init, fan_in=i,
                                          fan_out=o, distribution=c.dist,
                                          dtype=dtype)
        params = {
            "Wq": mk(k1, n_in, n_out), "Wk": mk(k2, n_in, n_out),
            "Wv": mk(k3, n_in, n_out), "Wo": mk(k4, n_out, n_out),
            "b": jnp.full((n_out,), c.bias_init or 0.0, dtype),
        }
        return params, {}, InputType.recurrent(n_out)

    def project_qkv(self, params, x):
        """[b,t,f] -> (q, k, v) each [b,t,H,Dh]. Split out of forward so the
        decode engine (decode/engine.py) can run the SAME projections when
        it appends one token's k/v to a KV-cache slot."""
        c = self.conf
        B, T, _ = x.shape
        H = int(c.n_heads)
        Dh = int(c.n_out) // H
        q = (x @ params["Wq"]).reshape(B, T, H, Dh)
        k = (x @ params["Wk"]).reshape(B, T, H, Dh)
        v = (x @ params["Wv"]).reshape(B, T, H, Dh)
        return q, k, v

    def attend(self, q, k, v, mask):
        """The kernel dispatch (shared by forward and the decode prefill)."""
        from ...parallel.ring_attention import attention_reference, \
            blockwise_attention
        c = self.conf
        T = q.shape[1]
        if getattr(c, "use_pallas", False):
            from ...kernels import flash_attention
            # block_size tunes the QUERY tile only; the key tile keeps the
            # kernel's swept default (1024) — forcing both to block_size
            # starved the MXU (256x256 measured ~1.7x slower than 256x1024
            # at T=4096 on a real v5e). Key masks fold into the kernel's
            # score tiles (fwd + both bwd), so ragged/packed batches keep
            # the fast path; untileable shapes fall back inside the call
            return flash_attention(q, k, v, causal=c.causal,
                                   block_q=int(c.block_size), key_mask=mask)
        if T % min(int(c.block_size), T) == 0:
            return blockwise_attention(q, k, v, block_size=int(c.block_size),
                                       causal=c.causal, key_mask=mask)
        return attention_reference(q, k, v, causal=c.causal, key_mask=mask)

    def finish(self, params, out, mask):
        """Output projection + activation + mask zeroing on the attention
        context [b,t,H,Dh] (shared by forward and both decode legs)."""
        c = self.conf
        B, T = out.shape[0], out.shape[1]
        out = out.reshape(B, T, int(c.n_out)) @ params["Wo"] + params["b"]
        out = self.activation_fn()(out)
        if mask is not None:
            out = out * mask[:, :, None]  # zero masked steps like the LSTM scan
        return out

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        c = self.conf
        attn_rng = None
        attn_drop = getattr(c, "attention_dropout", 0.0) or 0.0
        if rng is not None and attn_drop > 0:
            rng, attn_rng = jax.random.split(rng)
        x = apply_dropout(x, c.dropout, train, rng)
        q, k, v = self.project_qkv(params, x)
        out = self.attend(q, k, v, mask)
        out = apply_dropout(out, attn_drop, train, attn_rng)
        return self.finish(params, out, mask), state, mask
