"""ActivationLayer and DropoutLayer runtime (reference:
nn/layers/ActivationLayer.java, nn/layers/DropoutLayer.java)."""
from __future__ import annotations

import jax.numpy as jnp

from .base import BaseLayerModule, register_impl, apply_dropout


@register_impl("ActivationLayer")
class ActivationLayerModule(BaseLayerModule):
    def init(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        return self.activation_fn()(x), state, mask


@register_impl("DropoutLayer")
class DropoutLayerModule(BaseLayerModule):
    def init(self, rng, input_type, dtype=jnp.float32):
        return {}, {}, input_type

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        return apply_dropout(x, self.conf.dropout, train, rng), state, mask
