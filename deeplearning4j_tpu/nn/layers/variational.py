"""Variational autoencoder layer (reference:
nn/layers/variational/VariationalAutoencoder.java, 1063 LoC; config
nn/conf/layers/variational/VariationalAutoencoder.java).

Semantics match the reference: used inside a supervised net, forward() outputs
the posterior mean of p(z|x); pretraining maximises the ELBO with the
reparameterisation trick. Reconstruction distributions: gaussian (diagonal) and
bernoulli, mirroring the reference's GaussianReconstructionDistribution /
BernoulliReconstructionDistribution.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .base import BaseLayerModule, register_impl, apply_dropout
from ..activations import get_activation
from ..weights import init_weights
from ..conf.inputs import InputType


@register_impl("VariationalAutoencoder")
class VariationalAutoencoderModule(BaseLayerModule):
    def init(self, rng, input_type, dtype=jnp.float32):
        c = self.conf
        n_in, n_z = int(c.n_in), int(c.n_out)
        enc_sizes = [n_in] + [int(s) for s in c.encoder_layer_sizes]
        dec_sizes = [n_z] + [int(s) for s in c.decoder_layer_sizes]
        recon_mult = 2 if c.reconstruction_distribution == "gaussian" else 1
        params = {}
        keys = jax.random.split(rng, len(enc_sizes) + len(dec_sizes) + 3)
        ki = 0
        for i in range(len(enc_sizes) - 1):
            params[f"e{i}W"] = init_weights(keys[ki], (enc_sizes[i], enc_sizes[i + 1]),
                                            c.weight_init, fan_in=enc_sizes[i],
                                            fan_out=enc_sizes[i + 1], dtype=dtype)
            params[f"e{i}b"] = jnp.zeros((enc_sizes[i + 1],), dtype)
            ki += 1
        last_e = enc_sizes[-1]
        params["pZXMeanW"] = init_weights(keys[ki], (last_e, n_z), c.weight_init,
                                          fan_in=last_e, fan_out=n_z, dtype=dtype); ki += 1
        params["pZXMeanb"] = jnp.zeros((n_z,), dtype)
        params["pZXLogStd2W"] = init_weights(keys[ki], (last_e, n_z), c.weight_init,
                                             fan_in=last_e, fan_out=n_z, dtype=dtype); ki += 1
        params["pZXLogStd2b"] = jnp.zeros((n_z,), dtype)
        for i in range(len(dec_sizes) - 1):
            params[f"d{i}W"] = init_weights(keys[ki], (dec_sizes[i], dec_sizes[i + 1]),
                                            c.weight_init, fan_in=dec_sizes[i],
                                            fan_out=dec_sizes[i + 1], dtype=dtype)
            params[f"d{i}b"] = jnp.zeros((dec_sizes[i + 1],), dtype)
            ki += 1
        last_d = dec_sizes[-1]
        params["pXZW"] = init_weights(keys[ki], (last_d, n_in * recon_mult), c.weight_init,
                                      fan_in=last_d, fan_out=n_in * recon_mult, dtype=dtype)
        params["pXZb"] = jnp.zeros((n_in * recon_mult,), dtype)
        return params, {}, InputType.feed_forward(n_z)

    def _encode(self, params, x):
        c = self.conf
        act = get_activation(c.activation or "identity")
        h = x
        for i in range(len(c.encoder_layer_sizes)):
            h = act(h @ params[f"e{i}W"] + params[f"e{i}b"])
        mean = get_activation(c.pzx_activation)(h @ params["pZXMeanW"] + params["pZXMeanb"])
        log_var = h @ params["pZXLogStd2W"] + params["pZXLogStd2b"]
        return mean, log_var

    def _decode(self, params, z):
        c = self.conf
        act = get_activation(c.activation or "identity")
        h = z
        for i in range(len(c.decoder_layer_sizes)):
            h = act(h @ params[f"d{i}W"] + params[f"d{i}b"])
        return h @ params["pXZW"] + params["pXZb"]

    def forward(self, params, state, x, *, train=False, rng=None, mask=None):
        x = apply_dropout(x, self.conf.dropout, train, rng)
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        mean, _ = self._encode(params, x)
        return mean, state, mask

    def is_pretrainable(self):
        return True

    def pretrain_loss(self, params, x, rng):
        """Negative ELBO, reparameterised; mean over batch."""
        c = self.conf
        if x.ndim > 2:
            x = x.reshape(x.shape[0], -1)
        mean, log_var = self._encode(params, x)
        kl = -0.5 * jnp.sum(1.0 + log_var - mean ** 2 - jnp.exp(log_var), axis=-1)
        total = jnp.zeros(x.shape[0], x.dtype)
        key = rng if rng is not None else jax.random.PRNGKey(0)
        n_s = max(1, int(c.num_samples))
        for _ in range(n_s):
            key, sub = jax.random.split(key)
            eps = jax.random.normal(sub, mean.shape, mean.dtype)
            z = mean + jnp.exp(0.5 * log_var) * eps
            out = self._decode(params, z)
            if c.reconstruction_distribution == "bernoulli":
                logp = -jax.nn.softplus(-out) * x - jax.nn.softplus(out) * (1.0 - x)
                rec = -jnp.sum(logp, axis=-1)
            else:  # gaussian: out = [mean | log_var]
                n_in = x.shape[-1]
                rmean, rlogv = out[:, :n_in], out[:, n_in:]
                rec = 0.5 * jnp.sum(rlogv + (x - rmean) ** 2 / jnp.exp(rlogv)
                                    + jnp.log(2 * jnp.pi), axis=-1)
            total = total + rec
        return jnp.mean(total / n_s + kl)

    def generate_at_mean(self, params, z):
        """Decode latent points to reconstruction-distribution means
        (reference: VariationalAutoencoder.generateAtMeanGivenZ)."""
        out = self._decode(params, z)
        c = self.conf
        if c.reconstruction_distribution == "bernoulli":
            return jax.nn.sigmoid(out)
        n_in = int(self.conf.n_in)
        return out[:, :n_in]
