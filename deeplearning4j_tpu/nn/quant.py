"""Quantization codecs — the designated quant/dequant module (ROADMAP item 3).

BENCH_r05 put the headline train step AT the HBM roofline
(`roofline_binding=hbm`, `roofline_util≈1.0`): further speed means moving
fewer bytes. ZeRO-1 (parallel/zero.py) already removed the *redundant*
optimizer-state pool; this module removes precision from the two pools that
remain — moment precision for training and weight precision for serving —
the same reduced-precision-primitives direction the cuDNN paper
(PAPERS.md [1]) takes for inference.

Two codecs live here, and ONLY here (graftlint GL014 `quant-silent-widening`
flags float32 widening of quantized leaves anywhere else):

1. `MomentCodec` — bf16 / 8-bit block-wise optimizer moments. The 8-bit
   format is block-wise fp8-e4m3 codes with one POWER-OF-TWO scale per
   block (chosen by `frexp`/`ldexp` bit manipulation so `absmax/scale`
   lands in [128, 256), clipped to ±240). Two deliberate choices:

   - LOG-SPACED codes, not linear int8: Adam's second moment spans many
     orders of magnitude *within* a block, and a linear absmax grid rounds
     the small entries to zero — `update = m_hat/(sqrt(0)+eps)` then
     divides by eps and the run detonates (measured: a linear-int8 variant
     blew a toy MLP 15 units of weight in 10 steps). e4m3's binades keep
     ~6% relative error down to absmax/2^17, which second moments tolerate
     and first moments don't notice.
   - EXACT round-trips: pow2 scales make `codes * scale` an exact float op
     and re-encoding a decoded block reproduces the same scale and codes
     bit-for-bit. That idempotence is what makes the round-trip safe
     without stochastic rounding: conversion chains — checkpoint → restore
     → re-shard → re-shard — never compound quantization error, they
     replay it. (Stochastic-rounding codecs deliberately randomize the
     round, so each hop would drift; here only *training steps* move the
     moments.)

   Codecs operate on the FLAT zero-padded vectors of the ZeRO flatten-pad
   layout (parallel/zero.py), with blocks anchored at offset 0 — so the
   same canonical values re-encode to identical codes at ANY shard count
   (the zero padding beyond the real data quantizes to zero regardless of
   how much of it a given shard count appends).

2. `WeightQuant` — per-channel symmetric int8 weight quantization for the
   serving path. Eligible leaves (floating, ndim >= 2, weight-named) are
   replaced IN the param tree by their int8 codes; scales ride on the
   WeightQuant object and the dequant (`codes * scale`, broadcast over the
   last/output-channel axis) is traced INTO the jitted inference
   executables, so HBM holds and reads the narrow weights and the widening
   happens in-register on the way into the matmul. The float originals are
   kept as a host-side numpy backup (`restore_params`) so serializers write
   f32 zips and a failed parity gate can undo the quantization.

`quantize_model_weights` is the deploy-time entry: quantize + accuracy
parity gate in one move — breach restores the f32 weights and raises
`QuantParityError`, so a deploy can never silently ship a model whose int8
outputs diverged.
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np
import jax
import jax.numpy as jnp


MOMENT_DTYPES = ("f32", "bf16", "q8")

# blocks scale so absmax/scale lands in [128, 256); codes clip to +-240 so
# fp8 rounding can never cross the 256 binade boundary (which would flip
# the re-derived scale and break bitwise idempotence)
_Q_EXP = 8
_Q_CLIP = 240.0
_Q_MAX = 127.0     # int8 weight-quant ceiling (per-channel serving codes)


def _pow2_scale(absmax):
    """The power of two with absmax/scale in [128, 256) — exact via
    frexp/ldexp bit manipulation (no libm log2 rounding), so re-encoding a
    decoded block reproduces the identical scale. absmax == 0 -> scale 1."""
    _, e = jnp.frexp(absmax)                 # absmax = m * 2^e, m in [.5, 1)
    scale = jnp.ldexp(jnp.ones_like(absmax), e - _Q_EXP)
    return jnp.where(absmax > 0, scale, jnp.ones_like(absmax))


class MomentCodec:
    """bf16 / blockwise-int8 codec for the flat padded moment vectors of the
    ZeRO layout. One instance per ZeroUpdater; `dtype` in ("bf16", "q8")."""

    def __init__(self, dtype, n_shards=1, block=128):
        if dtype not in ("bf16", "q8"):
            raise ValueError(f"moment dtype {dtype!r} not in ('bf16', 'q8')")
        self.dtype = dtype
        self.n = max(1, int(n_shards))
        self.block = int(block)
        # q8 codes pad to a multiple of block*n so both the codes and the
        # per-block scales divide the data axis evenly
        self.granule = self.block * self.n

    # ------------------------------------------------------------ encode
    def encode(self, v):
        """f32 flat [L] (L a multiple of n_shards) -> stored representation:
        bf16 [L], or {"qcodes": fp8-e4m3 [L2], "qscale": f32 [L2/block]}
        with L2 = L rounded up to the granule (extra tail is zeros)."""
        if self.dtype == "bf16":
            return v.astype(jnp.bfloat16)
        L = v.shape[0]
        L2 = -(-L // self.granule) * self.granule
        if L2 > L:
            v = jnp.pad(v, (0, L2 - L))
        b = v.reshape(-1, self.block)
        scale = _pow2_scale(jnp.max(jnp.abs(b), axis=1)).astype(jnp.float32)
        q = jnp.clip(b / scale[:, None], -_Q_CLIP, _Q_CLIP)
        return {"qcodes": q.astype(jnp.float8_e4m3fn).reshape(-1),
                "qscale": scale}

    # ------------------------------------------------------------ decode
    def decode(self, enc, length):
        """Stored representation -> f32 flat [length]. Exact: fp8 code *
        pow2 scale never rounds, so decode(encode(decode(x))) == decode(x)."""
        if self.dtype == "bf16":
            return enc.astype(jnp.float32)
        q = enc["qcodes"].reshape(-1, self.block).astype(jnp.float32)
        v = (q * enc["qscale"][:, None]).reshape(-1)
        return v[:length]

    def is_encoded(self, leaf):
        """True for nodes this codec produced (pytree traversal stop)."""
        if self.dtype == "bf16":
            return (hasattr(leaf, "dtype") and getattr(leaf, "ndim", 0) == 1
                    and leaf.dtype == jnp.bfloat16)
        return isinstance(leaf, dict) and "qcodes" in leaf


# ---------------------------------------------------------------------------
# int8 weight quantization (serving)
# ---------------------------------------------------------------------------

# param keys that are NOT weights: biases, norm stats/affine, center-loss
# centers (mirrors network._is_weight_key)
_NON_WEIGHT_KEYS = ("gamma", "beta", "centers", "mean", "var")


def _is_quantizable_weight(key, leaf):
    return (hasattr(leaf, "ndim") and leaf.ndim >= 2
            and hasattr(leaf, "dtype")
            and jnp.issubdtype(leaf.dtype, jnp.floating)
            and not str(key).endswith("b")
            and str(key) not in _NON_WEIGHT_KEYS)


def quantize_weight(w):
    """Per-channel symmetric int8: one exact-absmax scale per OUTPUT channel
    (the last axis — dense [in, out], conv HWIO, LSTM [in, 4H] columns).
    Returns (codes int8, scale f32 [n_out])."""
    red = tuple(range(w.ndim - 1))
    absmax = jnp.max(jnp.abs(w), axis=red)
    scale = jnp.where(absmax > 0, absmax / _Q_MAX,
                      jnp.ones_like(absmax)).astype(jnp.float32)
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -_Q_MAX, _Q_MAX)
    return q.astype(jnp.int8), scale


def dequantize_weight(codes, scale):
    """Traced into the inference executable: the int8 codes are the
    HBM-resident operand; the widening multiply fuses into the consumer."""
    return codes.astype(scale.dtype) * scale


class WeightQuant:
    """Scales + host-side f32 backup for a weight-quantized param tree.

    `build` replaces eligible leaves of the (two-level {layer: {name: arr}})
    param tree with int8 codes; `dequant` is the traceable inverse the
    inference executables fuse (scales are closure constants — a few floats
    per channel); `restore_params` rebuilds the f32 tree from the backup
    (serializers write f32 zips; a failed parity gate un-quantizes)."""

    def __init__(self, scales, backup, dtype="int8"):
        self.scales = scales       # {layer: {name: f32 [n_out]}}
        self.backup = backup       # {layer: {name: host np f32 array}}
        self.dtype = dtype

    @staticmethod
    def build(params, dtype="int8"):
        if dtype != "int8":
            raise ValueError(f"weight quant dtype {dtype!r} != 'int8'")
        scales, backup, out = {}, {}, {}
        for lk, sub in params.items():
            new_sub = dict(sub)
            for k, leaf in sub.items():
                if not _is_quantizable_weight(k, leaf):
                    continue
                codes, scale = quantize_weight(leaf)
                scales.setdefault(lk, {})[k] = scale
                backup.setdefault(lk, {})[k] = np.asarray(leaf)
                new_sub[k] = codes
            out[lk] = new_sub
        if not scales:
            raise ValueError("no quantizable weight leaves found")
        return WeightQuant(scales, backup, dtype), out

    def dequant(self, params):
        """Traceable: int8 code leaves -> widened weights; everything else
        passes through untouched."""
        out = {}
        for lk, sub in params.items():
            lscales = self.scales.get(lk)
            if not lscales:
                out[lk] = sub
                continue
            out[lk] = {k: (dequantize_weight(v, lscales[k])
                           if k in lscales else v)
                       for k, v in sub.items()}
        return out

    def restore_params(self, params):
        out = {}
        for lk, sub in params.items():
            lback = self.backup.get(lk, {})
            out[lk] = {k: (jnp.asarray(lback[k]) if k in lback else v)
                       for k, v in sub.items()}
        return out


# ---------------------------------------------------------------------------
# deploy-time parity gate
# ---------------------------------------------------------------------------


class QuantParityError(RuntimeError):
    """int8 outputs diverged from f32 beyond the gate; the model was
    restored to f32 before raising."""

    def __init__(self, report):
        super().__init__(f"quantization parity gate breached: {report}")
        self.report = report


@dataclass
class QuantGate:
    """Accuracy-parity thresholds for a quantized deploy: classification
    heads must agree on >= `min_top1_agreement` of the parity rows AND the
    worst output delta must stay under `max_rel_delta` of the f32 output
    range."""
    max_rel_delta: float = 0.1
    min_top1_agreement: float = 0.97


def parity_report(ref, quant):
    """Compare f32 vs quantized outputs: max |delta| relative to the f32
    output range, plus top-1 agreement when the output looks like a
    distribution over classes (last dim > 1)."""
    ref = np.asarray(ref, np.float64)
    quant = np.asarray(quant, np.float64)
    span = float(max(np.max(np.abs(ref)), 1e-9))
    max_rel = float(np.max(np.abs(ref - quant))) / span
    top1 = None
    if ref.ndim >= 2 and ref.shape[-1] > 1:
        top1 = float(np.mean(np.argmax(ref, -1) == np.argmax(quant, -1)))
    return {"max_rel_delta": round(max_rel, 6),
            "top1_agreement": None if top1 is None else round(top1, 6)}


def quantize_model_weights(model, dtype="int8", parity_inputs=None,
                           gate=None):
    """Quantize `model`'s weights for serving, gated on accuracy parity.

    With `parity_inputs`, the f32 outputs are snapshotted first, the model
    is quantized, and the quantized outputs must pass `gate` — a breach
    restores the f32 weights and raises QuantParityError, so the caller's
    deploy fails with the model unchanged. Without parity inputs the
    quantization is applied ungated (callers measuring accuracy end-to-end,
    e.g. bench.py's ucidigits/real32 deltas). Returns the parity report."""
    gate = gate if gate is not None else QuantGate()
    if parity_inputs is None:
        model.quantize_weights(dtype)
        return {"gated": False, "dtype": dtype}
    x = np.asarray(parity_inputs)
    ref = np.asarray(model.output(x))
    model.quantize_weights(dtype)
    quant = np.asarray(model.output(x))
    report = parity_report(ref, quant)
    report.update(gated=True, dtype=dtype, rows=int(x.shape[0]))
    breach = report["max_rel_delta"] > gate.max_rel_delta or (
        report["top1_agreement"] is not None
        and report["top1_agreement"] < gate.min_top1_agreement)
    if breach:
        model.dequantize_weights()
        raise QuantParityError(report)
    return report


def synthetic_parity_inputs(model, batch=16, seed=0):
    """A deterministic standard-normal parity batch shaped from the model's
    configured input type, or None when the conf carries no input shape
    (the caller must then supply explicit parity inputs)."""
    t = getattr(model.conf, "input_type", None)
    if t is None:
        types = getattr(model.conf, "input_types", None)
        t = types[0] if types else None
    if t is None:
        return None
    rng = np.random.default_rng(seed)
    kind = getattr(t, "kind", None)
    if kind == "ff":
        shape = (batch, t.size)
    elif kind == "recurrent":
        shape = (batch, int(getattr(t, "timesteps", None) or 16), t.size)
    elif kind in ("cnn", "cnn_flat"):
        shape = (batch, t.height, t.width, t.channels)
    else:
        return None
    return rng.normal(size=shape).astype(np.float32)
