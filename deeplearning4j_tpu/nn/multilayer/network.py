"""MultiLayerNetwork: the sequential-stack model and #1 user entry point.

Reference: nn/multilayer/MultiLayerNetwork.java (2444 LoC; init :385,
fit(DataSetIterator) :902, computeGradientAndScore :1729, backprop :973,
output :1462, feedForwardToLayer :692, pretrain :164, doTruncatedBPTT :1064,
rnnTimeStep ~:2100, score(DataSet) :1629).

TPU-first redesign: instead of a Java per-layer interpreter loop calling
hand-written backpropGradient per layer, the ENTIRE minibatch step —
forward, loss, backward (autodiff), gradient normalization, updater
(optax: LR schedule + momentum/adam state), parameter update, batch-norm
running-stat update — traces into ONE jit-compiled XLA computation with donated
parameter/optimizer buffers (the functional analog of the reference's in-place
flattened param view, Model.setParamsViewArray nn/api/Model.java:123).
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
import optax

from ..conf.configuration import MultiLayerConfiguration, BackpropType
from ..layers.base import create_layer
from ..layers import feedforward, convolution, recurrent, misc, variational  # noqa: F401 (register impls)
from ..multistep import MultiStepTrainable
from ..updaters import apply_gradient_normalization
from ...optimize.listeners import resolve_listeners
from ...telemetry.trace import get_tracer
from ...telemetry.xla import timed_first_call


def _is_weight_key(k):
    return not (k.endswith("b") or k in ("gamma", "beta", "centers", "mean", "var"))


class MultiLayerNetwork(MultiStepTrainable):
    def __init__(self, conf: MultiLayerConfiguration):
        self.conf = conf
        self.layers = [create_layer(lc) for lc in conf.layers]
        self.params = None          # {"0": {...}, "1": {...}}
        self.states = None          # non-trainable per-layer state
        self.opt_state = None
        self._tx = None
        self.listeners = []
        self.iteration_count = 0
        self.epoch_count = 0
        self._score_dev = float("nan")
        self.last_gradients = None   # most recent step's gradients (StatsListener)
        self._dtype = jnp.dtype(conf.dtype)
        self._rng = jax.random.PRNGKey(conf.seed)
        self._rnn_state = {}        # streaming inference carries per layer idx
        self._jit_cache = {}
        self._ingest = None         # device-side ingest fused into the step
        self._zero = None           # ZeRO-1 sharded update (parallel/zero.py)
        self._wq = None             # int8 serving weights (nn/quant.py)

    @property
    def score_value(self):
        """Most recent minibatch score. The train step leaves the score ON
        DEVICE (a host readback through the TPU runtime costs orders of
        magnitude more than the step itself); the device→host sync happens
        lazily here, only when something actually reads the score."""
        s = self._score_dev
        if not isinstance(s, float):
            s = float(s)
            self._score_dev = s
        return s

    @score_value.setter
    def score_value(self, v):
        self._score_dev = v

    # ------------------------------------------------------------------ init
    def init(self, params=None):
        """Initialize parameters (reference: MultiLayerNetwork.init :385)."""
        conf = self.conf
        rng = jax.random.PRNGKey(conf.seed)
        self.params, self.states = {}, {}
        cur_type = conf.input_type
        for i, layer in enumerate(self.layers):
            rng, sub = jax.random.split(rng)
            pre = conf.input_preprocessors.get(i)
            if cur_type is not None and pre is not None:
                cur_type = pre.output_type(cur_type)
            elif cur_type is not None and cur_type.kind == "cnn_flat":
                from ..conf.inputs import InputType
                cur_type = InputType.feed_forward(cur_type.flat_size())
            p, s, out_type = layer.init(sub, cur_type, self._dtype)
            self.params[str(i)] = p
            self.states[str(i)] = s
            cur_type = out_type
        if params is not None:
            self.set_params(params)
        self._build_updater()
        return self

    def _build_updater(self, init_state=True):
        """Per-layer optax transforms (each layer may override the updater —
        reference: LayerUpdater per layer, UpdaterCreator). With a ZeRO-1
        updater installed (set_update_sharding), the per-layer transforms
        wrap into the sharded-update transform instead."""
        from ..updaters import layer_transform, per_layer_transform
        transforms = {str(i): layer_transform(lc)
                      for i, lc in enumerate(self.conf.layers)}
        if self._zero is not None:
            self._tx = self._zero.wrap(transforms, self.params)
        else:
            self._tx = per_layer_transform(transforms)
        if init_state:
            self.opt_state = self._tx.init(self.params)

    # -------------------------------------------------------------- forward
    def _apply_preprocessor(self, i, x, mask, rng=None):
        pre = self.conf.input_preprocessors.get(i)
        if pre is not None:
            x = pre(x, mask, rng=rng)
            mask = pre.feed_forward_mask(mask) if mask is not None else None
        return x, mask

    def _forward(self, params, states, x, *, train, rng, mask=None, to_layer=None,
                 initial_carries=None, collect=False):
        """Run layers [0, to_layer); returns (activations, new_states, mask,
        final_carries, collected)."""
        n = len(self.layers) if to_layer is None else to_layer
        new_states = dict(states)
        carries = {}
        collected = []
        cur_mask = mask
        for i in range(n):
            layer = self.layers[i]
            if rng is not None:
                rng, pre_rng, sub = jax.random.split(rng, 3)
            else:
                pre_rng = sub = None
            x, cur_mask = self._apply_preprocessor(i, x, cur_mask, rng=pre_rng)
            kwargs = {}
            if initial_carries is not None and str(i) in initial_carries:
                kwargs = {"initial_state": initial_carries[str(i)], "return_state": True}
            out = layer.forward(params[str(i)], states[str(i)], x, train=train,
                                rng=sub, mask=cur_mask, **kwargs)
            if len(out) == 4:
                x, new_s, cur_mask, final = out
                carries[str(i)] = final
            else:
                x, new_s, cur_mask = out
            new_states[str(i)] = new_s
            if collect:
                collected.append(x)
        return x, new_states, cur_mask, carries, collected

    # ------------------------------------------------------- mixed precision
    def _compute_dtype(self):
        """Mixed-precision compute dtype, or None when compute == param dtype."""
        cd = getattr(self.conf, "compute_dtype", None)
        if cd is None or jnp.dtype(cd) == self._dtype:
            return None
        return jnp.dtype(cd)

    @staticmethod
    def _cast_floats(tree, dt):
        return jax.tree_util.tree_map(
            lambda a: a.astype(dt)
            if hasattr(a, "dtype") and jnp.issubdtype(a.dtype, jnp.floating) else a,
            tree)

    def _cast_for_compute(self, params, x, *, keep_f32=()):
        """Cast params + input to the compute dtype for the MXU-bound layers;
        layers named in keep_f32 (the output/loss layers) keep the param dtype
        so softmax/cross-entropy run in full precision. BatchNorm statistics
        stay f32 inside the layer itself (layers/convolution.py)."""
        cd = self._compute_dtype()
        if cd is None:
            return params, x
        params = {k: (v if k in keep_f32 else self._cast_floats(v, cd))
                  for k, v in params.items()}
        if hasattr(x, "dtype") and (jnp.issubdtype(x.dtype, jnp.floating)
                                    or x.dtype == jnp.uint8):
            # uint8 covers the image-pixels-on-the-wire path: values 0..255
            # are exact in bf16 (ImageScalerPreProcessor rescales on-chip).
            # Wider integer inputs (embedding token ids) must NOT be cast —
            # ids > 256 are not representable in bf16.
            x = x.astype(cd)
        return params, x

    # ------------------------------------------------------------- loss/score
    def _loss(self, params, states, x, y, *, train, rng, mask=None, label_mask=None,
              initial_carries=None):
        out_idx = len(self.layers) - 1
        params, x = self._cast_for_compute(params, x, keep_f32=(str(out_idx),))
        if rng is not None:
            rng, fwd_rng, pre_rng = jax.random.split(rng, 3)
        else:
            fwd_rng = pre_rng = None
        # conf.remat recomputes (policy-chosen) activations in the backward
        # instead of storing them (nn/remat.py) — training only

        def fwd_fn(p, s, xx, rr, mm, ic):
            return self._forward(p, s, xx, train=train, rng=rr, mask=mm,
                                 to_layer=out_idx, initial_carries=ic)
        from ..remat import maybe_checkpoint
        fwd_fn = maybe_checkpoint(
            fwd_fn, getattr(self.conf, "remat", None) if train else None)
        feats, new_states, cur_mask, carries, _ = fwd_fn(
            params, states, x, fwd_rng, mask, initial_carries)
        out_layer = self.layers[out_idx]
        feats, cur_mask = self._apply_preprocessor(out_idx, feats, cur_mask,
                                                   rng=pre_rng)
        if self._compute_dtype() is not None:
            feats = feats.astype(self._dtype)  # loss math in full precision
        if not out_layer.is_output_layer():
            raise ValueError("Last layer is not an output/loss layer")
        lm = label_mask if label_mask is not None else cur_mask
        if isinstance(out_layer, feedforward.CenterLossOutputLayerModule):
            score = out_layer.score(params[str(out_idx)], feats, y, lm, train, rng,
                                    state=states[str(out_idx)])
            new_states[str(out_idx)] = out_layer.update_centers(states[str(out_idx)], feats, y)
        else:
            score = out_layer.score(params[str(out_idx)], feats, y, lm, train, rng)
        score = score + self._reg_score(params)
        return score, (new_states, carries)

    def _reg_score(self, params):
        """L1/L2 terms (reference: BaseLayer.calcL1/calcL2 added into score)."""
        total = 0.0
        for i, lc in enumerate(self.conf.layers):
            l1 = lc.l1 or 0.0
            l2 = lc.l2 or 0.0
            l1b = lc.l1_bias or 0.0
            l2b = lc.l2_bias or 0.0
            if l1 == 0 and l2 == 0 and l1b == 0 and l2b == 0:
                continue
            for k, p in params[str(i)].items():
                if _is_weight_key(k):
                    if l1:
                        total = total + l1 * jnp.sum(jnp.abs(p))
                    if l2:
                        total = total + 0.5 * l2 * jnp.sum(p ** 2)
                else:
                    if l1b:
                        total = total + l1b * jnp.sum(jnp.abs(p))
                    if l2b:
                        total = total + 0.5 * l2b * jnp.sum(p ** 2)
        return total

    def _normalize_grads(self, grads):
        out = {}
        for i, lc in enumerate(self.conf.layers):
            g = grads[str(i)]
            if lc.gradient_normalization and g:
                g = apply_gradient_normalization(
                    g, lc.gradient_normalization,
                    lc.gradient_normalization_threshold or 1.0)
            out[str(i)] = g
        return out

    # ------------------------------------------------------- device ingest
    def set_ingest(self, ingest):
        """Fuse a device-side ingest transform (etl.device_transform
        .DeviceIngest, or any object with traceable `apply_features` /
        `apply_labels`) into the jitted TRAIN step: batches then ship as raw
        narrow arrays (uint8/int codes) and decode/cast/normalize/one-hot
        run as the first fused XLA ops of the step — one executable, no
        extra dispatch, 4x+ fewer host-link bytes. Training paths only
        (fit/fit_batch/scanned multistep); output()/score()/solvers keep
        consuming preprocessed tensors. Clears the jit cache so every
        executable re-traces with the ingest ops fused."""
        self._ingest = ingest
        self._jit_cache.clear()
        return self

    def _apply_ingest(self, x, y):
        """Traced at the top of every train step. Post-ingest casts replay
        the non-ingest `_prep_batch` semantics on device: signed-int inputs
        (embedding ids) pass through, everything else lands on the param
        dtype; labels always land on the param dtype."""
        ing = self._ingest
        if ing is None:
            return x, y
        x = ing.apply_features(x)
        if not jnp.issubdtype(x.dtype, jnp.signedinteger) \
                and x.dtype != self._dtype:
            x = x.astype(self._dtype)
        y = ing.apply_labels(y)
        if y.dtype != self._dtype:
            y = y.astype(self._dtype)
        return x, y

    # ---------------------------------------------------------------- train
    def _make_train_step(self, tbptt=False):
        tx = self._tx

        def train_step(params, opt_state, states, rng, x, y, mask, label_mask, carries):
            x, y = self._apply_ingest(x, y)

            def loss_fn(p):
                return self._loss(p, states, x, y, train=True, rng=rng, mask=mask,
                                  label_mask=label_mask,
                                  initial_carries=carries if tbptt else None)
            (score, (new_states, out_carries)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = self._normalize_grads(grads)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, new_states, score, out_carries, grads

        # tbptt also donates the LSTM carries (arg 8): out_carries aliases
        # the incoming h/c buffers instead of allocating 2*layers fresh
        # [B, H] arrays per window — the non-scanned sibling of the
        # multi_tbptt carry donation, same HBM-bytes-are-milliseconds
        # argument (BENCH_r05 roofline_util~1.0). The std step passes
        # carries=None (zero pytree leaves), so donating it there is a no-op.
        donate = (0, 1, 2, 8) if tbptt else (0, 1, 2)
        return jax.jit(train_step, donate_argnums=donate)

    def _get_train_step(self, key):
        if key not in self._jit_cache:
            # first call compiles the XLA executable; timed_first_call
            # attributes that cost to jit_compiles_total in the telemetry
            # registry (the Julia-TPU paper's compile-vs-run accounting)
            self._jit_cache[key] = timed_first_call(
                self._make_train_step(tbptt="tbptt" in key),
                f"train_step:{key}")
        return self._jit_cache[key]

    def fit(self, data, labels=None, epochs=1, steps_per_execution=1,
            prefetch=None, ingest=None):
        """Train. `data` may be a DataSetIterator-like (including an
        etl.ParallelPipelineExecutor), a DataSet, or (x, y) arrays
        (reference: fit(DataSetIterator) :902 and fit(INDArray,INDArray)).

        steps_per_execution=K compiles K optimizer steps into ONE executable
        (lax.scan with donated carry — see nn/multistep.py): one host
        dispatch per K minibatches instead of the reference's per-minibatch
        loop (StochasticGradientDescent.java:51-72). Listeners then fire on
        a K-step cadence; ragged tails and incompatible groups (TBPTT
        windowing, non-SGD solvers, mismatched shapes) fall back to
        per-batch steps.

        prefetch=K wraps the iterator in an etl.DevicePrefetcher with a
        K-deep buffer (2 = double, 3 = triple buffering): batch N+1's
        host->device transfer overlaps batch N's compute, so the jit step
        traces arrays that are already device-resident.

        ingest=DeviceIngest(...) (equivalent to set_ingest beforehand) fuses
        device-side decode/cast/normalize/one-hot into the SAME compiled
        step, so prefetch transfers narrow raw bytes and the first fused
        XLA ops do the widening on-chip."""
        from ...datasets.dataset import DataSet
        from ...datasets.iterator.base import as_iterator
        if ingest is not None:
            self.set_ingest(ingest)
        if labels is not None:
            data = DataSet(data, labels)
        it = as_iterator(data)
        wrapped = None
        if prefetch:
            from ...etl.prefetch import DevicePrefetcher
            it = wrapped = DevicePrefetcher(it, queue_size=int(prefetch))
        K = max(1, int(steps_per_execution))
        tracer = get_tracer()          # no-op span per epoch when disabled
        try:
            for _ in range(epochs):
                with tracer.span("epoch", epoch=self.epoch_count):
                    for listener in self.listeners:
                        listener.on_epoch_start(self)
                    it.reset()
                    if K > 1:
                        self._fit_grouped(it, K)
                    else:
                        for ds in it:
                            self.fit_batch(ds)
                    for listener in self.listeners:
                        listener.on_epoch_end(self)
                self.epoch_count += 1
        except BaseException:
            if wrapped is not None:
                try:
                    wrapped.close()
                except Exception:
                    pass           # don't mask the primary training error
            raise
        if wrapped is not None:
            wrapped.close()        # stop the fit-owned prefetch thread
        return self

    def _prep_batch(self, ds):
        """(x, y, mask, lmask) as device arrays — the per-step leaves both
        fit_batch and the scanned multi-step path consume. With an ingest
        fused (`set_ingest`) the arrays stay RAW/NARROW — the widening cast
        happens inside the compiled step, not here."""
        if self._ingest is not None:
            x = jnp.asarray(ds.features)
            y = jnp.asarray(ds.labels)
            mask = None if ds.features_mask is None else jnp.asarray(ds.features_mask, self._dtype)
            lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask, self._dtype)
            return x, y, mask, lmask
        x = jnp.asarray(ds.features, self._dtype) \
            if not str(ds.features.dtype).startswith("int") else jnp.asarray(ds.features)
        y = jnp.asarray(ds.labels, self._dtype)
        mask = None if ds.features_mask is None else jnp.asarray(ds.features_mask, self._dtype)
        lmask = None if ds.labels_mask is None else jnp.asarray(ds.labels_mask, self._dtype)
        return x, y, mask, lmask

    def _scan_loss(self, p, states, x, y, rng, mask, lmask):
        x, y = self._apply_ingest(x, y)
        score, (new_states, _) = self._loss(p, states, x, y, train=True,
                                            rng=rng, mask=mask,
                                            label_mask=lmask)
        return score, new_states

    def _multi_step_mode(self, prepped):
        from ..conf.configuration import OptimizationAlgorithm
        x = prepped[0]
        if self.conf.optimization_algo != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
            return None
        if self._listeners_need_gradients():
            return None
        if (self.conf.backprop_type == BackpropType.TRUNCATED_BPTT
                and x.ndim == 3 and x.shape[1] > self.conf.tbptt_fwd_length):
            # windows scan only when they tile the sequence exactly
            return "tbptt" if x.shape[1] % self.conf.tbptt_fwd_length == 0 \
                else None
        return "std"

    def _prepare_tbptt(self, prepped):
        """Flatten K TBPTT batches into one [K*W, ...] window scan: every
        batch contributes W = T/L windows, a `first` flag resets the carried
        recurrent state at batch boundaries, and an rng table replays
        EXACTLY the splits K fit_batch calls would draw (one step key per
        batch, one sub-key per window), advancing self._rng identically."""
        L = self.conf.tbptt_fwd_length
        T = prepped[0][0].shape[1]
        W = T // L
        K = len(prepped)

        def win(a, dims3):
            # [B, T, ...] -> [W, B, L, ...]; non-temporal arrays replicate
            if a is None:
                return None
            if a.ndim in dims3 and a.shape[1] == T:
                parts = [a[:, w * L:(w + 1) * L] for w in range(W)]
                return jnp.stack(parts)
            return jnp.stack([a] * W)

        stacked = []
        for (x, y, mask, lmask) in prepped:
            stacked.append((win(x, (3,)), win(y, (3,)), win(mask, (2, 3)),
                            win(lmask, (2, 3))))
        # [K, W, ...] -> [K*W, ...]
        flat = jax.tree_util.tree_map(
            lambda *a: jnp.concatenate(a), *stacked)
        firsts = jnp.tile(jnp.arange(W) == 0, K)              # [K*W]

        @jax.jit
        def rng_table(r):
            def outer(r, _):
                r, step = jax.random.split(r)

                def inner(s, _):
                    s, sub = jax.random.split(s)
                    return s, sub
                _, subs = jax.lax.scan(inner, step, None, length=W)
                return r, subs
            r, tab = jax.lax.scan(outer, r, None, length=K)
            return r, tab.reshape((K * W,) + tab.shape[2:])

        self._rng, rngs = rng_table(self._rng)
        return "tbptt", (flat + (firsts, rngs)), K

    def _run_prepared_tbptt(self, stacked, K):
        tx = self._tx
        if "multi_tbptt" not in self._jit_cache:
            def multi_tbptt(params, opt_state, states, carries, stacked):
                def body(carry, batch):
                    params, opt_state, states, carries = carry
                    x, y, mask, lmask, first, sub = batch
                    x, y = self._apply_ingest(x, y)
                    carries = jax.tree_util.tree_map(
                        lambda c: jnp.where(first, jnp.zeros_like(c), c),
                        carries)

                    def loss_fn(p):
                        return self._loss(p, states, x, y, train=True,
                                          rng=sub, mask=mask,
                                          label_mask=lmask,
                                          initial_carries=carries)
                    (score, (new_states, new_carries)), grads = \
                        jax.value_and_grad(loss_fn, has_aux=True)(params)
                    grads = self._normalize_grads(grads)
                    updates, opt_state = tx.update(grads, opt_state, params)
                    params = optax.apply_updates(params, updates)
                    return (params, opt_state, new_states, new_carries), score

                # final carries ARE an output: the donated carry buffers can
                # alias them, so donation sticks instead of warning "Some
                # donated buffers were not usable" (at roofline_util≈1.0,
                # HBM bytes saved are milliseconds saved — BENCH_r05)
                (params, opt_state, states, carries), scores = jax.lax.scan(
                    body, (params, opt_state, states, carries), stacked)
                return params, opt_state, states, carries, scores
            self._jit_cache["multi_tbptt"] = timed_first_call(
                jax.jit(multi_tbptt, donate_argnums=(0, 1, 2, 3)),
                "train_step:multi_tbptt")
        B = jax.tree_util.tree_leaves(stacked)[0].shape[1]
        carries = self._zero_carries(B, self._dtype)
        (self.params, self.opt_state, self.states, _,
         win_scores) = self._jit_cache["multi_tbptt"](
            self.params, self.opt_state, self.states, carries, stacked)
        # per-batch score = mean over that batch's windows (singles parity)
        return win_scores.reshape(K, -1).mean(axis=1)

    def fit_batch(self, ds):
        """One minibatch step — one XLA computation on device."""
        if self.params is None:
            self.init()
        self._check_trainable()        # int8 serving weights can't train
        tracer = get_tracer()          # no-op spans when tracing is off
        with tracer.span("iteration", iteration=self.iteration_count):
            x, y, mask, lmask = self._prep_batch(ds)
            self._rng, step_rng = jax.random.split(self._rng)

            from ..conf.configuration import OptimizationAlgorithm
            if self.conf.optimization_algo != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
                # second-order / line-search solvers work on the flattened param
                # vector (reference: Solver.java:55 factory on OptimizationAlgorithm);
                # one solver instance per model so its compiled fns are reused
                if getattr(self, "_flat_solver", None) is None:
                    from ...optimize.solvers import make_solver
                    self._flat_solver = make_solver(
                        self.conf.optimization_algo, self,
                        line_search_iterations=self.conf.max_num_line_search_iterations)
                with tracer.span("solver_step"):
                    self._flat_solver.optimize(x, y, mask, lmask)
            elif (self.conf.backprop_type == BackpropType.TRUNCATED_BPTT and x.ndim == 3
                    and x.shape[1] > self.conf.tbptt_fwd_length):
                self._fit_tbptt(x, y, mask, lmask, step_rng)
            else:
                step = self._get_train_step("std")
                with tracer.span("jit_step", rows=int(x.shape[0])):
                    (self.params, self.opt_state, self.states, score, _,
                     self.last_gradients) = step(
                        self.params, self.opt_state, self.states, step_rng,
                        x, y, mask, lmask, None)
                self.score_value = score  # device scalar; syncs lazily on read
            self.iteration_count += 1
            for listener in self.listeners:
                if hasattr(listener, "record_batch_size"):
                    listener.record_batch_size(x.shape[0])
                listener.iteration_done(self, self.iteration_count)
        if not any(getattr(l, "wants_gradients", False) for l in self.listeners):
            # don't pin a params-sized gradient pytree on device between steps
            self.last_gradients = None

    def _fit_tbptt(self, x, y, mask, lmask, rng):
        """Truncated BPTT (reference: doTruncatedBPTT :1064): slide a window of
        tbptt_fwd_length over time, carrying recurrent state (stop-gradient)
        across windows."""
        T = x.shape[1]
        L = self.conf.tbptt_fwd_length
        carries = self._zero_carries(x.shape[0], x.dtype)
        step = self._get_train_step("tbptt")
        scores = []
        for start in range(0, T, L):
            end = min(start + L, T)
            xw = x[:, start:end]
            yw = y[:, start:end] if y.ndim == 3 else y
            mw = mask[:, start:end] if mask is not None else None
            lmw = lmask[:, start:end] if lmask is not None else None
            rng, sub = jax.random.split(rng)
            # gradient truncation at window edges is inherent: each window's
            # value_and_grad differentiates params only; carries enter the next
            # step as concrete (non-differentiated) arguments
            with get_tracer().span("jit_step", window_start=start):
                (self.params, self.opt_state, self.states, score, carries,
                 self.last_gradients) = step(
                    self.params, self.opt_state, self.states, sub, xw, yw,
                    mw, lmw, carries)
            scores.append(score)
        # mean stays on device; syncs lazily when score_value is read
        self.score_value = jnp.mean(jnp.stack(scores))

    def _zero_carries(self, batch, dtype):
        carries = {}
        for i, layer in enumerate(self.layers):
            if hasattr(layer, "init_carry"):
                carries[str(i)] = layer.init_carry(batch, dtype)
        return carries

    # ------------------------------------------------------------ inference
    def output(self, x, train=False, mask=None):
        """Full forward pass (reference: output :1462). Jitted per input shape.
        train=True uses train-mode semantics (batch statistics for BN); dropout
        stays off because no rng is threaded through inference. `mask`
        ([batch, time] validity for 3-D sequence inputs) flows to every layer
        like in training — the serving batcher's padded+masked length buckets
        ride through here."""
        if self.params is None:
            self.init()
        x = jnp.asarray(x)
        masked = mask is not None
        key = ("output", bool(train), masked)
        if key not in self._jit_cache:
            is_train = bool(train)

            def fwd(params, states, xx, mm):
                # int8 serving weights: the executable's params inputs ARE
                # the narrow codes; this traced dequant fuses the widening
                # into the consumers (nn/quant.py)
                params = self._dequant_params(params)
                params, xx = self._cast_for_compute(
                    params, xx, keep_f32=(str(len(self.layers) - 1),))
                out, _, _, _, _ = self._forward(params, states, xx,
                                                train=is_train, rng=None,
                                                mask=mm)
                return out.astype(self._dtype)
            self._jit_cache[key] = timed_first_call(
                jax.jit(fwd), f"output:train={bool(train)},mask={masked}")
        return self._jit_cache[key](
            self.params, self.states, x,
            None if mask is None else jnp.asarray(mask, self._dtype))

    def feed_forward(self, x, train=False):
        """Per-layer activations list (reference: feedForward)."""
        x = jnp.asarray(x)
        _, _, _, _, acts = self._forward(self._dequant_params(self.params),
                                         self.states, x, train=train,
                                         rng=None, collect=True)
        return acts

    def feed_forward_to_layer(self, layer_idx, x, train=False):
        """(reference: feedForwardToLayer :692) — activations up to and
        including layer_idx."""
        x = jnp.asarray(x)
        out, _, _, _, _ = self._forward(self._dequant_params(self.params),
                                        self.states, x, train=train,
                                        rng=None, to_layer=layer_idx + 1)
        return out

    def score(self, ds_or_x, labels=None, train=False):
        """Mean loss on data (reference: score(DataSet) :1629)."""
        if labels is not None:
            x, y, mask, lmask = ds_or_x, labels, None, None
        else:
            x, y = ds_or_x.features, ds_or_x.labels
            mask = ds_or_x.features_mask
            lmask = ds_or_x.labels_mask
        s, _ = self._loss(self._dequant_params(self.params), self.states,
                          jnp.asarray(x), jnp.asarray(y),
                          train=train, rng=None,
                          mask=None if mask is None else jnp.asarray(mask),
                          label_mask=None if lmask is None else jnp.asarray(lmask))
        return float(s)

    def compute_gradient_and_score(self, x, y, mask=None, label_mask=None):
        """(reference: computeGradientAndScore :1729) — used by gradient checks."""
        def loss_fn(p):
            s, _ = self._loss(p, self.states, jnp.asarray(x), jnp.asarray(y),
                              train=False, rng=None,
                              mask=None if mask is None else jnp.asarray(mask),
                              label_mask=None if label_mask is None else jnp.asarray(label_mask))
            return s
        score, grads = jax.value_and_grad(loss_fn)(self.params)
        return grads, float(score)

    # ------------------------------------------------------- rnn streaming
    def rnn_time_step(self, x):
        """Stateful streaming inference (reference: rnnTimeStep ~:2100):
        feeds one or more timesteps, keeps hidden state between calls."""
        x = jnp.asarray(x)
        squeeze = x.ndim == 2
        if squeeze:
            x = x[:, None, :]
        carries = self._rnn_state or self._zero_carries(x.shape[0], self._dtype)
        out, _, _, new_carries, _ = self._forward(
            self._dequant_params(self.params), self.states, x, train=False,
            rng=None, initial_carries=carries)
        self._rnn_state = new_carries
        return out[:, -1] if squeeze and out.ndim == 3 else out

    def rnn_clear_previous_state(self):
        self._rnn_state = {}

    def rnn_get_previous_state(self, layer_idx):
        return self._rnn_state.get(str(layer_idx))

    def rnn_set_previous_state(self, layer_idx, state):
        self._rnn_state[str(layer_idx)] = state

    # generate() — greedy KV-cache decode — lives on MultiStepTrainable
    # (shared with ComputationGraph, like set_update_sharding)

    # ------------------------------------------------------------ pretrain
    def pretrain(self, data, epochs=1):
        """Greedy layerwise unsupervised pretraining for AE/RBM/VAE layers
        (reference: pretrain :164)."""
        for i, layer in enumerate(self.layers):
            if layer.is_pretrainable():
                self.pretrain_layer(i, data, epochs)
        return self

    def pretrain_layer(self, idx, data, epochs=1):
        from ...datasets.iterator.base import as_iterator
        layer = self.layers[idx]
        if not layer.is_pretrainable():
            return self
        lc = self.conf.layers[idx]
        tx = lc.updater.to_optax()
        lp = self.params[str(idx)]
        opt_state = tx.init(lp)

        def pstep(lp, opt_state, rng, feats):
            def loss_fn(p):
                return layer.pretrain_loss(p, feats, rng)
            loss, grads = jax.value_and_grad(loss_fn)(lp)
            updates, opt_state = tx.update(grads, opt_state, lp)
            return optax.apply_updates(lp, updates), opt_state, loss
        # the layer params + updater state rebind every call, so their
        # buffers alias in place instead of a fresh allocation per batch
        # (GL010 — same contract as the main train steps)
        pstep = jax.jit(pstep, donate_argnums=(0, 1))

        it = as_iterator(data)
        for _ in range(epochs):
            it.reset()
            for ds in it:
                x = jnp.asarray(ds.features, self._dtype)
                full = dict(self.params)
                full[str(idx)] = lp
                feats, _, _, _, _ = self._forward(full, self.states, x, train=False,
                                                  rng=None, to_layer=idx)
                feats, _ = self._apply_preprocessor(idx, feats, None)
                self._rng, sub = jax.random.split(self._rng)
                lp, opt_state, loss = pstep(lp, opt_state, sub, feats)
                self.score_value = loss  # device scalar; syncs lazily on read
        self.params[str(idx)] = lp
        return self

    # -------------------------------------------------------------- params
    def param_table(self):
        """{(layer, name): array} (reference: Model.paramTable)."""
        out = {}
        for i, p in self.params.items():
            for k, v in p.items():
                out[f"{i}_{k}"] = v
        return out

    def num_params(self):
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(self.params))

    def get_flat_params(self):
        """Flattened param vector in deterministic (layer, name) order —
        the analog of the reference's flattened view (Model.params())."""
        leaves = []
        for i in range(len(self.layers)):
            p = self.params[str(i)]
            for k in sorted(p.keys()):
                leaves.append(np.asarray(p[k]).ravel())
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate(leaves)

    def set_flat_params(self, flat):
        flat = np.asarray(flat)
        off = 0
        for i in range(len(self.layers)):
            p = self.params[str(i)]
            for k in sorted(p.keys()):
                n = int(np.prod(p[k].shape)) if p[k].shape else 1
                p[k] = jnp.asarray(flat[off:off + n].reshape(p[k].shape), p[k].dtype)
                off += n
        return self

    def set_params(self, params):
        self.params = jax.tree_util.tree_map(jnp.asarray, params)
        return self

    def set_listeners(self, *listeners):
        self.listeners = resolve_listeners(listeners)
        return self

    def add_listener(self, listener):
        self.listeners.append(listener)
        return self

    # ------------------------------------------------------------ evaluate
    def evaluate(self, iterator, top_n=1):
        """top_n > 1 also tracks top-N accuracy (reference:
        MultiLayerNetwork.evaluate(iter, labels, topN))."""
        from ...eval.evaluation import Evaluation
        from ...datasets.iterator.base import as_iterator
        e = Evaluation(top_n=top_n)
        it = as_iterator(iterator)
        it.reset()
        for ds in it:
            out = self.output(ds.features)
            e.eval(np.asarray(ds.labels), np.asarray(out),
                   None if ds.labels_mask is None else np.asarray(ds.labels_mask))
        return e

    def clone(self):
        net = MultiLayerNetwork(self.conf)
        if self.params is not None:
            net.init(params=jax.tree_util.tree_map(jnp.array, self.params))
            net.states = jax.tree_util.tree_map(jnp.array, self.states)
        return net

    def summary(self):
        lines = ["idx | layer | params"]
        for i, (lc, layer) in enumerate(zip(self.conf.layers, self.layers)):
            n = sum(int(x.size) for x in jax.tree_util.tree_leaves(self.params[str(i)])) \
                if self.params else 0
            lines.append(f"{i} | {type(lc).__name__} | {n}")
        lines.append(f"total params: {self.num_params() if self.params else 0}")
        return "\n".join(lines)
