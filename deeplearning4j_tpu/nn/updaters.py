"""Updaters (optimizers), learning-rate schedules, and gradient normalization.

Capability parity with the reference's updater system:
- updater set: nn/conf/Updater.java:9-18 (SGD, ADAM, ADADELTA, ADAGRAD,
  RMSPROP, NESTEROVS, NONE/CUSTOM)
- LR schedules: nn/updater/LayerUpdater.java:135-155 (Exponential, Inverse,
  Step, TorchStep, Poly, Sigmoid, explicit Schedule map)
- gradient normalization: nn/updater/LayerUpdater.java:182-194
  (RenormalizeL2PerLayer, RenormalizeL2PerParamType, ClipElementWiseAbsoluteValue,
  ClipL2PerLayer, ClipL2PerParamType)

TPU-first: each updater lowers to an optax GradientTransformation; the whole
update (schedule, momentum/adam state, clipping, weight decay) runs inside the
one compiled XLA train step — the reference applies these in Java per iteration
(LayerUpdater.update:72/preApply:174) before a separate axpy step function.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field, asdict

import jax
import jax.numpy as jnp
import optax


# ---------------------------------------------------------------------------
# Learning-rate schedules (reference: LearningRatePolicy + LayerUpdater.java:135-155)
# ---------------------------------------------------------------------------

def make_schedule(base_lr, policy=None, decay_rate=None, power=None, steps=None,
                  schedule_map=None):
    """Return an optax schedule fn step -> lr."""
    if policy is None or policy == "none" or policy == "fixed":
        return lambda step: base_lr
    p = str(policy).lower()
    if p == "exponential":
        return lambda step: base_lr * (decay_rate ** step)
    if p == "inverse":
        return lambda step: base_lr / ((1.0 + decay_rate * step) ** power)
    if p == "step":
        return lambda step: base_lr * (decay_rate ** jnp.floor(step / steps))
    if p == "torchstep":
        return lambda step: base_lr * (decay_rate ** jnp.floor(step / steps))
    if p == "poly":
        return lambda step: base_lr * ((1.0 - jnp.minimum(step / steps, 1.0)) ** power)
    if p == "sigmoid":
        return lambda step: base_lr / (1.0 + jnp.exp(-decay_rate * (step - steps)))
    if p == "schedule":
        if not schedule_map:
            return lambda step: base_lr
        boundaries = sorted(int(k) for k in schedule_map)
        values = [base_lr] + [float(schedule_map[k] if k in schedule_map else schedule_map[str(k)]) for k in boundaries]
        bounds_arr = jnp.asarray(boundaries)

        def sched(step):
            idx = jnp.sum(step >= bounds_arr)
            return jnp.asarray(values)[idx]
        return sched
    raise ValueError(f"Unknown lr policy '{policy}'")


# ---------------------------------------------------------------------------
# Updater configs
# ---------------------------------------------------------------------------

_UPDATER_REGISTRY: dict = {}


def register_updater(cls):
    _UPDATER_REGISTRY[cls.__name__] = cls
    return cls


def updater_from_dict(d):
    d = dict(d)
    cls = _UPDATER_REGISTRY[d.pop("type")]
    return cls(**d)


@dataclass
class BaseUpdater:
    learning_rate: float = 1e-1
    lr_policy: str | None = None
    lr_policy_decay_rate: float | None = None
    lr_policy_power: float | None = None
    lr_policy_steps: float | None = None
    lr_schedule_map: dict | None = None

    def schedule(self):
        return make_schedule(self.learning_rate, self.lr_policy,
                             self.lr_policy_decay_rate, self.lr_policy_power,
                             self.lr_policy_steps, self.lr_schedule_map)

    def to_optax(self):
        raise NotImplementedError

    def to_dict(self):
        d = {k: v for k, v in asdict(self).items() if v is not None}
        d["type"] = type(self).__name__
        return d


@register_updater
@dataclass
class Sgd(BaseUpdater):
    def to_optax(self):
        return optax.sgd(self.schedule())


@register_updater
@dataclass
class Nesterovs(BaseUpdater):
    momentum: float = 0.9
    momentum_schedule: dict | None = None

    def to_optax(self):
        if self.momentum_schedule:
            sm = {int(k): float(v) for k, v in self.momentum_schedule.items()}
            boundaries = sorted(sm)
            values = [self.momentum] + [sm[k] for k in boundaries]
            bounds_arr = jnp.asarray(boundaries)

            def mom_sched(step):
                return jnp.asarray(values)[jnp.sum(step >= bounds_arr)]

            return optax.inject_hyperparams(
                lambda learning_rate, momentum: optax.sgd(
                    learning_rate, momentum=momentum, nesterov=True))(
                learning_rate=self.schedule(), momentum=mom_sched)
        return optax.sgd(self.schedule(), momentum=self.momentum, nesterov=True)


@register_updater
@dataclass
class Adam(BaseUpdater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adam(self.schedule(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@register_updater
@dataclass
class AdaMax(BaseUpdater):
    learning_rate: float = 1e-3
    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.adamax(self.schedule(), b1=self.beta1, b2=self.beta2, eps=self.epsilon)


@register_updater
@dataclass
class AdaDelta(BaseUpdater):
    # AdaDelta is LR-free in the reference; None means multiplier 1.0, while an
    # explicit learning_rate acts as an optax step-size multiplier.
    learning_rate: float | None = None
    rho: float = 0.95
    epsilon: float = 1e-6

    def to_optax(self):
        lr = 1.0 if self.learning_rate is None else self.learning_rate
        return optax.adadelta(lr, rho=self.rho, eps=self.epsilon)


@register_updater
@dataclass
class AdaGrad(BaseUpdater):
    epsilon: float = 1e-6

    def to_optax(self):
        return optax.adagrad(self.schedule(), eps=self.epsilon)


@register_updater
@dataclass
class RmsProp(BaseUpdater):
    rms_decay: float = 0.95
    epsilon: float = 1e-8

    def to_optax(self):
        return optax.rmsprop(self.schedule(), decay=self.rms_decay, eps=self.epsilon)


@register_updater
@dataclass
class NoOp(BaseUpdater):
    def to_optax(self):
        return optax.set_to_zero()


def layer_transform(layer_conf):
    """The optax transform for one layer conf — the layer's own updater, or
    the reference's default plain SGD(0.1) when none is set. The single
    construction point MultiLayerNetwork, ComputationGraph, and the ZeRO-1
    sharded-update wrapper (parallel/zero.py) all build from."""
    return layer_conf.updater.to_optax() if layer_conf.updater is not None \
        else optax.sgd(0.1)


def per_layer_transform(transforms: dict):
    """Top-level-partitioned optimizer: transforms[name] updates only
    params[name]'s subtree.

    Replaces optax.multi_transform for the per-layer-updater contract
    (reference: one LayerUpdater per layer, nn/updater/LayerUpdater.java:29):
    multi_transform traverses the FULL tree once per label with masked
    leaves — O(L²) op count for L layers, measured ~78 ms/step on the
    ResNet-50 train step (161 labels) vs <2 ms for this partition."""
    def init(params):
        return {k: transforms[k].init(v) for k, v in params.items()}

    def update(grads, state, params=None):
        ups, new_state = {}, {}
        for k, g in grads.items():
            u, s = transforms[k].update(
                g, state[k], None if params is None else params[k])
            ups[k] = u
            new_state[k] = s
        return ups, new_state

    return optax.GradientTransformation(init, update)


# ---------------------------------------------------------------------------
# Gradient normalization (reference: GradientNormalization enum + LayerUpdater.java:182-194)
# ---------------------------------------------------------------------------

class GradientNormalization:
    NONE = "none"
    RENORMALIZE_L2_PER_LAYER = "renormalize_l2_per_layer"
    RENORMALIZE_L2_PER_PARAM_TYPE = "renormalize_l2_per_param_type"
    CLIP_ELEMENTWISE_ABSOLUTE_VALUE = "clip_elementwise_absolute_value"
    CLIP_L2_PER_LAYER = "clip_l2_per_layer"
    CLIP_L2_PER_PARAM_TYPE = "clip_l2_per_param_type"


def apply_gradient_normalization(layer_grads: dict, mode: str, threshold: float = 1.0):
    """Apply gradient normalization to one layer's {param_name: grad} dict."""
    if mode in (None, GradientNormalization.NONE):
        return layer_grads
    if mode == GradientNormalization.RENORMALIZE_L2_PER_LAYER:
        total = jnp.sqrt(sum(jnp.sum(g ** 2) for g in layer_grads.values()) + 1e-12)
        return {k: g / total for k, g in layer_grads.items()}
    if mode == GradientNormalization.RENORMALIZE_L2_PER_PARAM_TYPE:
        return {k: g / jnp.sqrt(jnp.sum(g ** 2) + 1e-12) for k, g in layer_grads.items()}
    if mode == GradientNormalization.CLIP_ELEMENTWISE_ABSOLUTE_VALUE:
        return {k: jnp.clip(g, -threshold, threshold) for k, g in layer_grads.items()}
    if mode == GradientNormalization.CLIP_L2_PER_LAYER:
        total = jnp.sqrt(sum(jnp.sum(g ** 2) for g in layer_grads.values()) + 1e-12)
        scale = jnp.minimum(1.0, threshold / total)
        return {k: g * scale for k, g in layer_grads.items()}
    if mode == GradientNormalization.CLIP_L2_PER_PARAM_TYPE:
        out = {}
        for k, g in layer_grads.items():
            n = jnp.sqrt(jnp.sum(g ** 2) + 1e-12)
            out[k] = g * jnp.minimum(1.0, threshold / n)
        return out
    raise ValueError(f"Unknown gradient normalization mode '{mode}'")
