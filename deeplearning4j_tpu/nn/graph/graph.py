"""ComputationGraph: arbitrary-DAG model with multi-input/multi-output.

Reference: nn/graph/ComputationGraph.java (2276 LoC; init :267,
topologicalSortOrder :850, fit :671/:740, calcBackpropGradients :1175,
rnnTimeStep :1789) and the vertex runtime nn/graph/vertex/GraphVertex.java.

TPU-first: vertices are pure functions evaluated in topological order inside
one traced computation; forward+backward+updater compile to a single XLA
program per step, exactly like MultiLayerNetwork.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp
import optax

from ..conf.graph_configuration import (ComputationGraphConfiguration,
                                        DuplicateToTimeSeriesVertex)
from ..conf.configuration import BackpropType
from ..layers.base import create_layer
from ..layers import feedforward, convolution, recurrent, misc, variational  # noqa: F401
from ..multistep import MultiStepTrainable
from ...telemetry.xla import timed_first_call
from ..updaters import apply_gradient_normalization
from ...optimize.listeners import resolve_listeners


class ComputationGraph(MultiStepTrainable):
    def __init__(self, conf: ComputationGraphConfiguration):
        self.conf = conf
        self.order = conf.topo_sort()
        self.layers = {}
        for name in self.order:
            spec = conf.vertices[name]
            if spec.kind == "layer":
                self.layers[name] = create_layer(spec.layer_conf)
        self.params = None
        self.states = None
        self.opt_state = None
        self._tx = None
        self.listeners = []
        self.iteration_count = 0
        self.epoch_count = 0
        self._score_dev = float("nan")
        self._dtype = jnp.dtype(conf.dtype)
        self._rng = jax.random.PRNGKey(conf.seed)
        self._jit_cache = {}
        self._rnn_state = {}
        self._ingest = None         # device-side ingest fused into the step
        self._zero = None           # ZeRO-1 sharded update (parallel/zero.py)
        self._wq = None             # int8 serving weights (nn/quant.py)

    @property
    def score_value(self):
        """Most recent minibatch score; kept on device by the train step and
        synced to host lazily on first read (mirrors MultiLayerNetwork)."""
        s = self._score_dev
        if not isinstance(s, float):
            s = float(s)
            self._score_dev = s
        return s

    @score_value.setter
    def score_value(self, v):
        self._score_dev = v

    # ------------------------------------------------------------------ init
    def init(self, params=None):
        conf = self.conf
        rng = jax.random.PRNGKey(conf.seed)
        self.params, self.states = {}, {}
        types = {}
        if conf.input_types:
            for name, t in zip(conf.network_inputs, conf.input_types):
                types[name] = t
        for name in self.order:
            spec = conf.vertices[name]
            if spec.kind == "input":
                continue
            if spec.kind == "layer":
                rng, sub = jax.random.split(rng)
                t = types.get(spec.inputs[0])
                if t is not None and spec.preprocessor is not None:
                    t = spec.preprocessor.output_type(t)
                elif t is not None and t.kind == "cnn_flat":
                    from ..conf.inputs import InputType
                    t = InputType.feed_forward(t.flat_size())
                p, s, out_t = self.layers[name].init(sub, t, self._dtype)
                self.params[name] = p
                self.states[name] = s
                types[name] = out_t
            else:
                in_types = [types.get(i) for i in spec.inputs]
                if all(t is not None for t in in_types):
                    types[name] = spec.vertex_conf.output_type(in_types)
        if params is not None:
            self.params = jax.tree_util.tree_map(jnp.asarray, params)
        self._build_updater()
        return self

    def _build_updater(self, init_state=True):
        from ..updaters import layer_transform, per_layer_transform
        transforms = {name: layer_transform(self.conf.vertices[name].layer_conf)
                      for name in self.params}
        if self._zero is not None:
            self._tx = self._zero.wrap(transforms, self.params)
        else:
            self._tx = per_layer_transform(transforms)
        if init_state:
            self.opt_state = self._tx.init(self.params)

    # -------------------------------------------------------------- forward
    def _forward(self, params, states, inputs, *, train, rng, masks=None,
                 initial_carries=None):
        """inputs: list of arrays aligned with network_inputs. Returns
        (activations dict, new_states, out_masks dict, carries)."""
        conf = self.conf
        acts, out_masks = {}, {}
        new_states = dict(states)
        carries = {}
        in_masks = masks or [None] * len(conf.network_inputs)
        timesteps = None
        for name, x, m in zip(conf.network_inputs, inputs, in_masks):
            acts[name] = x
            out_masks[name] = m
            if hasattr(x, "ndim") and x.ndim == 3:
                timesteps = x.shape[1]
        for name in self.order:
            spec = conf.vertices[name]
            if spec.kind == "input":
                continue
            xs = [acts[i] for i in spec.inputs]
            ms = [out_masks.get(i) for i in spec.inputs]
            if spec.kind == "layer":
                x, m = xs[0], ms[0]
                if rng is not None:
                    rng, pre_rng, sub = jax.random.split(rng, 3)
                else:
                    pre_rng = sub = None
                if spec.preprocessor is not None:
                    x = spec.preprocessor(x, m, rng=pre_rng)
                    m = spec.preprocessor.feed_forward_mask(m) if m is not None else None
                kwargs = {}
                if initial_carries is not None and name in initial_carries:
                    kwargs = {"initial_state": initial_carries[name], "return_state": True}
                out = self.layers[name].forward(params[name], states[name], x,
                                                train=train, rng=sub, mask=m, **kwargs)
                if len(out) == 4:
                    y, s, m, fin = out
                    carries[name] = fin
                else:
                    y, s, m = out
                new_states[name] = s
                acts[name] = y
                out_masks[name] = m
            else:
                vc = spec.vertex_conf
                if isinstance(vc, DuplicateToTimeSeriesVertex):
                    ref = vc.reference_input
                    t = acts[ref].shape[1] if ref in acts and acts[ref].ndim == 3 else timesteps
                    acts[name] = vc.apply(xs, ms, timesteps=t)
                else:
                    acts[name] = vc.apply(xs, ms)
                out_masks[name] = vc.output_mask(ms)
        return acts, new_states, out_masks, carries

    # ------------------------------------------------------- mixed precision
    def _compute_dtype(self):
        cd = getattr(self.conf, "compute_dtype", None)
        if cd is None or jnp.dtype(cd) == self._dtype:
            return None
        return jnp.dtype(cd)

    def _cast_for_compute(self, params, inputs):
        """bf16 compute for all non-output layers; output layers keep the
        param dtype so their loss math runs in full precision (mirrors
        MultiLayerNetwork._cast_for_compute)."""
        cd = self._compute_dtype()
        if cd is None:
            return params, inputs
        outs = set(self.conf.network_outputs)
        # uint8 = image pixels (exact in bf16, rescaled on-chip); wider ints
        # (embedding ids) must not be cast — ids > 256 don't fit bf16
        cast = lambda a: a.astype(cd) \
            if hasattr(a, "dtype") and (jnp.issubdtype(a.dtype, jnp.floating)
                                        or a.dtype == jnp.uint8) else a
        params = {k: (v if k in outs else jax.tree_util.tree_map(cast, v))
                  for k, v in params.items()}
        inputs = [cast(x) for x in inputs]
        return params, inputs

    # ---------------------------------------------------------------- loss
    def _loss(self, params, states, inputs, labels, *, train, rng, masks=None,
              label_masks=None, initial_carries=None):
        conf = self.conf
        params, inputs = self._cast_for_compute(params, inputs)
        if rng is not None:
            rng, fwd_rng = jax.random.split(rng)
        else:
            fwd_rng = None
        # run everything except output layers' score; output layer forward is
        # replaced by its integrated loss on the features feeding it. Under
        # conf.remat the forward recomputes (policy-chosen) activations in
        # the backward instead of storing them (nn/remat.py) — training only
        def fwd_fn(p, s, xx, rr, mm, ic):
            return self._forward(p, s, xx, train=train, rng=rr, masks=mm,
                                 initial_carries=ic)
        from ..remat import maybe_checkpoint
        fwd_fn = maybe_checkpoint(
            fwd_fn, getattr(conf, "remat", None) if train else None)
        acts, new_states, out_masks, carries = fwd_fn(
            params, states, inputs, fwd_rng, masks, initial_carries)
        total = 0.0
        lm = label_masks or [None] * len(conf.network_outputs)
        for out_name, y, mlab in zip(conf.network_outputs, labels, lm):
            spec = conf.vertices[out_name]
            layer = self.layers[out_name]
            if not layer.is_output_layer():
                raise ValueError(f"Network output '{out_name}' is not an output layer")
            feats = acts[spec.inputs[0]]
            if spec.preprocessor is not None:
                if rng is not None:
                    rng, pre_rng = jax.random.split(rng)
                else:
                    pre_rng = None
                feats = spec.preprocessor(feats, out_masks.get(spec.inputs[0]),
                                          rng=pre_rng)
            if self._compute_dtype() is not None:
                feats = feats.astype(self._dtype)  # loss math in full precision
            mask = mlab if mlab is not None else out_masks.get(spec.inputs[0])
            if isinstance(layer, feedforward.CenterLossOutputLayerModule):
                total = total + layer.score(params[out_name], feats, y, mask, train,
                                            rng, state=states[out_name])
                new_states[out_name] = layer.update_centers(states[out_name], feats, y)
            else:
                total = total + layer.score(params[out_name], feats, y, mask, train, rng)
        total = total + self._reg_score(params)
        return total, (new_states, carries)

    def _reg_score(self, params):
        total = 0.0
        for name, p in params.items():
            lc = self.conf.vertices[name].layer_conf
            l1, l2 = lc.l1 or 0.0, lc.l2 or 0.0
            l1b, l2b = lc.l1_bias or 0.0, lc.l2_bias or 0.0
            if not (l1 or l2 or l1b or l2b):
                continue
            for k, v in p.items():
                is_w = not (k.endswith("b") or k in ("gamma", "beta", "centers"))
                if is_w:
                    if l1:
                        total = total + l1 * jnp.sum(jnp.abs(v))
                    if l2:
                        total = total + 0.5 * l2 * jnp.sum(v ** 2)
                else:
                    if l1b:
                        total = total + l1b * jnp.sum(jnp.abs(v))
                    if l2b:
                        total = total + 0.5 * l2b * jnp.sum(v ** 2)
        return total

    def _normalize_grads(self, grads):
        out = {}
        for name, g in grads.items():
            lc = self.conf.vertices[name].layer_conf
            if lc.gradient_normalization and g:
                g = apply_gradient_normalization(g, lc.gradient_normalization,
                                                 lc.gradient_normalization_threshold or 1.0)
            out[name] = g
        return out

    # ------------------------------------------------------- device ingest
    def set_ingest(self, ingest):
        """Fuse a device-side ingest transform into the jitted train step
        (mirrors MultiLayerNetwork.set_ingest): `apply_features` runs on the
        FIRST network input, `apply_labels` on the FIRST label — the
        single-input/single-output shape every ingest workload here has.
        Training paths only; output()/score() keep consuming preprocessed
        tensors. Clears the jit cache so executables re-trace with the
        ingest ops fused."""
        self._ingest = ingest
        self._jit_cache.clear()
        return self

    def _apply_ingest(self, inputs, labels):
        ing = self._ingest
        if ing is None:
            return inputs, labels
        inputs = [ing.apply_features(inputs[0])] + list(inputs[1:])
        out = []
        for i, l in enumerate(labels):
            y = ing.apply_labels(l) if i == 0 else l
            # restore the non-ingest _prep_batch cast for EVERY label head,
            # not just the ingested one
            if y.dtype != self._dtype:
                y = y.astype(self._dtype)
            out.append(y)
        return inputs, out

    # ---------------------------------------------------------------- train
    def _make_train_step(self, tbptt=False):
        tx = self._tx

        def train_step(params, opt_state, states, rng, inputs, labels, masks,
                       label_masks, carries):
            inputs, labels = self._apply_ingest(inputs, labels)

            def loss_fn(p):
                return self._loss(p, states, inputs, labels, train=True, rng=rng,
                                  masks=masks, label_masks=label_masks,
                                  initial_carries=carries if tbptt else None)
            (score, (new_states, out_carries)), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            grads = self._normalize_grads(grads)
            updates, opt_state = tx.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, new_states, score, out_carries

        # tbptt donates the recurrent carries too (arg 8): out_carries
        # aliases the incoming h/c buffers across windows instead of fresh
        # [B, H] allocations (see MultiLayerNetwork._make_train_step); std
        # passes carries=None — zero leaves, donation is a no-op there
        donate = (0, 1, 2, 8) if tbptt else (0, 1, 2)
        return jax.jit(train_step, donate_argnums=donate)

    def _get_train_step(self, key="std"):
        """One cached jitted step per mode; jit itself retraces per input
        structure (mask presence etc.), so no structure-derived keys needed.
        timed_first_call routes the compile through the jit accounting and
        the cost registry (telemetry/cost.py) like the MLN train steps."""
        if key not in self._jit_cache:
            self._jit_cache[key] = timed_first_call(
                self._make_train_step(tbptt=(key == "tbptt")),
                f"graph_train_step:{key}")
        return self._jit_cache[key]

    def fit(self, data, labels=None, epochs=1, steps_per_execution=1,
            prefetch=None, ingest=None):
        """Accepts MultiDataSet / DataSet / iterator thereof / (x, y)
        (reference: fit(DataSetIterator) :671, fit(MultiDataSet) :740).

        steps_per_execution=K compiles K optimizer steps into ONE executable
        (lax.scan with donated carry, nn/multistep.py) — one host dispatch
        per K minibatches; listeners fire on a K-step cadence.

        prefetch=K wraps the source in an etl.DevicePrefetcher (K-deep
        device buffer: batch N+1's h2d DMA overlaps batch N's compute);
        ingest=DeviceIngest(...) fuses device-side decode/cast/one-hot into
        the compiled step (= set_ingest), so prefetch ships narrow raw bytes
        — mirrors MultiLayerNetwork.fit."""
        from ...datasets.dataset import DataSet, MultiDataSet
        from ...datasets.iterator.base import (as_iterator, DataSetIterator,
                                               ListDataSetIterator)
        if ingest is not None:
            self.set_ingest(ingest)
        if labels is not None:
            data = MultiDataSet(data, labels)
        if isinstance(data, (DataSet, MultiDataSet)):
            items = [data]
        elif isinstance(data, DataSetIterator):
            items = data
        elif isinstance(data, (list, tuple)):
            items = list(data)
        else:
            items = as_iterator(data)
        wrapped = None
        if prefetch:
            from ...etl.prefetch import DevicePrefetcher
            if isinstance(items, list):
                items = ListDataSetIterator(items)
            items = wrapped = DevicePrefetcher(items,
                                               queue_size=int(prefetch))
        K = max(1, int(steps_per_execution))
        try:
            for _ in range(epochs):
                for listener in self.listeners:
                    listener.on_epoch_start(self)
                if hasattr(items, "reset"):
                    items.reset()
                if K > 1:
                    self._fit_grouped(items, K)
                else:
                    for ds in items:
                        self.fit_batch(ds)
                for listener in self.listeners:
                    listener.on_epoch_end(self)
                self.epoch_count += 1
        except BaseException:
            if wrapped is not None:
                try:
                    wrapped.close()
                except Exception:
                    pass           # don't mask the primary training error
            raise
        if wrapped is not None:
            wrapped.close()        # stop the fit-owned prefetch thread
        return self

    def _prep_batch(self, ds):
        """(inputs, labels, masks, lmasks) lists of device arrays — the
        per-step leaves both fit_batch and the scanned path consume."""
        from ...datasets.dataset import DataSet, MultiDataSet
        if isinstance(ds, DataSet):
            ds = MultiDataSet([ds.features], [ds.labels],
                              None if ds.features_mask is None else [ds.features_mask],
                              None if ds.labels_mask is None else [ds.labels_mask])
        inputs = [jnp.asarray(f) for f in ds.features]
        # with a fused ingest, labels ship raw/narrow (e.g. int class ids)
        # and the one-hot expansion happens inside the compiled step
        labels = [jnp.asarray(l) for l in ds.labels] if self._ingest is not None \
            else [jnp.asarray(l, self._dtype) for l in ds.labels]
        masks = None if ds.features_masks is None else \
            [None if m is None else jnp.asarray(m, self._dtype) for m in ds.features_masks]
        lmasks = None if ds.labels_masks is None else \
            [None if m is None else jnp.asarray(m, self._dtype) for m in ds.labels_masks]
        return inputs, labels, masks, lmasks

    def _scan_loss(self, p, states, inputs, labels, rng, masks, lmasks):
        inputs, labels = self._apply_ingest(inputs, labels)
        score, (new_states, _) = self._loss(p, states, inputs, labels,
                                            train=True, rng=rng, masks=masks,
                                            label_masks=lmasks)
        return score, new_states

    def _multi_step_mode(self, prepped):
        from ..conf.configuration import OptimizationAlgorithm
        inputs = prepped[0]
        if self.conf.optimization_algo != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
            return None
        T = max((x.shape[1] for x in inputs
                 if hasattr(x, "ndim") and x.ndim == 3), default=0)
        if (self.conf.backprop_type == BackpropType.TRUNCATED_BPTT
                and T > self.conf.tbptt_fwd_length):
            return None  # graph TBPTT groups run per-batch
        return None if self._listeners_need_gradients() else "std"

    def fit_batch(self, ds):
        if self.params is None:
            self.init()
        self._check_trainable()     # int8 serving weights can't train
        inputs, labels, masks, lmasks = self._prep_batch(ds)
        self._rng, step_rng = jax.random.split(self._rng)
        from ..conf.configuration import OptimizationAlgorithm
        if self.conf.optimization_algo != OptimizationAlgorithm.STOCHASTIC_GRADIENT_DESCENT:
            # flat solvers (reference: Solver.java:55); cached per model
            if getattr(self, "_flat_solver", None) is None:
                from ...optimize.solvers import make_solver
                self._flat_solver = make_solver(
                    self.conf.optimization_algo, self,
                    line_search_iterations=self.conf.max_num_line_search_iterations)
            self._flat_solver.optimize(inputs, labels, masks, lmasks)
        else:
            T = max((x.shape[1] for x in inputs
                     if hasattr(x, "ndim") and x.ndim == 3), default=0)
            if (self.conf.backprop_type == BackpropType.TRUNCATED_BPTT
                    and T > self.conf.tbptt_fwd_length):
                self._fit_tbptt(inputs, labels, masks, lmasks, step_rng, T)
            else:
                step = self._get_train_step("std")
                self.params, self.opt_state, self.states, score, _ = step(
                    self.params, self.opt_state, self.states, step_rng, inputs,
                    labels, masks, lmasks, None)
                self.score_value = score  # device scalar; syncs lazily on read
        self.iteration_count += 1
        for listener in self.listeners:
            if hasattr(listener, "record_batch_size"):
                listener.record_batch_size(inputs[0].shape[0])
            listener.iteration_done(self, self.iteration_count)

    def _fit_tbptt(self, inputs, labels, masks, lmasks, rng, T):
        """Truncated BPTT over the graph (reference: ComputationGraph TBPTT via
        doTruncatedBPTT in ComputationGraph.java): slide a tbptt_fwd_length
        window over every time-distributed (3D) input/label, carrying recurrent
        layer state (stop-gradient) across windows; non-temporal inputs are
        passed whole to every window."""
        L = self.conf.tbptt_fwd_length
        batch = inputs[0].shape[0]
        carries = self._zero_carries(batch)
        step = self._get_train_step("tbptt")
        scores = []
        for start in range(0, T, L):
            end = min(start + L, T)
            xw = [x[:, start:end] if x.ndim == 3 and x.shape[1] == T else x
                  for x in inputs]
            yw = [y[:, start:end] if y.ndim == 3 and y.shape[1] == T else y
                  for y in labels]
            mw = None if masks is None else \
                [None if m is None else
                 (m[:, start:end] if m.ndim >= 2 and m.shape[1] == T else m)
                 for m in masks]
            lmw = None if lmasks is None else \
                [None if m is None else
                 (m[:, start:end] if m.ndim >= 2 and m.shape[1] == T else m)
                 for m in lmasks]
            rng, sub = jax.random.split(rng)
            # gradient truncation at window edges is inherent: each window's
            # value_and_grad differentiates params only; carries enter the next
            # step as concrete (non-differentiated) arguments
            self.params, self.opt_state, self.states, score, carries = step(
                self.params, self.opt_state, self.states, sub, xw, yw, mw, lmw,
                carries)
            scores.append(score)
        self.score_value = jnp.mean(jnp.stack(scores))

    # ------------------------------------------------------------ inference
    def output(self, *inputs, train=False, mask=None):
        """(reference: ComputationGraph.output / outputSingle). `mask` is a
        [batch, time] validity mask for the FIRST network input (the
        serving batcher's padded+masked length buckets)."""
        if self.params is None:
            self.init()
        inputs = [jnp.asarray(x) for x in inputs]
        masked = mask is not None
        key = ("output", len(inputs), masked)
        if key not in self._jit_cache:
            def fwd(params, states, xs, mm):
                # int8 serving weights: codes are the executable's operands;
                # the traced dequant fuses into the consumers (nn/quant.py)
                params = self._dequant_params(params)
                params, xs = self._cast_for_compute(params, xs)
                masks = None if mm is None else [mm] + [None] * (len(xs) - 1)
                acts, _, _, _ = self._forward(params, states, xs, train=False,
                                              rng=None, masks=masks)
                return [acts[o].astype(self._dtype) for o in self.conf.network_outputs]
            self._jit_cache[key] = timed_first_call(
                jax.jit(fwd),
                f"graph_output:inputs={len(inputs)},mask={masked}")
        outs = self._jit_cache[key](
            self.params, self.states, inputs,
            None if mask is None else jnp.asarray(mask, self._dtype))
        return outs[0] if len(outs) == 1 else outs

    def feed_forward(self, *inputs, train=False):
        acts, _, _, _ = self._forward(self._dequant_params(self.params),
                                      self.states,
                                      [jnp.asarray(x) for x in inputs],
                                      train=train, rng=None)
        return acts

    def score(self, ds):
        from ...datasets.dataset import DataSet, MultiDataSet
        if isinstance(ds, DataSet):
            ds = MultiDataSet([ds.features], [ds.labels])
        inputs = [jnp.asarray(f) for f in ds.features]
        labels = [jnp.asarray(l, self._dtype) for l in ds.labels]
        s, _ = self._loss(self._dequant_params(self.params), self.states,
                          inputs, labels, train=False, rng=None)
        return float(s)

    def compute_gradient_and_score(self, inputs, labels, masks=None, label_masks=None):
        inputs = [jnp.asarray(x) for x in (inputs if isinstance(inputs, (list, tuple)) else [inputs])]
        labels = [jnp.asarray(y) for y in (labels if isinstance(labels, (list, tuple)) else [labels])]

        def loss_fn(p):
            s, _ = self._loss(p, self.states, inputs, labels, train=False, rng=None,
                              masks=masks, label_masks=label_masks)
            return s
        score, grads = jax.value_and_grad(loss_fn)(self.params)
        return grads, float(score)

    # ------------------------------------------------------- rnn streaming
    def rnn_time_step(self, *inputs):
        """(reference: rnnTimeStep :1789)"""
        inputs = [jnp.asarray(x) for x in inputs]
        squeeze = inputs[0].ndim == 2
        if squeeze:
            inputs = [x[:, None, :] if x.ndim == 2 else x for x in inputs]
        batch = inputs[0].shape[0]
        carries = self._rnn_state or self._zero_carries(batch)
        acts, _, _, new_carries = self._forward(
            self._dequant_params(self.params), self.states, inputs,
            train=False, rng=None, initial_carries=carries)
        self._rnn_state = new_carries
        outs = [acts[o] for o in self.conf.network_outputs]
        if squeeze:
            outs = [o[:, -1] if o.ndim == 3 else o for o in outs]
        return outs[0] if len(outs) == 1 else outs

    def rnn_clear_previous_state(self):
        self._rnn_state = {}

    # generate() — greedy KV-cache decode — lives on MultiStepTrainable
    # (shared with MultiLayerNetwork, like set_update_sharding)

    def _zero_carries(self, batch):
        carries = {}
        for name, layer in self.layers.items():
            if hasattr(layer, "init_carry"):
                carries[name] = layer.init_carry(batch, self._dtype)
        return carries

    def clone(self):
        """Deep copy (params/states/score); mirrors MultiLayerNetwork.clone —
        required by the early-stopping InMemoryModelSaver."""
        net = ComputationGraph(self.conf)
        if self.params is not None:
            net.init(params=jax.tree_util.tree_map(jnp.array, self.params))
            net.states = jax.tree_util.tree_map(jnp.array, self.states)
        return net

    # -------------------------------------------------------------- params
    def param_table(self):
        out = {}
        for name, p in self.params.items():
            for k, v in p.items():
                out[f"{name}_{k}"] = v
        return out

    def num_params(self):
        return sum(int(x.size) for x in jax.tree_util.tree_leaves(self.params))

    def get_flat_params(self):
        leaves = []
        for name in sorted(self.params.keys()):
            p = self.params[name]
            for k in sorted(p.keys()):
                leaves.append(np.asarray(p[k]).ravel())
        if not leaves:
            return np.zeros((0,), np.float32)
        return np.concatenate(leaves)

    def set_flat_params(self, flat):
        flat = np.asarray(flat)
        off = 0
        for name in sorted(self.params.keys()):
            p = self.params[name]
            for k in sorted(p.keys()):
                n = int(np.prod(p[k].shape)) if p[k].shape else 1
                p[k] = jnp.asarray(flat[off:off + n].reshape(p[k].shape), p[k].dtype)
                off += n
        return self

    def set_listeners(self, *listeners):
        self.listeners = resolve_listeners(listeners)
        return self

    def evaluate(self, iterator, top_n=1):
        """top_n > 1 also tracks top-N accuracy (reference:
        MultiLayerNetwork.evaluate(iter, labels, topN))."""
        from ...eval.evaluation import Evaluation
        from ...datasets.iterator.base import as_iterator
        e = Evaluation(top_n=top_n)
        it = as_iterator(iterator)
        it.reset()
        for ds in it:
            out = self.output(ds.features)
            e.eval(np.asarray(ds.labels), np.asarray(out),
                   None if ds.labels_mask is None else np.asarray(ds.labels_mask))
        return e
