"""Rematerialization (gradient checkpointing) for the training forward.

The ResNet-50 headline step is HBM-bound with the MXU idle ~70% of the step
(PERF.md): the roofline-correct optimization is to SPEND idle FLOPs to move
fewer bytes. `jax.checkpoint` over the forward does exactly that — saved
residuals (activation stores + backward re-reads) disappear in exchange for
recomputing them from cheaper-to-save values during the backward.

Policies (MultiLayerConfiguration.remat / GraphBuilder global conf):
  "convs_and_dots" — save conv and matmul OUTPUTS, recompute every
      elementwise/BN/padding chain in the backward. For conv+BN training
      this deletes the stored copies of the normalize/ReLU chains — the
      same byte reduction PERF.md r4 estimated for a hand-fused conv+BN
      Pallas epilogue (~25%), obtained from the autodiff system instead of
      a kernel rewrite.
  "dots" — jax.checkpoint_policies.checkpoint_dots: save matmul-class
      outputs only; convs recompute too (doubles conv forward FLOPs).
  "full" — save only the forward's inputs; recompute everything.

The reference has no analog: its workspace memory manager
(nd4j workspaces) reuses buffers but never trades compute for memory.
"""
from __future__ import annotations

import jax


def _convs_and_dots_saveable(prim, *_, **__):
    return prim.name in ("conv_general_dilated", "dot_general")


def _policies():
    cp = jax.checkpoint_policies
    return {
        "full": None,
        "dots": cp.checkpoint_dots,
        "dots_no_batch": cp.checkpoint_dots_with_no_batch_dims,
        "convs_and_dots": _convs_and_dots_saveable,
    }


def maybe_checkpoint(fn, mode):
    """Wrap `fn` in jax.checkpoint under the named policy; identity when
    mode is falsy. Unknown modes fail loudly (a typo silently training
    without remat would be a perf heisenbug)."""
    if not mode:
        return fn
    policies = _policies()
    if mode not in policies:
        raise ValueError(f"unknown remat mode {mode!r}; "
                         f"one of {sorted(policies)}")
    policy = policies[mode]
    if policy is None:
        return jax.checkpoint(fn)
    return jax.checkpoint(fn, policy=policy)
