"""Paged KV cache: a BlockPool of fixed-size token blocks + block tables.

The slab cache pays `[slots, capacity, H, Dh]` of HBM per attention layer
whether or not any request uses its capacity — a 2k-capacity slot serving a
40-token chat strands 98% of its bytes, the same stranded-capacity math the
ZeRO sharding work attacked for optimizer state (arXiv 2004.13336). The
paged layout stops paying for unused tokens:

  pool   [num_blocks, block_size, H, Dh]   one allocation, all slots
  table  [slots, capacity//block_size] i32 logical block j of slot s lives
                                           in pool block table[s, j]

Token t of a slot lives at (table[s, t // block_size], t % block_size), so
a gather of the slot's table row reconstructs its contiguous K/V — that is
`kernels.flash_attention.flash_decode_paged`. The table is a plain int32
ARRAY OPERAND of the decode step (replicated on a mesh; the pool itself
keeps head-sharding), never a shape: requests of any length mix in one
executable, and the zero-steady-state-recompile contract survives paging.

Block 0 is a reserved SCRATCH block: unallocated table entries and the pad
chunks of a prefill bucket all point there, so out-of-range writes land in
a block nobody reads (every read is masked by the per-slot length vector)
instead of needing in-trace bounds checks.

Everything stateful here is HOST-SIDE and owned by the scheduler loop
thread: `BlockPool` hands out physical block ids (`alloc`/`free`), the
scheduler writes table rows, and admission may OVERSUBSCRIBE the pool —
admit more requests than the pool could back at full length — with a
watermark-triggered preempt of the youngest slot when growth runs dry
(the preempted request keeps its partial tokens and re-prefills
prompt+partial on re-admission; see DecodeScheduler)."""
from __future__ import annotations

import numpy as np


class PoolExhausted(RuntimeError):
    """Allocation failed: fewer free blocks than requested. The scheduler
    answers by preempting the youngest slot (watermark policy), never by
    failing the request."""


def blocks_for(n_tokens, block_size):
    """Physical blocks needed to hold n_tokens."""
    return -(-int(n_tokens) // int(block_size))


class BlockPool:
    """Host-side free-list allocator over the pool's physical blocks.

    Block 0 is never handed out (the scratch block). Allocation is
    all-or-nothing; `defrag()` re-sorts the free list so future allocations
    prefer low block ids, keeping the pool's high-water mark (and the HBM
    working set a real allocator would page) compact after churn."""

    def __init__(self, num_blocks, block_size):
        num_blocks = int(num_blocks)
        block_size = int(block_size)
        if block_size < 1 or (block_size & (block_size - 1)):
            raise ValueError(f"block_size must be a power of two, got "
                             f"{block_size}")
        if num_blocks < 2:
            raise ValueError("need >= 2 blocks (block 0 is scratch)")
        self.num_blocks = num_blocks
        self.block_size = block_size
        # pop() takes from the tail: descending order -> lowest id first
        self._free = list(range(num_blocks - 1, 0, -1))
        self.high_water = 0          # max blocks ever simultaneously held

    @property
    def capacity_blocks(self):
        """Allocatable blocks (scratch excluded)."""
        return self.num_blocks - 1

    @property
    def free_blocks(self):
        return len(self._free)

    @property
    def used_blocks(self):
        return self.capacity_blocks - len(self._free)

    def utilization(self):
        """Allocated fraction of the allocatable pool (the
        kv_pool_utilization gauge)."""
        return self.used_blocks / max(self.capacity_blocks, 1)

    def alloc(self, n):
        """n physical block ids, or PoolExhausted with the pool untouched."""
        n = int(n)
        if n < 0:
            raise ValueError(f"alloc({n})")
        if n > len(self._free):
            raise PoolExhausted(
                f"need {n} blocks, {len(self._free)} free "
                f"(pool {self.capacity_blocks})")
        out = [self._free.pop() for _ in range(n)]
        self.high_water = max(self.high_water, self.used_blocks)
        return out

    def free(self, blocks):
        """Return blocks to the pool (double-free and scratch are errors)."""
        for b in blocks:
            b = int(b)
            if b <= 0 or b >= self.num_blocks:
                raise ValueError(f"block {b} is not allocatable")
            if b in self._free:
                raise ValueError(f"double free of block {b}")
            self._free.append(b)

    def defrag(self):
        """Re-sort the free list so the next allocations take the lowest
        block ids — after heavy churn the live set packs toward the front
        of the pool (the indirection makes physical compaction unnecessary;
        this keeps the id space, and a real allocator's page set, tight)."""
        self._free.sort(reverse=True)

    def reset(self):
        self._free = list(range(self.num_blocks - 1, 0, -1))
        self.high_water = 0


def make_table(slots, max_blocks):
    """All-scratch block table [slots, max_blocks] int32 (logical block j of
    slot s -> physical block table[s, j]; 0 = unallocated/scratch)."""
    return np.zeros((int(slots), int(max_blocks)), np.int32)
