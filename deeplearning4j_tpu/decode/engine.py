"""DecodeEngine: fixed-shape KV-cache decode executables for the nn types.

One engine serves one model with three executable families:

- ``step``: ONE compiled function of fixed shape — [slots] token ids in,
  [slots] next ids out — that advances EVERY in-flight request by one token.
  Attention layers append the token's k/v into their [slots, capacity, H,
  Dh] cache rows with a per-slot `lax.dynamic_update_slice` (vmapped over
  the slot axis) and attend against the cache masked by the per-slot length
  vector (kernels.flash_attention.flash_decode); recurrent layers carry
  their (h, c) state in [slots, n_out] cache rows. Because every shape is a
  function of (slots, capacity) only — never of how many tokens any request
  has generated — steady-state decoding NEVER recompiles, no matter how
  requests join and leave the batch.
- ``prefill``: one compiled function per power-of-two prompt-length bucket.
  The prompt runs as a normal full-sequence forward (causal attention via
  the masked flash kernel — the same padded+masked length-bucket discipline
  the serving batcher applies to /predict), each attention layer's K/V
  projections land in the slot's cache rows in one dynamic_update_slice,
  and the recurrent final carries land in the slot's carry rows. Pad
  positions write garbage K/V beyond `length`; the length mask keeps every
  later step from ever attending to them.
- ``verify`` (speculative decoding, decode/speculative.py): one compiled
  function per window size W — appends a W-token window at a dynamic
  `start` offset of one slot and returns ALL W next-token distributions in
  one batched pass (prefill-shaped work: it spends the compute the
  HBM-bound step leaves idle). Rollback after the accept decision is a
  host-side length reset — which is why verify requires rewind-free state
  (attention-only models; LSTM carries cannot rewind).

Both legs emit SAMPLED token ids (decode/sampling.py): temperature /
top-k / top-p / seed arrive as batch-shaped ARRAY OPERANDS, with
temperature <= 0 short-circuiting to argmax in-trace, so greedy and
creative requests co-batch in the same executable and per-request sampling
params never become recompile keys (graftlint GL016).

The cache is a plain pytree ``{"lengths": int32[slots], "layers": {name:
entry}}`` threaded functionally through the executables and DONATED, so
steady state re-uses the cache buffers in place instead of allocating a
fresh multi-MB cache per token. With ``paged=True`` the attention entries
become a shared BLOCK POOL ``[num_blocks, block_size, H, Dh]`` addressed
through a ``[slots, max_blocks]`` int32 block-table operand
(decode/paged.py): appends scatter into (table[pos//bs], pos%bs), the
attention gathers the slot's blocks back into contiguous rows
(kernels.flash_attention.flash_decode_paged), and capacity is whatever the
scheduler's allocator backs — token-for-token equal to the slab layout
(parity-tested), with the table replicated on a mesh while the pool keeps
head-sharding.

Decode runs in the model's param dtype (no mixed-precision cast): decode is
bound by streaming cache bytes, not MXU throughput, and greedy parity with
``model.output`` is the contract the tests pin.
"""
from __future__ import annotations

import threading

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..nn.layers.convolution import LayerNormalizationModule
from ..nn.layers.feedforward import (DenseLayerModule, EmbeddingLayerModule,
                                     LossLayerModule, OutputLayerModule,
                                     RnnOutputLayerModule)
from ..nn.layers.misc import ActivationLayerModule, DropoutLayerModule
from ..nn.layers.recurrent import (GravesBidirectionalLSTMModule,
                                   SelfAttentionLayerModule, _BaseLSTMModule)
from ..telemetry.xla import record_jit_compile
from ..util.time_source import monotonic_s
from . import sampling as _sampling


class DecodeUnsupported(TypeError):
    """The model contains a construct with no token-streaming semantics
    (bidirectional recurrence, non-causal attention, temporal pooling...)."""


# layers whose forward is a pure per-position map ([b,t,f] -> [b,t,g] with
# position i depending only on position i): safe in both decode legs
_POSITIONWISE = (DenseLayerModule, EmbeddingLayerModule, RnnOutputLayerModule,
                 OutputLayerModule, LossLayerModule, ActivationLayerModule,
                 DropoutLayerModule, LayerNormalizationModule)

# graph vertices that are per-position maps over their inputs
_POSITIONWISE_VERTICES = ("ElementWiseVertex", "MergeVertex")

MIN_PREFILL_BUCKET = 16   # floor the prompt buckets: bounds the executable
                          # set at log2(capacity/16)+1 without measurable
                          # padding waste at serving prompt sizes


def bucket_for_len(n, capacity):
    """Smallest power-of-two >= n (floored at MIN_PREFILL_BUCKET, capped at
    the cache capacity) — the prefill executable key."""
    b = MIN_PREFILL_BUCKET
    while b < n:
        b <<= 1
    return min(b, capacity)


class _Node:
    __slots__ = ("name", "kind", "inputs", "module", "vertex")

    def __init__(self, name, kind, inputs=(), module=None, vertex=None):
        self.name = name
        self.kind = kind            # "input" | "layer" | "vertex"
        self.inputs = tuple(inputs)
        self.module = module
        self.vertex = vertex


def _check_layer(name, module):
    if isinstance(module, GravesBidirectionalLSTMModule):
        raise DecodeUnsupported(
            f"layer {name!r}: bidirectional recurrence needs future tokens "
            "and cannot stream")
    if isinstance(module, SelfAttentionLayerModule):
        if not getattr(module.conf, "causal", False):
            raise DecodeUnsupported(
                f"layer {name!r}: non-causal attention attends to future "
                "positions and cannot decode incrementally")
        return
    if isinstance(module, (_BaseLSTMModule,) + _POSITIONWISE):
        return
    raise DecodeUnsupported(
        f"layer {name!r} ({type(module).__name__}) has no per-token decode "
        "semantics")


def build_plan(model):
    """(nodes, input_name, output_name, vocab) for a MultiLayerNetwork or a
    single-input/single-output ComputationGraph. A mesh-serving wrapper
    (serving/mesh.MeshDispatcher) is planned through the model it wraps —
    duck-typed on `mesh_inner` so decode/ never imports serving/."""
    from ..nn.graph.graph import ComputationGraph
    from ..nn.multilayer.network import MultiLayerNetwork
    model = getattr(model, "mesh_inner", model)
    if isinstance(model, MultiLayerNetwork):
        it = getattr(model.conf, "input_type", None)
        vocab = int(it.size) if it is not None and hasattr(it, "size") \
            else int(model.conf.layers[0].n_in)
        if getattr(model.conf, "input_preprocessors", None):
            if any(model.conf.input_preprocessors.get(i) is not None
                   for i in range(len(model.layers))):
                raise DecodeUnsupported(
                    "input preprocessors have no per-token semantics")
        nodes = [_Node("__in__", "input")]
        prev = "__in__"
        for i, module in enumerate(model.layers):
            _check_layer(str(i), module)
            nodes.append(_Node(str(i), "layer", (prev,), module=module))
            prev = str(i)
        return nodes, "__in__", prev, vocab
    if isinstance(model, ComputationGraph):
        conf = model.conf
        if len(conf.network_inputs) != 1 or len(conf.network_outputs) != 1:
            raise DecodeUnsupported(
                "decode requires a single-input/single-output graph")
        vocab = int(conf.input_types[0].size) if conf.input_types \
            else int(conf.vertices[model.order[1]].layer_conf.n_in)
        nodes = []
        for name in model.order:
            spec = conf.vertices[name]
            if spec.kind == "input":
                nodes.append(_Node(name, "input"))
            elif spec.kind == "layer":
                if spec.preprocessor is not None:
                    raise DecodeUnsupported(
                        f"vertex {name!r}: preprocessors have no per-token "
                        "semantics")
                module = model.layers[name]
                _check_layer(name, module)
                nodes.append(_Node(name, "layer", spec.inputs, module=module))
            else:
                vc = spec.vertex_conf
                if type(vc).__name__ not in _POSITIONWISE_VERTICES:
                    raise DecodeUnsupported(
                        f"vertex {name!r} ({type(vc).__name__}) is not a "
                        "per-position map")
                nodes.append(_Node(name, "vertex", spec.inputs, vertex=vc))
        return nodes, conf.network_inputs[0], conf.network_outputs[0], vocab
    raise DecodeUnsupported(f"cannot decode a {type(model).__name__}")


class DecodeEngine:
    def __init__(self, model, *, slots=4, max_len=128, compile_tracker=None,
                 registry=None, paged=False, block_size=16, num_blocks=None,
                 cost_registry=None):
        self.model = model
        self.slots = int(slots)
        self.capacity = int(max_len)
        self.paged = bool(paged)
        self.block_size = int(block_size)
        if self.paged:
            if self.block_size < 1 or (self.block_size
                                       & (self.block_size - 1)):
                raise ValueError(f"block_size must be a power of two, got "
                                 f"{self.block_size}")
            # capacity in whole blocks: the table addresses nothing finer
            bs = self.block_size
            self.capacity = -(-self.capacity // bs) * bs
            self.max_blocks = self.capacity // bs
            # default pool: every slot fully backed, +1 for the scratch
            # block — byte-parity with the slab, so paged-vs-slab parity
            # tests compare equal capacity (the scheduler passes a smaller
            # pool to actually oversubscribe)
            self.num_blocks = (self.slots * self.max_blocks + 1
                               if num_blocks is None else int(num_blocks))
            if self.num_blocks < 2:
                raise ValueError("paged cache needs >= 2 blocks "
                                 "(block 0 is scratch)")
        else:
            self.max_blocks = 0
            self.num_blocks = 0
        self.nodes, self.input_name, self.output_name, self.vocab = \
            build_plan(model)
        if model.params is None:
            model.init()
        self._dtype = model._dtype
        # recurrent carries accumulate in f32 for sub-32-bit param dtypes
        # (mirrors nn/layers/recurrent._lstm_scan's acc_dt choice)
        self._acc_dtype = (jnp.float32
                           if jnp.issubdtype(self._dtype, jnp.floating)
                           and jnp.finfo(self._dtype).bits < 32
                           else self._dtype)
        self.compile_tracker = compile_tracker
        self.registry = registry            # MetricsRegistry for jit counters
        # live cost attribution (telemetry/cost.py): each decode executable
        # family (step / prefill:L / verify:W) is captured at first call and
        # its wall time sampled every Nth dispatch (the sync is paid only on
        # sampled dispatches — decode steps are otherwise async)
        self.cost_registry = cost_registry
        # mesh-sharded decode (serving/mesh.py): a wrapped model carries the
        # serving MeshContext; the KV cache partitions its head axis over
        # the mesh model axis and the step/prefill executables pin the
        # cache's out_shardings so donation survives partitioning
        self.mesh = getattr(model, "mesh_context", None)
        self._cache_shardings = None        # lazily built pytree
        self._step_fn = None
        self._prefill_fns = {}              # length bucket -> jitted fn
        self._verify_fns = {}               # window size W -> jitted fn
        self._compiled = set()              # labels whose first call was timed
        self._jit_lock = threading.Lock()
        # default (greedy) sampling operands, built once: callers that never
        # sample pay zero per-call operand construction
        self._greedy_step_ops = _sampling.batch_operands(self.slots)
        self._greedy_slot_ops = _sampling.slot_operands(None, 0)

    # ------------------------------------------------------------ cache
    def _cache_zeros(self):
        """Abstract cache construction (shapes/dtypes only — placement is
        `init_cache`'s job, so `cache_bytes` can eval_shape this)."""
        layers = {}
        for node in self.nodes:
            if node.kind != "layer":
                continue
            m = node.module
            if isinstance(m, SelfAttentionLayerModule):
                H = int(m.conf.n_heads)
                Dh = int(m.conf.n_out) // H
                # paged: one shared pool per layer instead of per-slot rows;
                # [N, bs, H, Dh] keeps the head axis at index 2, so the mesh
                # cache_sharding rule (4-D -> shard axis 2) head-shards the
                # pool exactly as it does the slab
                shape = ((self.num_blocks, self.block_size, H, Dh)
                         if self.paged
                         else (self.slots, self.capacity, H, Dh))
                layers[node.name] = {"k": jnp.zeros(shape, self._dtype),
                                     "v": jnp.zeros(shape, self._dtype)}
            elif isinstance(m, _BaseLSTMModule):
                n_out = int(m.conf.n_out)
                layers[node.name] = {
                    "h": jnp.zeros((self.slots, n_out), self._acc_dtype),
                    "c": jnp.zeros((self.slots, n_out), self._acc_dtype)}
        return {"lengths": jnp.zeros((self.slots,), jnp.int32),
                "layers": layers}

    def init_cache(self):
        """Fresh all-zero cache pytree (slot lengths all 0); on a serving
        mesh every entry is placed under its head-sharded NamedSharding."""
        cache = self._cache_zeros()
        if self.mesh is None:
            return cache
        return jax.tree_util.tree_map(
            lambda leaf, s: jax.device_put(leaf, s), cache,
            self.cache_shardings())

    def cache_shardings(self):
        """NamedSharding pytree matching the cache (mesh only): attention
        K/V [slots, capacity, H, Dh] shard heads over the model axis,
        recurrent carries shard features, lengths replicate."""
        if self._cache_shardings is None:
            shapes = jax.eval_shape(self._cache_zeros)
            self._cache_shardings = jax.tree_util.tree_map(
                lambda leaf: self.mesh.cache_sharding(leaf.shape), shapes)
        return self._cache_shardings

    def cache_bytes(self, per_shard=False):
        # eval_shape: sizes from the abstract pytree, no device allocation
        shapes = jax.eval_shape(self._cache_zeros)
        if not per_shard or self.mesh is None:
            return sum(int(x.size * x.dtype.itemsize)
                       for x in jax.tree_util.tree_leaves(shapes))
        # per-shard: what ONE chip holds resident — the honest capacity
        # number for admission and gauges on a mesh (a head-sharded entry
        # puts 1/n_model of its bytes on each chip; uneven entries stay
        # replicated and count whole)
        total = 0
        for x in jax.tree_util.tree_leaves(shapes):
            nbytes = int(x.size * x.dtype.itemsize)
            total += nbytes // self.mesh.cache_shard_count(x.shape)
        return total

    # ------------------------------------------------------------ walks
    def _paged_append_seq(self, entry, t, row):
        """Scatter a [L, H, Dh] token sequence into the pool along `row`
        (the slot's table row): the L positions reshape into L/bs chunks of
        one block each, landing at the row's physical block ids. Pad chunks
        of a prefill bucket address block 0 (scratch) — over-length writes
        land where nobody reads instead of needing in-trace bounds checks."""
        bs = self.block_size
        L = t.shape[0]
        chunks = -(-L // bs)
        pad = chunks * bs - L
        if pad:
            t = jnp.pad(t, ((0, pad), (0, 0), (0, 0)))
        tc = t.reshape(chunks, bs, t.shape[1], t.shape[2])
        return entry.at[row[:chunks]].set(tc.astype(entry.dtype))

    def _walk_prefill(self, params, states, x0, mask, cache, slot, length,
                      table=None):
        """Full-sequence forward over the plan, capturing each stateful
        layer's K/V (resp. final carry) into `slot`'s cache rows — in paged
        mode, into the pool blocks of `slot`'s table row."""
        acts = {self.input_name: x0}
        layers = dict(cache["layers"])
        if table is not None:
            row = lax.dynamic_index_in_dim(table, slot, 0, keepdims=False)
        for node in self.nodes:
            if node.kind == "input":
                continue
            if node.kind == "vertex":
                acts[node.name] = node.vertex.apply(
                    [acts[i] for i in node.inputs])
                continue
            m = node.module
            p, s = params[node.name], states[node.name]
            x = acts[node.inputs[0]]
            if isinstance(m, SelfAttentionLayerModule):
                q, k, v = m.project_qkv(p, x)             # [1, L, H, Dh]
                out = m.attend(q, k, v, mask)
                y = m.finish(p, out, mask)
                entry = layers[node.name]
                if table is not None:
                    layers[node.name] = {
                        "k": self._paged_append_seq(entry["k"], k[0], row),
                        "v": self._paged_append_seq(entry["v"], v[0], row)}
                else:
                    z = jnp.zeros((), slot.dtype)  # match the traced slot's
                    layers[node.name] = {          # index dtype under x64
                        "k": lax.dynamic_update_slice(
                            entry["k"], k.astype(entry["k"].dtype),
                            (slot, z, z, z)),
                        "v": lax.dynamic_update_slice(
                            entry["v"], v.astype(entry["v"].dtype),
                            (slot, z, z, z))}
            elif isinstance(m, _BaseLSTMModule):
                n_out = int(m.conf.n_out)
                zeros = (jnp.zeros((1, n_out), self._dtype),
                         jnp.zeros((1, n_out), self._dtype))
                # masked steps carry state through (the scan's contract), so
                # the final carry equals the state after `length` real steps
                y, _, _, (hf, cf) = m.forward(p, s, x, mask=mask,
                                              initial_state=zeros,
                                              return_state=True)
                entry = layers[node.name]
                z = jnp.zeros((), slot.dtype)
                layers[node.name] = {
                    "h": lax.dynamic_update_slice(
                        entry["h"], hf.astype(entry["h"].dtype), (slot, z)),
                    "c": lax.dynamic_update_slice(
                        entry["c"], cf.astype(entry["c"].dtype), (slot, z))}
            else:
                y = m.forward(p, s, x, train=False, rng=None, mask=mask)[0]
            acts[node.name] = y
        return acts[self.output_name], layers

    def _walk_step(self, params, states, x0, cache, pos, kv_valid,
                   table=None):
        """[slots, 1, f] single-token forward against the cache. `pos` is
        the per-slot append position (clamped), `kv_valid` the number of
        valid cache entries including the appended token."""
        from ..kernels import flash_decode, flash_decode_paged
        acts = {self.input_name: x0}
        layers = dict(cache["layers"])
        if table is not None:
            bs = self.block_size
            # physical (block, offset) of each slot's append position; an
            # unallocated logical block maps to 0 = scratch, so a slot the
            # scheduler hasn't backed writes where nobody reads
            blk = jnp.take_along_axis(table, (pos // bs)[:, None],
                                      axis=1)[:, 0]
            off = pos % bs
        for node in self.nodes:
            if node.kind == "input":
                continue
            if node.kind == "vertex":
                acts[node.name] = node.vertex.apply(
                    [acts[i] for i in node.inputs])
                continue
            m = node.module
            p, s = params[node.name], states[node.name]
            x = acts[node.inputs[0]]
            if isinstance(m, SelfAttentionLayerModule):
                q, kt, vt = m.project_qkv(p, x)           # [S, 1, H, Dh]
                entry = layers[node.name]
                if table is not None:
                    nk = entry["k"].at[blk, off].set(
                        kt[:, 0].astype(entry["k"].dtype))
                    nv = entry["v"].at[blk, off].set(
                        vt[:, 0].astype(entry["v"].dtype))
                    layers[node.name] = {"k": nk, "v": nv}
                    out = flash_decode_paged(
                        q, nk, nv, table, kv_valid,
                        use_pallas=getattr(m.conf, "use_pallas", False))
                else:
                    append = jax.vmap(
                        lambda row, t, at: lax.dynamic_update_slice(
                            row, t, (at, jnp.zeros((), at.dtype),
                                     jnp.zeros((), at.dtype))))
                    nk = append(entry["k"], kt.astype(entry["k"].dtype), pos)
                    nv = append(entry["v"], vt.astype(entry["v"].dtype), pos)
                    layers[node.name] = {"k": nk, "v": nv}
                    out = flash_decode(q, nk, nv, kv_valid,
                                       use_pallas=getattr(m.conf,
                                                          "use_pallas",
                                                          False))
                y = m.finish(p, out.astype(x.dtype), None)
            elif isinstance(m, _BaseLSTMModule):
                entry = layers[node.name]
                y, _, _, (hf, cf) = m.forward(
                    p, s, x, initial_state=(entry["h"], entry["c"]),
                    return_state=True)
                layers[node.name] = {"h": hf.astype(entry["h"].dtype),
                                     "c": cf.astype(entry["c"].dtype)}
            else:
                y = m.forward(p, s, x, train=False, rng=None)[0]
            acts[node.name] = y
        return acts[self.output_name], layers

    @staticmethod
    def _verify_attend(q, k, v, start):
        """[1, W, H, Dh] window queries vs one slot's full [1, C, H, Dh]
        cache row, causal against GLOBAL positions: query i (at position
        start+i) sees keys [0, start+i]. Cache entries beyond start+W hold
        stale garbage from longer rolled-back windows — causally masked, so
        rollback never has to zero them. W is tiny (K+1 draft tokens), so
        the [H, W, C] score tile is reference-einsum territory; a Mosaic
        flash variant with a query offset is the rig follow-up."""
        W, C = q.shape[1], k.shape[1]
        scale = 1.0 / float(np.sqrt(q.shape[-1]))
        qpos = start + jnp.arange(W, dtype=jnp.int32)
        kpos = jnp.arange(C, dtype=jnp.int32)
        mask = kpos[None, :] <= qpos[:, None]                # [W, C]
        s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) * scale
        s = jnp.where(mask[None, None], s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v.astype(jnp.float32))
        return out.astype(q.dtype)

    def _walk_verify(self, params, states, x0, cache, slot, start):
        """[1, W, f] window forward for speculative verify: each attention
        layer appends the window's K/V at `slot` row offset `start` and
        attends the window against the whole row. Attention-only by
        construction — `verify()` rejects recurrent plans, because rollback
        is a host-side length reset and carries cannot rewind."""
        acts = {self.input_name: x0}
        layers = dict(cache["layers"])
        for node in self.nodes:
            if node.kind == "input":
                continue
            if node.kind == "vertex":
                acts[node.name] = node.vertex.apply(
                    [acts[i] for i in node.inputs])
                continue
            m = node.module
            p, s = params[node.name], states[node.name]
            x = acts[node.inputs[0]]
            if isinstance(m, SelfAttentionLayerModule):
                q, k, v = m.project_qkv(p, x)             # [1, W, H, Dh]
                entry = layers[node.name]
                z = jnp.zeros((), slot.dtype)
                st = jnp.asarray(start, slot.dtype)
                nk = lax.dynamic_update_slice(
                    entry["k"], k.astype(entry["k"].dtype), (slot, st, z, z))
                nv = lax.dynamic_update_slice(
                    entry["v"], v.astype(entry["v"].dtype), (slot, st, z, z))
                layers[node.name] = {"k": nk, "v": nv}
                krow = lax.dynamic_index_in_dim(nk, slot, 0, keepdims=True)
                vrow = lax.dynamic_index_in_dim(nv, slot, 0, keepdims=True)
                out = self._verify_attend(q, krow, vrow, start)
                y = m.finish(p, out.astype(x.dtype), None)
            else:
                y = m.forward(p, s, x, train=False, rng=None)[0]
            acts[node.name] = y
        return acts[self.output_name], layers

    # ------------------------------------------------------- executables
    def _one_hot(self, ids):
        return jax.nn.one_hot(ids, self.vocab, dtype=self._dtype)

    def _build_step(self):
        C = self.capacity
        paged = self.paged

        def step_fn(params, states, cache, ids, samp, table):
            # int8 serving weights: decode executables consume the narrow
            # codes too; the fused dequant is the same one output() traces
            params = self.model._dequant_params(params)
            lengths = cache["lengths"]
            pos = jnp.clip(lengths, 0, C - 1)
            x0 = self._one_hot(ids[:, None])              # [S, 1, V]
            y, layers = self._walk_step(params, states, x0, cache,
                                        pos, pos + 1,
                                        table=table if paged else None)
            probs = y[:, -1].astype(jnp.float32)          # [S, V]
            new_cache = {"lengths": jnp.minimum(lengths + 1, C),
                         "layers": layers}
            return new_cache, _sampling.sample_tokens(probs, samp), probs

        return jax.jit(step_fn, donate_argnums=(2,), **self._jit_sharding())

    def _build_prefill(self, L):
        paged = self.paged

        def prefill_fn(params, states, cache, slot, ids, length, samp,
                       table):
            params = self.model._dequant_params(params)
            x0 = self._one_hot(ids[None, :])              # [1, L, V]
            valid = (jnp.arange(L, dtype=jnp.int32)
                     < length).astype(self._dtype)[None]  # [1, L]
            y, layers = self._walk_prefill(params, states, x0, valid,
                                           cache, slot, length,
                                           table=table if paged else None)
            z = jnp.zeros((), length.dtype)
            probs = lax.dynamic_slice(
                y, (z, length - 1, z), (1, 1, self.vocab))[0, 0]
            probs = probs.astype(jnp.float32)
            new_cache = {"lengths": cache["lengths"].at[slot].set(length),
                         "layers": layers}
            return new_cache, _sampling.sample_tokens(probs[None],
                                                      samp)[0], probs

        return jax.jit(prefill_fn, donate_argnums=(2,),
                       **self._jit_sharding())

    def _build_verify(self, W):
        def verify_fn(params, states, cache, slot, ids, start):
            params = self.model._dequant_params(params)
            x0 = self._one_hot(ids[None, :])              # [1, W, V]
            y, layers = self._walk_verify(params, states, x0, cache,
                                          slot, start)
            probs = y[0].astype(jnp.float32)              # [W, V]
            # lengths unchanged: the accept decision is host-side, and the
            # host commits the accepted length via set_length afterwards
            new_cache = {"lengths": cache["lengths"], "layers": layers}
            return new_cache, probs

        return jax.jit(verify_fn, donate_argnums=(2,),
                       **self._jit_sharding(n_repl=1))

    def _jit_sharding(self, n_repl=2):
        """Extra jit kwargs on a mesh: pin the output cache to the SAME
        head-sharded placement as the donated input cache, so GSPMD's
        propagation can never pick a layout that breaks buffer donation —
        the zero-fresh-allocation steady state (GL011's sibling invariant)
        holds sharded exactly as it does on one chip. Token ids and probs
        replicate (they're host-read every step); `n_repl` is how many such
        trailing outputs the executable returns."""
        if self.mesh is None:
            return {}
        repl = self.mesh.cache_sharding(())     # replicated NamedSharding
        return {"out_shardings":
                (self.cache_shardings(),) + (repl,) * n_repl}

    def _ensure_placed(self):
        """A mesh-wrapped model keeps its params placed (TP specs or
        replicated) — re-checked per call because quantize/dequantize swap
        the params object; identity-cached so steady state pays nothing."""
        placer = getattr(self.model, "ensure_placed", None)
        if placer is not None:
            placer()

    def _run(self, fn, label, bucket, *args):
        """Invoke a decode executable. On a mesh, the call takes the
        context's run_lock and blocks until ready inside it: one
        partitioned wave in flight per mesh, or concurrently-launched
        collectives (this step vs the batcher's /predict dispatch)
        interleave their rendezvous participants and deadlock XLA's CPU
        runtime. Single-chip engines skip both."""
        if self.mesh is None:
            return self._timed(fn, label, bucket, *args)
        with self.mesh.run_lock:
            out = self._timed(fn, label, bucket, *args)
            jax.block_until_ready(out)
            return out

    def _timed(self, fn, label, bucket, *args):
        """Invoke a decode executable; the first call per label is the XLA
        compile and is timed into the compile accounting (CompileTracker
        phase="decode" + jit_compiles_total), same discipline as the
        batcher's observed buckets. With a cost registry attached, the first
        call also captures the executable's XLA costs (from an abstract-arg
        snapshot taken BEFORE the donating call) and every Nth later call is
        wall-timed into the sampled dispatch_ms histogram."""
        cr = self.cost_registry
        if label in self._compiled:
            if cr is not None and cr.dispatch_due(label):
                t0 = monotonic_s()
                out = fn(*args)
                jax.block_until_ready(out[1])
                cr.observe_dispatch(label, (monotonic_s() - t0) * 1000.0)
                return out
            return fn(*args)
        abs_args = None
        if cr is not None:
            try:
                from ..telemetry.cost import abstractify
                abs_args = abstractify(args)
            except Exception:
                abs_args = None
        t0 = monotonic_s()
        out = fn(*args)
        jax.block_until_ready(out[1])
        ms = (monotonic_s() - t0) * 1000.0
        self._compiled.add(label)
        record_jit_compile(label, ms, registry=self.registry)
        if self.compile_tracker is not None:
            self.compile_tracker.record(ms, bucket=bucket, phase="decode")
        if cr is not None and abs_args is not None:
            cr.capture(label, fn, abs_args, family="decode",
                       samples=self._cost_samples(label))
            cr.dispatch_due(label)
            cr.observe_dispatch(label, ms)
        return out

    def _cost_samples(self, label):
        """Tokens one execution of this executable serves — the per-token
        normalizer for the cost table: a step advances every slot one
        token; prefill:L ingests L tokens; verify:W scores a W-token
        window."""
        if label == "decode_step":
            return self.slots
        tail = label.rsplit(":", 1)
        if len(tail) == 2 and tail[1].isdigit():
            return int(tail[1])
        return 1

    def prefill_bucket(self, n):
        return bucket_for_len(n, self.capacity)

    def observed_buckets(self):
        with self._jit_lock:
            return sorted(self._prefill_fns)

    def executable_counts(self):
        """{label: XLA cache size} for the compiled decode executables — the
        hard recompile assertion (a retrace would grow a count past 1).
        On a mesh these are PER-SHARD sizes in the only honest sense: one
        partitioned executable per label serves all chips, so a sharded
        cache must still report 1 per label — a mesh engine that minted a
        per-chip executable family would show up here as a count of
        n_chips, and the smoke/tests pin it at 1."""
        out = {}
        with self._jit_lock:
            fns = [("decode_step", self._step_fn)] + \
                [(f"decode_prefill:{L}", f)
                 for L, f in sorted(self._prefill_fns.items())] + \
                [(f"decode_verify:{W}", f)
                 for W, f in sorted(self._verify_fns.items())]
        for label, fn in fns:
            if fn is None:
                continue
            size = getattr(fn, "_cache_size", None)
            if callable(size):
                out[label] = int(size())
        return out

    # ------------------------------------------------------------- api
    def full_table(self, slots=None):
        """Fully-provisioned block table (paged mode): slot s owns blocks
        [1 + s*max_blocks, ...) contiguously. This is the static layout
        engine-level callers (generate, warmup, parity tests) use — the
        scheduler builds real tables block-by-block from its BlockPool.
        Requires the default full-size pool."""
        if not self.paged:
            raise ValueError("full_table() is paged-mode only")
        n = self.slots if slots is None else int(slots)
        nb = self.max_blocks
        table = np.zeros((self.slots, nb), np.int32)
        for s in range(min(n, self.slots)):
            want = 1 + s * nb + np.arange(nb, dtype=np.int32)
            # a smaller-than-default pool can't back every slot: leave the
            # overflow on scratch (warmup tolerates garbage K/V)
            table[s] = np.where(want < self.num_blocks, want, 0)
        return table

    def _step_operands(self, sampling):
        return self._greedy_step_ops if sampling is None else sampling

    def prefill(self, cache, slot, prompt_ids, sampling=None, step_index=0,
                table=None):
        """Run `prompt_ids` (python ints / 1-D array) into cache slot `slot`;
        returns (cache, first generated id, last-position probs [vocab]).

        `sampling`: a SamplerConfig (greedy when None); `step_index` is the
        fold_in counter of the emitted token — 0 on a fresh admission,
        len(partial) on a post-preemption re-prefill. `table`: the paged
        block table (defaults to the static full table)."""
        ids = np.asarray(prompt_ids, np.int32).reshape(-1)
        n = ids.shape[0]
        if n < 1:
            raise ValueError("empty prompt")
        if n >= self.capacity:
            raise ValueError(
                f"prompt of {n} tokens does not fit the cache "
                f"(capacity {self.capacity}, needs room for >=1 new token)")
        self._ensure_placed()
        L = self.prefill_bucket(n)
        padded = np.zeros((L,), np.int32)
        padded[:n] = ids
        if sampling is None and step_index == 0:
            samp = self._greedy_slot_ops
        else:
            samp = _sampling.slot_operands(sampling, step_index)
        if self.paged and table is None:
            table = self.full_table()
        with self._jit_lock:
            fn = self._prefill_fns.get(L)
            if fn is None:
                fn = self._prefill_fns[L] = self._build_prefill(L)
        cache, nid, probs = self._run(
            fn, f"decode_prefill:{L}", L, self.model.params,
            self.model.states, cache, np.int32(slot), padded, np.int32(n),
            samp, table if self.paged else None)
        return cache, int(nid), np.asarray(probs)

    def step(self, cache, last_ids, sampling=None, table=None):
        """Advance every slot one token. `last_ids`: [slots] int token ids
        (inactive slots may carry any id; their outputs are ignored and their
        cache rows are reset by the next prefill). `sampling`: the operand
        dict from sampling.batch_operands (greedy when None — per-request
        sampling params are ARRAY operands here, never jit keys). Returns
        (cache, next_ids [slots] np.int32, probs [slots, vocab])."""
        ids = np.asarray(last_ids, np.int32).reshape(self.slots)
        self._ensure_placed()
        if self.paged and table is None:
            table = self.full_table()
        with self._jit_lock:
            if self._step_fn is None:
                self._step_fn = self._build_step()
            fn = self._step_fn
        cache, nxt, probs = self._run(
            fn, "decode_step", "step", self.model.params, self.model.states,
            cache, ids, self._step_operands(sampling),
            table if self.paged else None)
        return cache, np.asarray(nxt), np.asarray(probs)

    def has_recurrent(self):
        return any(node.kind == "layer"
                   and isinstance(node.module, _BaseLSTMModule)
                   for node in self.nodes)

    def verify(self, cache, slot, tokens, start):
        """Speculative verify: append the W-token window `tokens` at row
        offset `start` of `slot` and return (cache, probs [W, vocab]) — the
        next-token distribution AFTER each window position, all W in ONE
        batched pass. The caller owns the accept decision and commits the
        surviving length via `set_length` (rollback = not advancing it).
        One executable per W; attention-only, slab-layout only."""
        if self.paged:
            raise DecodeUnsupported(
                "speculative verify runs on the slab layout (the paged "
                "scheduler path and the verify window are separate tiers)")
        if self.has_recurrent():
            raise DecodeUnsupported(
                "verify needs rewind-free state: recurrent carries cannot "
                "roll back to `start` after a rejected draft")
        ids = np.asarray(tokens, np.int32).reshape(-1)
        W = ids.shape[0]
        if W < 1:
            raise ValueError("empty verify window")
        if int(start) + W > self.capacity:
            raise ValueError(
                f"verify window [{int(start)}, {int(start) + W}) exceeds "
                f"capacity {self.capacity}")
        self._ensure_placed()
        with self._jit_lock:
            fn = self._verify_fns.get(W)
            if fn is None:
                fn = self._verify_fns[W] = self._build_verify(W)
        cache, probs = self._run(
            fn, f"decode_verify:{W}", W, self.model.params,
            self.model.states, cache, np.int32(slot), ids, np.int32(start))
        return cache, np.asarray(probs)

    def set_length(self, cache, slot, n):
        """Host-side length commit for `slot` (the speculative accept /
        rollback primitive: cache rows beyond the new length become dead
        weight the causal mask hides)."""
        lengths = np.asarray(cache["lengths"]).copy()
        lengths[int(slot)] = int(n)
        out = dict(cache)
        if self.mesh is not None:
            out["lengths"] = jax.device_put(
                jnp.asarray(lengths), self.cache_shardings()["lengths"])
        else:
            out["lengths"] = jnp.asarray(lengths)
        return out

    def carry_snapshot(self, cache):
        """Host copy of the recurrent carries + lengths — tiny ([slots,
        n_out] per LSTM layer, no K/V. The speculative engine snapshots a
        recurrent DRAFT before proposing and restores on rollback; attention
        entries don't need it (rollback is a length reset)."""
        snap = {"lengths": np.asarray(cache["lengths"]).copy(), "layers": {}}
        for name, entry in cache["layers"].items():
            if "h" in entry:
                snap["layers"][name] = {k: np.asarray(v).copy()
                                        for k, v in entry.items()}
        return snap

    def carry_restore(self, cache, snap):
        """Rewind the recurrent carries (and lengths) to a snapshot."""
        layers = dict(cache["layers"])
        shardings = self.cache_shardings() if self.mesh is not None else None
        for name, entry in snap["layers"].items():
            if shardings is not None:
                layers[name] = {
                    k: jax.device_put(jnp.asarray(v),
                                      shardings["layers"][name][k])
                    for k, v in entry.items()}
            else:
                layers[name] = {k: jnp.asarray(v)
                                for k, v in entry.items()}
        out = {"lengths": jnp.asarray(snap["lengths"]), "layers": layers}
        if shardings is not None:
            out["lengths"] = jax.device_put(jnp.asarray(snap["lengths"]),
                                            shardings["lengths"])
        return out

    def warmup(self, buckets=()):
        """Compile the step and the given prefill buckets on a scratch cache
        (deploy-time warm-up: a hot-swapped model is never cold)."""
        cache = self.init_cache()
        for L in sorted(set(int(b) for b in buckets)):
            L = min(max(L, MIN_PREFILL_BUCKET), self.capacity)
            # a (L-1)-token prompt maps to bucket L
            cache, _, _ = self.prefill(cache, 0, np.zeros((max(L - 1, 1),),
                                                          np.int32))
        cache, _, _ = self.step(cache, np.zeros((self.slots,), np.int32))
        return self

    def generate(self, prompt_ids, max_new_tokens=20, stop_id=None,
                 sampler=None):
        """Single-request decode on slot 0 (the host loop behind
        `network.generate`); greedy unless `sampler` (a SamplerConfig)
        says otherwise. Returns the list of generated token ids."""
        if int(max_new_tokens) < 1:
            # same contract as DecodeScheduler.submit: the prefill always
            # emits one token, so 0 is unservable, not "empty result"
            raise ValueError("max_new_tokens must be >= 1")
        cache = self.init_cache()
        table = self.full_table() if self.paged else None
        cache, nid, _ = self.prefill(cache, 0, prompt_ids, sampling=sampler,
                                     table=table)
        out = [nid]
        ids = np.zeros((self.slots,), np.int32)
        while len(out) < int(max_new_tokens) and out[-1] != stop_id \
                and len(np.asarray(prompt_ids).reshape(-1)) + len(out) \
                < self.capacity:
            ids[0] = out[-1]
            samp = None
            if sampler is not None:
                # fold_in counter = index of the token being emitted
                samp = _sampling.batch_operands(
                    self.slots, {0: sampler}, {0: len(out)})
            cache, nxt, _ = self.step(cache, ids, sampling=samp,
                                      table=table)
            out.append(int(nxt[0]))
        return out
