"""Autoregressive decode subsystem: KV-cache continuous batching, sampled
decoding, paged KV, and speculative verify.

The LLM-style workloads this repo trains (`zoo.transformer_lm`,
`zoo.char_rnn_lstm`) are served token-by-token here, with the same
zero-steady-state-recompile discipline the serving batcher and device-side
ingest established:

- `DecodeEngine` compiles a fixed-shape decode step (every token, every
  mix of co-batched requests), one prefill per power-of-two prompt-length
  bucket, and one speculative-verify pass per window size. The KV cache is
  a fixed [slots, capacity, heads, head_dim] tensor per attention layer
  (plus a [slots, n_out] carry pair per recurrent layer) with a per-slot
  length vector; appends are `lax.dynamic_update_slice` writes, and the
  attention step masks against the length vector inside the flash kernel
  (`kernels.flash_attention.flash_decode`).
- `sampling.SamplerConfig` carries a request's temperature / top-k /
  top-p / seed; they enter the step executable as BATCH-SHAPED ARRAY
  OPERANDS (never jit keys — graftlint GL016), with per-slot
  `fold_in(PRNGKey(seed), step)` keys making every sampled stream
  reproducible across runs, hot-swaps, and preemptions.
- `paged.BlockPool` + a `[slots, max_blocks]` block-table operand replace
  the slab with pow2-token pool blocks (`DecodeEngine(paged=True)`,
  `kernels.flash_attention.flash_decode_paged`): capacity is allocated
  block-by-block as requests generate, so admission can OVERSUBSCRIBE and
  reclaim via preempt-and-requeue instead of stranding slab bytes.
- `SpeculativeEngine` pairs a cheap draft with the serving target: the
  draft proposes K tokens, the target scores all K in one batched verify,
  and greedy speculative output is token-for-token identical to
  target-only decoding.
- `DecodeScheduler` owns slot lifecycle: requests join free slots and
  retire PER TOKEN (continuous batching), with admission shedding,
  per-token deadline budgets, TTFT/ITL histograms with trace exemplars,
  block allocation/preemption in paged mode, and ModelRegistry hot-swap
  (drain-then-swap, engines cached per model so a rollback never
  recompiles).

`ServingServer(decode=True)` exposes this as POST /generate, routed through
the same FleetFrontend failover/canary layer as /predict.
"""
from .engine import DecodeEngine, DecodeUnsupported
from .paged import BlockPool, PoolExhausted, blocks_for
from .sampling import SamplerConfig
from .scheduler import DecodeScheduler, GenerateRequest
from .speculative import SpeculativeEngine

__all__ = ["BlockPool", "DecodeEngine", "DecodeScheduler",
           "DecodeUnsupported", "GenerateRequest", "PoolExhausted",
           "SamplerConfig", "SpeculativeEngine", "blocks_for"]
