"""Autoregressive decode subsystem: KV-cache continuous batching.

The LLM-style workloads this repo trains (`zoo.transformer_lm`,
`zoo.char_rnn_lstm`) are served token-by-token here, with the same
zero-steady-state-recompile discipline the serving batcher and device-side
ingest established:

- `DecodeEngine` compiles exactly TWO kinds of executables per model: one
  fixed-shape decode step (every token, every mix of co-batched requests)
  and one prefill per power-of-two prompt-length bucket. The KV cache is a
  fixed [slots, capacity, heads, head_dim] tensor per attention layer
  (plus a [slots, n_out] carry pair per recurrent layer) with a per-slot
  length vector; appends are `lax.dynamic_update_slice` writes, and the
  attention step masks against the length vector inside the flash kernel
  (`kernels.flash_attention.flash_decode`).
- `DecodeScheduler` owns slot lifecycle: requests join free slots and
  retire PER TOKEN (continuous batching), with admission shedding,
  per-token deadline budgets, TTFT/ITL histograms with trace exemplars,
  and ModelRegistry hot-swap (drain-then-swap, engines cached per model so
  a rollback never recompiles).

`ServingServer(decode=True)` exposes this as POST /generate, routed through
the same FleetFrontend failover/canary layer as /predict.
"""
from .engine import DecodeEngine, DecodeUnsupported
from .scheduler import DecodeScheduler, GenerateRequest

__all__ = ["DecodeEngine", "DecodeScheduler", "DecodeUnsupported",
           "GenerateRequest"]
