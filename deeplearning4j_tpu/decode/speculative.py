"""Speculative decoding: a cheap DRAFT proposes K tokens, the TARGET
verifies all K in ONE batched pass (arXiv 2211.17192's accept/rollback).

Why it wins: the decode step is HBM-bound — each target step streams the
whole KV cache and weight set to emit ONE token. The verify executable
(DecodeEngine._build_verify) streams the same bytes once but scores a
K+1-token window, so every accepted draft token amortizes the target's
memory traffic. Acceptance is what sets the speedup: a draft that agrees
with the target a fraction `r` of the time yields ~(1 + r*K') tokens per
target pass.

Contract (pinned in tests + tools/smoke_decode_v2.py): GREEDY speculative
output is token-for-token identical to target-only greedy decoding — a
draft token survives only when it IS the target's argmax, the first
mismatch is replaced by the target's argmax (which target-only decoding
would have emitted there), and a fully-accepted window earns the bonus
token from the window's last distribution. Speculation changes WHERE
tokens come from, never WHICH tokens come out.

Sampled mode runs the standard rejection scheme on the FILTERED
distributions (sampling.filter_probs_np, the numpy mirror of the traced
filter): accept draft token x with prob min(1, p_t(x)/p_d(x)); on the
first rejection resample from normalize(max(p_t - p_d, 0)). The output is
distributed exactly as target-only sampling — but it is a different draw
from that distribution, so sampled mode does not reproduce the
non-speculative token stream (greedy mode does, exactly).

Rollback mechanics, per model family:
- target: attention-only (slab layout). The verify pass writes the whole
  window into the cache; rollback = NOT advancing the slot length past the
  accepted prefix (`DecodeEngine.set_length`). Stale K/V beyond the
  accepted length is causally masked. Recurrent targets raise
  DecodeUnsupported — an LSTM carry cannot rewind to mid-window.
- draft: any decodable model. Attention drafts roll back by length too;
  recurrent drafts snapshot their carries before proposing
  (`carry_snapshot`, [slots, n_out] per layer — tiny) and restore +
  replay the accepted tokens on rejection.
"""
from __future__ import annotations

import numpy as np

from .engine import DecodeEngine, DecodeUnsupported
from .sampling import filter_probs_np


class SpeculativeEngine:
    """Draft+target pair decoding one request at a time (slot 0 of two
    single-slot engines). `k` is the proposal window; telemetry
    (acceptance rate, per-round token yield) feeds the bench's
    spec_acceptance_rate / spec_speedup_x numbers."""

    def __init__(self, draft_model, target_model, *, k=4, max_len=128,
                 compile_tracker=None, registry=None):
        if draft_model is target_model:
            raise ValueError("draft and target must be distinct models "
                             "(a self-draft verifies nothing)")
        self.k = int(k)
        if self.k < 1:
            raise ValueError("k must be >= 1")
        self.capacity = int(max_len)
        self.target = DecodeEngine(target_model, slots=1, max_len=max_len,
                                   compile_tracker=compile_tracker,
                                   registry=registry)
        if self.target.has_recurrent():
            raise DecodeUnsupported(
                "speculative target must be attention-only: verify rollback "
                "is a length reset and recurrent carries cannot rewind")
        self.draft = DecodeEngine(draft_model, slots=1, max_len=max_len,
                                  compile_tracker=compile_tracker,
                                  registry=registry)
        if self.draft.vocab != self.target.vocab:
            raise ValueError(
                f"draft vocab {self.draft.vocab} != target vocab "
                f"{self.target.vocab}: accept/rollback compares token ids")
        self._draft_recurrent = self.draft.has_recurrent()
        # telemetry (host counters; stats() snapshots them)
        self.proposed = 0
        self.accepted = 0
        self.rounds = 0
        self.emitted = 0
        self._reg_metrics = None
        if registry is not None:
            self._reg_metrics = (
                registry.counter("spec_proposed_total",
                                 "Draft tokens proposed"),
                registry.counter("spec_accepted_total",
                                 "Draft tokens accepted by the target"))
            registry.gauge("spec_acceptance_rate",
                           "Accepted/proposed draft tokens (lifetime)",
                           fn=lambda: self.acceptance_rate())

    @classmethod
    def from_registry(cls, model_registry, draft_version, target_version,
                      **kwargs):
        """Build from two deployed ModelRegistry versions (the serving-side
        wiring: draft and target are both ordinary registry citizens, so
        hot-swap/rollback machinery applies to either)."""
        draft = model_registry.get(draft_version).model
        target = model_registry.get(target_version).model
        return cls(draft, target, **kwargs)

    def acceptance_rate(self):
        return self.accepted / max(self.proposed, 1)

    def stats(self):
        return {"proposed": self.proposed, "accepted": self.accepted,
                "acceptance_rate": self.acceptance_rate(),
                "rounds": self.rounds, "emitted": self.emitted}

    def executable_counts(self):
        out = {}
        for tag, eng in (("target", self.target), ("draft", self.draft)):
            for label, n in eng.executable_counts().items():
                out[f"{tag}:{label}"] = n
        return out

    # --------------------------------------------------------------- decode
    def generate(self, prompt_ids, max_new_tokens=20, stop_id=None,
                 sampler=None):
        """Speculative decode; returns the generated token ids (greedy
        unless `sampler` — greedy output is exactly
        `DecodeEngine(target).generate(...)`)."""
        if int(max_new_tokens) < 1:
            raise ValueError("max_new_tokens must be >= 1")
        prompt = [int(t) for t in np.asarray(prompt_ids).reshape(-1)]
        if len(prompt) + 1 > self.capacity:
            raise ValueError(
                f"prompt of {len(prompt)} tokens leaves no room in "
                f"capacity {self.capacity}")
        greedy = sampler is None or sampler.is_greedy
        rng = None if greedy else np.random.default_rng(sampler.seed)

        tc = self.target.init_cache()
        dc = self.draft.init_cache()
        # prefill both; the TARGET's emission is the first output token
        # (the draft's is discarded — it only primes the draft cache)
        tc, first, _ = self.target.prefill(tc, 0, prompt, sampling=sampler)
        dc, _, _ = self.draft.prefill(dc, 0, prompt)
        # toks = prompt + emitted. Invariant between rounds: each cache
        # holds toks[:fed] with fed == len(toks) - 1 for the target (the
        # draft catches up lazily); toks[-1] is the pending token neither
        # model has consumed yet.
        toks = prompt + [first]
        fed_t = len(toks) - 1
        fed_d = len(prompt)
        out = [first]
        ids1 = np.zeros((1,), np.int32)

        def done():
            return len(out) >= int(max_new_tokens) or \
                (stop_id is not None and out[-1] == stop_id)

        while not done():
            # window sizing: verify appends W = kk+1 tokens at fed_t
            kk = min(self.k, self.capacity - len(toks))
            if kk < 1:
                break                                    # capacity reached
            # ---- draft catch-up: feed the tokens accepted last round
            while fed_d < len(toks) - 1:
                ids1[0] = toks[fed_d]
                dc, _, _ = self.draft.step(dc, ids1)
                fed_d += 1
            snap = self.draft.carry_snapshot(dc) if self._draft_recurrent \
                else None
            # ---- propose: kk greedy draft steps from the pending token
            drafts, draft_dists = [], []
            nxt = toks[-1]
            for _ in range(kk):
                ids1[0] = nxt
                dc, step_nxt, dp = self.draft.step(dc, ids1)
                fed_d += 1
                if greedy:
                    nxt = int(step_nxt[0])
                else:
                    dist = filter_probs_np(dp[0], sampler)
                    draft_dists.append(dist)
                    nxt = int(rng.choice(dist.shape[0], p=dist))
                drafts.append(nxt)
            # ---- verify: ONE batched target pass over the whole window
            window = [toks[-1]] + drafts                 # W = kk + 1
            tc, vprobs = self.target.verify(tc, 0, window, fed_t)
            # vprobs[i] is the target's next-token distribution AFTER
            # window position i — i.e. the distribution drafts[i] must
            # have come from to survive
            accepted = 0
            emitted = []
            for i, d in enumerate(drafts):
                if greedy:
                    t = int(np.argmax(vprobs[i]))
                    if d == t:
                        accepted += 1
                        emitted.append(d)
                        continue
                    emitted.append(t)                    # the correction
                    break
                pt = filter_probs_np(vprobs[i], sampler)
                pd = draft_dists[i]
                if rng.random() < min(1.0, pt[d] / max(pd[d], 1e-30)):
                    accepted += 1
                    emitted.append(d)
                    continue
                resid = np.maximum(pt - pd, 0.0)
                tot = resid.sum()
                pr = resid / tot if tot > 0 else pt
                emitted.append(int(rng.choice(pr.shape[0], p=pr)))
                break
            else:
                # full accept: the window's last distribution is a free
                # bonus token no extra pass pays for
                if greedy:
                    emitted.append(int(np.argmax(vprobs[kk])))
                else:
                    pb = filter_probs_np(vprobs[kk], sampler)
                    emitted.append(int(rng.choice(pb.shape[0], p=pb)))
            # ---- commit + rollback
            toks.extend(emitted)
            out.extend(emitted)
            # target: accepted prefix = pending + accepted drafts
            fed_t += 1 + accepted
            tc = self.target.set_length(tc, 0, fed_t)
            # draft: attention rolls back by length; recurrent restores the
            # pre-proposal carries (accepted tokens replay in the next
            # round's catch-up)
            if accepted < len(drafts):
                if self._draft_recurrent:
                    dc = self.draft.carry_restore(dc, snap)
                    fed_d = len(toks) - 1 - len(emitted)
                else:
                    # draft cache's first len(old toks)+accepted entries are
                    # exactly toks[:-1] (the correction token is pending)
                    fed_d = len(toks) - 1
                    dc = self.draft.set_length(dc, 0, fed_d)
            # full accept: draft already holds toks up to the last draft;
            # fed_d is len(toks) - 2 (bonus pending + its predecessor
            # unfed) — the next catch-up feeds it
            self.rounds += 1
            self.proposed += len(drafts)
            self.accepted += accepted
            self.emitted += len(emitted)
            if self._reg_metrics is not None:
                self._reg_metrics[0].add(len(drafts))
                self._reg_metrics[1].add(accepted)
        # over-emission past max_new_tokens / stop is trimmed, so output
        # length semantics match the plain decode loop
        if stop_id is not None and stop_id in out:
            out = out[:out.index(stop_id) + 1]
        return out[:int(max_new_tokens)]
