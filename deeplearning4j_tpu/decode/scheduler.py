"""DecodeScheduler: continuous batching over the DecodeEngine's cache slots.

One scheduler thread owns the engine, the live cache, and the slot
lifecycle; HTTP handler threads only touch the bounded admission queue.
Every loop iteration:

1. **admit** — free slots are filled from the queue (expired requests fail
   with DeadlineExceeded instead of burning a prefill). Each admission runs
   one prefill executable (compiled per pow2 prompt-length bucket) which
   also emits the request's FIRST token — time-to-first-token is observed
   on `decode_ttft_ms` with the request's trace id as exemplar.
2. **step** — one fixed-shape decode step advances EVERY active slot one
   token; the wall time is each active request's inter-token latency
   (`decode_itl_ms`). Requests retire per token (max_new_tokens reached,
   stop id emitted, cache capacity hit, or the per-token deadline budget
   spent — a deadline mid-generation returns the PARTIAL result with
   finish_reason="deadline", not an error).

Requests therefore join and leave the in-flight batch per token with zero
steady-state recompiles: after the step executable and a prompt-length
bucket have compiled once, no request mix recompiles anything
(counter-asserted in tests/test_decode.py and tools/smoke_decode.py via
CompileTracker / jit_compiles_total / the engine's XLA cache sizes).

Hot-swap: the scheduler pins one model version per cache generation. When
ModelRegistry's active version changes, admission pauses, in-flight
requests drain on the old engine (a step batch never mixes versions), then
the engine/cache swap. Engines are cached per model object, and
`warmup(model)` (wired into ServingServer.deploy) compiles the new
version's step + observed prefill buckets BEFORE the registry pointer
swaps — a deploy is never cold, a rollback never recompiles.

Sampling rides along per request: a SamplerConfig's temperature / top-k /
top-p / seed become batch-shaped ARRAY operands of the step wave
(decode/sampling.py), so greedy and creative requests co-batch in one
executable and per-request params never mint executables (GL016).

Paged mode (`paged=True`, decode/paged.py): the engine's slab becomes a
shared block pool and THIS loop thread owns the allocator — admission
allocates each request's prompt blocks and writes its table row, a slot
grows block-by-block as it generates, and retirement frees. The pool may
be smaller than slots x capacity (OVERSUBSCRIPTION): admission only needs
the prompt to fit NOW, betting most requests finish short. When the bet
loses — a growth allocation finds the pool dry (the watermark) — the
YOUNGEST active slot is preempted: its blocks free immediately, the
request re-queues at the FRONT with its partial tokens, and on re-admission
it re-prefills prompt+partial in one bucket pass whose sampling step index
continues the seeded stream exactly (the preemption is invisible in the
token stream). Deadline-expired and preempted slots retire through the
same `_release_slot` path, so slot ids, pool blocks, and the active_slots
gauge can never leak however a request leaves its slot.
"""
from __future__ import annotations

import collections
import threading

from concurrent.futures import Future, TimeoutError as FuturesTimeoutError

from ..serving.admission import (DeadlineExceeded, RejectedError,
                                 safe_set_exception, safe_set_result)
from ..serving.registry import NoModelDeployed
from ..telemetry.trace import current_span, get_tracer
from ..util.time_source import monotonic_s
from .paged import BlockPool, PoolExhausted, blocks_for, make_table
from .sampling import batch_operands


class GenerateRequest:
    __slots__ = ("prompt", "max_new_tokens", "stop_id", "future", "deadline",
                 "enqueued_at", "trace_ctx", "tokens", "slot", "version",
                 "ttft_ms", "finish_reason", "sampler", "admit_seq")

    def __init__(self, prompt, max_new_tokens, stop_id=None, deadline=None,
                 sampler=None):
        self.prompt = [int(t) for t in prompt]
        self.max_new_tokens = int(max_new_tokens)
        self.stop_id = stop_id
        self.future = Future()
        self.deadline = deadline          # absolute monotonic_s() or None
        self.enqueued_at = monotonic_s()
        self.trace_ctx = current_span()   # handler thread's span rides along
        self.tokens = []
        self.slot = None
        self.version = None
        self.ttft_ms = None
        self.finish_reason = None
        self.sampler = sampler            # SamplerConfig or None (greedy)
        self.admit_seq = None             # admission order; youngest preempts

    def expired(self, now=None):
        return self.deadline is not None and \
            (now if now is not None else monotonic_s()) > self.deadline

    def complete(self):
        safe_set_result(self.future, {
            "tokens": list(self.tokens),
            "n_prompt": len(self.prompt),
            "version": self.version,
            "ttft_ms": self.ttft_ms,
            "finish_reason": self.finish_reason,
        })

    def fail(self, exc):
        safe_set_exception(self.future, exc)


class DecodeScheduler:
    def __init__(self, registry, metrics_registry, *, slots=4, max_len=128,
                 queue_capacity=64, default_max_new_tokens=32, tracer=None,
                 compile_tracker=None, logger=None, idle_wait_s=0.2,
                 max_engines=4, paged=False, block_size=16,
                 pool_blocks=None, cost_registry=None):
        self.registry = registry                    # ModelRegistry
        self.slots = int(slots)
        self.max_len = int(max_len)
        self.paged = bool(paged)
        self.block_size = int(block_size)
        # allocatable pool size INCLUDING the scratch block; None = fully
        # backed (slots * ceil(max_len/bs) + 1 — no oversubscription).
        # Smaller pools oversubscribe: admission bets requests finish short
        # and the preempt/requeue path covers the losses.
        self.pool_blocks = None if pool_blocks is None else int(pool_blocks)
        self.queue_capacity = int(queue_capacity)
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.tracer = tracer if tracer is not None else get_tracer()
        self.compile_tracker = compile_tracker
        self.cost_registry = cost_registry
        self.logger = logger
        self.idle_wait_s = float(idle_wait_s)
        self.max_engines = int(max_engines)
        self.metrics_registry = metrics_registry

        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queue = collections.deque()
        self._closed = False
        self._thread = None
        # loop-thread-owned state
        self._engines = collections.OrderedDict()   # id(model) -> (model, eng)
        self._engine = None
        self._cache = None
        self._version = None
        self._active = {}                           # slot -> GenerateRequest
        self._free = list(range(self.slots))
        self._observed_buckets = set()
        self._admit_seq = 0
        # paged-mode allocator state (loop-thread-owned, rebuilt with the
        # cache each generation)
        self._pool = None                           # BlockPool
        self._table = None                          # [slots, max_blocks] i32
        self._slot_blocks = {}                      # slot -> [block ids]

        reg = metrics_registry
        self.m_requests = reg.counter("decode_requests_total",
                                      "Generate requests answered")
        self.m_tokens = reg.counter("decode_tokens_total",
                                    "Tokens generated (all requests)")
        self.m_shed = reg.counter("decode_shed_total",
                                  "Generate requests shed at admission (429)")
        self.m_expired = reg.counter(
            "decode_expired_total",
            "Generate requests whose deadline passed while queued (504)")
        self.m_errors = reg.counter("decode_errors_total",
                                    "Generate requests failed in the engine")
        self.m_preempted = reg.counter(
            "decode_preempted_total",
            "Slots preempted (blocks reclaimed, request re-queued with its "
            "partial tokens) when the KV block pool ran dry")
        self.m_ttft = reg.histogram(
            "decode_ttft_ms", "Time to first token (admission to first "
            "token), ms")
        self.m_itl = reg.histogram(
            "decode_itl_ms", "Inter-token latency (one decode step), ms")
        self.m_tps = reg.gauge("decode_tokens_per_sec",
                               "Decode throughput over the last step wave")
        reg.gauge("decode_active_slots", "In-flight generate requests",
                  fn=lambda: float(self.active_count()))
        reg.gauge("decode_queue_depth", "Generate requests awaiting a slot",
                  fn=lambda: float(self.depth()))
        # PER-SHARD cache bytes: on a mesh the KV cache partitions its head
        # axis across chips, and what admission/capacity must answer for is
        # what ONE chip holds resident — the global figure would overstate
        # per-chip pressure by n_model x (single-chip engines report the
        # same number either way)
        reg.gauge("decode_cache_mb",
                  "KV-cache bytes resident PER SHARD (MB) for the live "
                  "engine", fn=lambda: self.cache_mb())
        reg.gauge("decode_kv_pool_utilization",
                  "Allocated fraction of the paged KV block pool (0 when "
                  "the slab layout serves)",
                  fn=lambda: self.pool_utilization())
        for c in (self.m_requests, self.m_tokens, self.m_shed,
                  self.m_expired, self.m_errors, self.m_preempted):
            c.inc(0)

    # ------------------------------------------------------------ admission
    def depth(self):
        with self._lock:
            return len(self._queue)

    def active_count(self):
        # loop-thread-written dict; len() is atomic enough for a gauge
        return len(self._active)

    def submit(self, prompt_ids, max_new_tokens=None, timeout_ms=None,
               stop_id=None, sampler=None):
        """Admit one generate request; returns its Future (shed raises
        RejectedError, an unservable request ValueError). `sampler` is a
        sampling.SamplerConfig (None = greedy)."""
        max_new = self.default_max_new_tokens if max_new_tokens is None \
            else int(max_new_tokens)
        prompt = list(prompt_ids)
        if not prompt:
            raise ValueError("empty prompt")
        if max_new < 1:
            raise ValueError("max_new_tokens must be >= 1")
        if len(prompt) + max_new > self.max_len:
            raise ValueError(
                f"prompt ({len(prompt)}) + max_new_tokens ({max_new}) "
                f"exceeds the cache capacity {self.max_len}; split the "
                "request or deploy with a larger decode_max_len")
        if self.paged and self.pool_blocks is not None and \
                blocks_for(len(prompt) + 1, self.block_size) > \
                self.pool_blocks - 1:
            raise ValueError(
                f"prompt of {len(prompt)} tokens can never fit the KV "
                f"block pool ({self.pool_blocks - 1} allocatable blocks of "
                f"{self.block_size} tokens)")
        deadline = None if timeout_ms is None \
            else monotonic_s() + float(timeout_ms) / 1000.0
        req = GenerateRequest(prompt, max_new, stop_id=stop_id,
                              deadline=deadline, sampler=sampler)
        with self._work:
            if self._closed:
                self.m_shed.add(1)
                raise RejectedError("server is draining", retry_after_s=5)
            if len(self._queue) >= self.queue_capacity:
                self.m_shed.add(1)
                raise RejectedError(
                    f"decode queue full ({self.queue_capacity} pending)",
                    retry_after_s=1)
            self._queue.append(req)
            self._work.notify()
        return req.future

    def generate(self, prompt_ids, max_new_tokens=None, timeout_ms=None,
                 stop_id=None, wait_s=120.0, sampler=None):
        """Blocking convenience: submit + wait; a wait timeout abandons the
        request so it cannot burn a slot generating tokens nobody reads."""
        fut = self.submit(prompt_ids, max_new_tokens=max_new_tokens,
                          timeout_ms=timeout_ms, stop_id=stop_id,
                          sampler=sampler)
        try:
            return fut.result(timeout=wait_s)
        except FuturesTimeoutError:
            self.abandon(fut)
            raise

    def abandon(self, future):
        """Best-effort cancellation of a request whose caller gave up: a
        still-queued request is withdrawn and failed; an in-flight one has
        its token budget clamped so it retires at the next step instead of
        generating a full answer nobody will read."""
        with self._lock:
            for r in list(self._queue):
                if r.future is future:
                    self._queue.remove(r)
                    r.fail(RejectedError("abandoned by caller"))
                    return True
        for r in list(self._active.values()):   # loop-thread-owned; the
            if r.future is future:              # int write is benign
                r.max_new_tokens = 0
                return True
        return False

    # ------------------------------------------------------------ lifecycle
    def start(self):
        if self._thread is not None and self._thread.is_alive():
            return self
        with self._work:        # _closed is guarded by the work condition
            self._closed = False
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="decode-scheduler")
        self._thread.start()
        return self

    def stop(self, drain=True, timeout=30.0):
        """Stop admitting and exit once in-flight work finishes. drain=True
        (default) also serves what is already queued; drain=False sheds the
        queue with RejectedError (in-flight generations still run to their
        own finish — they are bounded by max_new_tokens)."""
        with self._work:
            self._closed = True
            if not drain:
                queued, self._queue = list(self._queue), collections.deque()
            else:
                queued = []
            self._work.notify_all()
        for r in queued:
            r.fail(RejectedError("server shutting down"))
        if self._thread is not None:
            self._thread.join(timeout)

    def probe(self):
        """HealthMonitor probe: unhealthy when the loop thread died."""
        t = self._thread
        if t is None:
            return "degraded", {"reason": "not started"}
        if not t.is_alive() and not self._closed:
            return "unhealthy", {"reason": "decode loop dead"}
        return "healthy", {"active": self.active_count(),
                           "queued": self.depth(),
                           "version": self._version}

    def snapshot(self):
        """JSON block for the serving /metrics snapshot."""
        with self._lock:     # _observed_buckets is written under this lock
            buckets = sorted(self._observed_buckets)
        out = {
            "requests": self.m_requests.get(),
            "tokens": self.m_tokens.get(),
            "shed": self.m_shed.get(),
            "expired": self.m_expired.get(),
            "errors": self.m_errors.get(),
            "active_slots": self.active_count(),
            "queue_depth": self.depth(),
            "tokens_per_sec": self.m_tps.get(),
            "ttft_ms": self.m_ttft.percentiles(),
            "itl_ms": self.m_itl.percentiles(),
            "version": self._version,
            "prefill_buckets": buckets,
            "cache_mb": self.cache_mb(),
        }
        if self.paged:
            pool = self._pool
            out["paged"] = {
                "block_size": self.block_size,
                "pool_blocks": pool.capacity_blocks if pool else 0,
                "used_blocks": pool.used_blocks if pool else 0,
                "high_water": pool.high_water if pool else 0,
                "utilization": self.pool_utilization(),
                "preempted": self.m_preempted.get(),
            }
        return out

    def pool_utilization(self):
        pool = self._pool
        return pool.utilization() if pool is not None else 0.0

    def cache_mb(self):
        """PER-SHARD KV-cache megabytes of the live engine (0.0 before the
        first deploy). Sharded caches divide each entry by its shard count,
        so the gauge answers "what does one chip hold", matching the
        per-chip HBM budget the capacity plane reasons about."""
        eng = self._engine
        if eng is None:
            return 0.0
        try:
            return float(eng.cache_bytes(per_shard=True)) / 1e6
        except Exception:
            return 0.0

    # ------------------------------------------------------------- engines
    def engine_for(self, model):
        """One DecodeEngine per model object, LRU-bounded — a rollback to a
        recently-served version reuses its compiled executables."""
        from .engine import DecodeEngine
        key = id(model)
        with self._lock:
            hit = self._engines.get(key)
            if hit is not None and hit[0] is model:
                self._engines.move_to_end(key)
                return hit[1]
        eng = DecodeEngine(model, slots=self.slots, max_len=self.max_len,
                           compile_tracker=self.compile_tracker,
                           registry=self.metrics_registry, paged=self.paged,
                           block_size=self.block_size,
                           num_blocks=self.pool_blocks,
                           cost_registry=self.cost_registry)
        with self._lock:
            self._engines[key] = (model, eng)
            self._engines.move_to_end(key)
            while len(self._engines) > self.max_engines:
                self._engines.popitem(last=False)
        return eng

    def warmup(self, model):
        """Deploy-time warm-up: compile the step + every observed prompt
        bucket for `model` BEFORE the registry pointer swaps."""
        with self._lock:
            buckets = set(self._observed_buckets)
        self.engine_for(model).warmup(buckets)

    # ------------------------------------------------------------ the loop
    def _run(self):
        while True:
            with self._work:
                while not self._queue and not self._active \
                        and not self._closed:
                    self._work.wait(self.idle_wait_s)
                if self._closed and not self._queue and not self._active:
                    return
            try:
                self._admit()
                self._step_wave()
            except Exception as e:          # last resort: the loop survives
                self._fail_all(e)

    def _fail_all(self, exc):
        self.m_errors.add(len(self._active))
        for slot, r in list(self._active.items()):
            r.fail(exc)
            self._free.append(slot)
        self._active.clear()
        self._cache = None                  # poisoned (possibly donated away)
        self._pool = None                   # allocator dies with its cache
        self._table = None
        self._slot_blocks = {}
        if self.logger is not None:
            self.logger.error("decode_wave_failed",
                              error=f"{type(exc).__name__}: {exc}")

    def _pop_queued(self):
        with self._lock:
            if self._queue:
                return self._queue.popleft()
            return None

    def _admit(self):
        if not self._free:
            return
        # pin ONE (version, model) per cache generation; on a hot-swap,
        # drain in-flight work before re-pinning (a step never mixes
        # versions)
        try:
            entry = self.registry.active_entry()
        except NoModelDeployed as e:
            while True:
                r = self._pop_queued()
                if r is None:
                    return
                r.fail(e)
            return
        if self._engine is None or self._version != entry.version \
                or self._engine.model is not entry.model:
            if self._active:
                return                      # drain first, swap next wave
            try:
                self._engine = self.engine_for(entry.model)
            except Exception as e:
                # a model with no decode semantics (DecodeUnsupported) — or
                # any engine-build failure — is deterministic for this
                # version: fail EVERYTHING queued and stop, instead of
                # leaving the queue full and the loop spinning on it
                if self.logger is not None:
                    self.logger.error(
                        "decode_engine_unavailable", version=entry.version,
                        error=f"{type(e).__name__}: {e}")
                while True:
                    r = self._pop_queued()
                    if r is None:
                        return
                    self.m_errors.add(1)
                    r.fail(e)
            self._version = entry.version
            self._cache = self._engine.init_cache()
            self._reset_pool()
        if self._cache is None:
            self._cache = self._engine.init_cache()
            self._reset_pool()
        while self._free:
            r = self._pop_queued()
            if r is None:
                return
            now = monotonic_s()
            if r.expired(now):
                # a preempted request that expires while re-queued holds
                # real tokens: it retires like a mid-generation deadline
                # (partial result), NOT as a 504 — same retire path either
                # way, so the accounting cannot diverge
                if r.tokens:
                    self._finish(r, "deadline")
                else:
                    self.m_expired.add(1)
                    r.fail(DeadlineExceeded(
                        "deadline exceeded while awaiting a decode slot"))
                continue
            # ctx is the FULL generated-so-far prefix: for a fresh request
            # just the prompt; for a preempted one prompt+partial, whose
            # re-prefill emits the next token at the sampling step index
            # the lost slot would have used (seeded streams are preemption-
            # invariant)
            ctx = r.prompt + r.tokens
            if self.paged:
                need = blocks_for(len(ctx), self.block_size)
                if need > self._pool.capacity_blocks:
                    if r.tokens:
                        # a preempted request outgrew the whole pool: what
                        # it generated is the answer, same as hitting the
                        # slab capacity wall mid-flight
                        self._finish(r, "capacity")
                    else:
                        self.m_errors.add(1)
                        r.fail(ValueError(
                            f"context of {len(ctx)} tokens can never fit "
                            f"the KV block pool "
                            f"({self._pool.capacity_blocks} blocks of "
                            f"{self.block_size})"))
                    continue
                if need > self._pool.free_blocks:
                    with self._lock:
                        self._queue.appendleft(r)
                    return          # wait for retirements to free blocks
            slot = self._free.pop()
            r.slot, r.version = slot, self._version
            r.admit_seq = self._admit_seq
            self._admit_seq += 1
            if self.paged:
                blks = self._pool.alloc(need)
                self._slot_blocks[slot] = blks
                self._table[slot, :] = 0
                self._table[slot, :len(blks)] = blks
            bucket = self._engine.prefill_bucket(len(ctx))
            with self._lock:
                self._observed_buckets.add(bucket)
            with self.tracer.span("decode_prefill", parent=r.trace_ctx,
                                  slot=slot, bucket=bucket,
                                  n_prompt=len(ctx)):
                try:
                    self._cache, nid, _ = self._engine.prefill(
                        self._cache, slot, ctx, sampling=r.sampler,
                        step_index=len(r.tokens),
                        table=self._table if self.paged else None)
                except Exception as e:
                    self.m_errors.add(1)
                    r.fail(e)
                    self._release_slot(slot)
                    if self.logger is not None:
                        self.logger.error(
                            "decode_prefill_failed", slot=slot,
                            error=f"{type(e).__name__}: {e}")
                    # the prefill DONATES the whole cache: after a failure
                    # mid-execution the co-batched slots' buffers may be
                    # gone too, so fail them loudly rather than stepping a
                    # poisoned cache next wave; a fresh cache re-inits on
                    # the next admission
                    if self._active:
                        self._fail_all(RuntimeError(
                            "co-batched KV cache lost to a failed prefill: "
                            f"{type(e).__name__}: {e}"))
                    else:
                        self._cache = None
                        self._pool = None
                        self._table = None
                        self._slot_blocks = {}
                    return
            now = monotonic_s()
            if r.ttft_ms is None:       # first admission only — a re-
                r.ttft_ms = (now - r.enqueued_at) * 1000.0   # admission is
                self.m_ttft.observe(r.ttft_ms,       # not a second "first
                                    trace_id=getattr(r.trace_ctx,  # token"
                                                     "trace_id", None))
            r.tokens.append(int(nid))
            self.m_tokens.add(1)
            self._active[slot] = r
            self._maybe_retire(slot, now)

    # --------------------------------------------------------- paged alloc
    def _reset_pool(self):
        """(Re)build the allocator beside a fresh cache — pool state and
        cache contents live and die together (a table pointing into a
        previous generation's pool would read garbage)."""
        if not self.paged or self._engine is None:
            self._pool = None
            self._table = None
            self._slot_blocks = {}
            return
        eng = self._engine
        self._pool = BlockPool(eng.num_blocks, eng.block_size)
        self._table = make_table(self.slots, eng.max_blocks)
        self._slot_blocks = {}

    def _grow(self, slot):
        """Back `slot`'s next append position with a physical block,
        preempting the YOUNGEST active slot whenever the pool is dry (the
        oversubscription watermark). Returns False when `slot` itself was
        the youngest and lost its own blocks."""
        r = self._active[slot]
        # cache holds prompt + tokens[:-1]; the step appends tokens[-1]
        need = blocks_for(len(r.prompt) + len(r.tokens), self.block_size)
        row = self._slot_blocks[slot]
        while len(row) < need:
            try:
                blk = self._pool.alloc(1)[0]
            except PoolExhausted:
                victim = max(self._active,
                             key=lambda s: self._active[s].admit_seq)
                self._preempt(victim)
                if victim == slot:
                    return False
                continue
            row.append(blk)
            self._table[slot, len(row) - 1] = blk
        return True

    def _preempt(self, slot):
        """Reclaim a slot's blocks mid-flight: the request keeps its tokens
        and re-queues at the FRONT (it was admitted before anything queued
        behind it); re-admission re-prefills prompt+partial."""
        r = self._active.pop(slot)
        self._release_slot(slot)
        self.m_preempted.add(1)
        with self._lock:
            self._queue.appendleft(r)
        if self.logger is not None:
            self.logger.info("decode_preempted", slot=slot,
                             n_tokens=len(r.tokens),
                             pool_free=self._pool.free_blocks)

    # ------------------------------------------------------------ stepping
    def _step_wave(self):
        if not self._active:
            return
        import numpy as np
        if self.paged:
            # oldest-first: seniority keeps its blocks, the youngest pays
            for slot in sorted(self._active,
                               key=lambda s: self._active[s].admit_seq):
                if slot in self._active:    # not preempted as a victim
                    self._grow(slot)
            if not self._active:
                return
        ids = np.zeros((self.slots,), np.int32)
        any_sampled = False
        for slot, r in self._active.items():
            ids[slot] = r.tokens[-1]
            any_sampled = any_sampled or r.sampler is not None
        samp = None
        if any_sampled:
            # per-slot sampling params + fold_in step indexes as ARRAY
            # operands — swinging every request never recompiles (GL016)
            samp = batch_operands(
                self.slots,
                {s: r.sampler for s, r in self._active.items()},
                {s: len(r.tokens) for s, r in self._active.items()})
        t0 = monotonic_s()
        self._cache, nxt, _ = self._engine.step(
            self._cache, ids, sampling=samp,
            table=self._table if self.paged else None)
        wall = monotonic_s() - t0
        n_active = len(self._active)
        self.m_tps.set(n_active / max(wall, 1e-9))
        now = monotonic_s()
        for slot, r in list(self._active.items()):
            r.tokens.append(int(nxt[slot]))
            self.m_tokens.add(1)
            self.m_itl.observe(wall * 1000.0,
                               trace_id=getattr(r.trace_ctx, "trace_id",
                                                None))
            self._maybe_retire(slot, now)

    # ----------------------------------------------------------- retiring
    def _release_slot(self, slot):
        """The ONE place a slot id (and, paged, its pool blocks + table
        row) returns to the free state — retire, preempt, and prefill-
        failure all route through here, so no exit path can leak a slot or
        strand blocks. When the last active slot leaves, the free list is
        re-sorted so future allocations pack low block ids (defrag)."""
        self._free.append(slot)
        if self._pool is not None:
            blks = self._slot_blocks.pop(slot, None)
            if blks:
                self._pool.free(blks)
            self._table[slot, :] = 0
            if not self._active:
                self._pool.defrag()

    def _finish(self, r, reason):
        r.finish_reason = reason
        self.m_requests.add(1)
        r.complete()

    def _retire(self, slot, r, reason):
        self._active.pop(slot, None)
        self._release_slot(slot)
        self._finish(r, reason)
        if self.logger is not None:
            self.logger.debug("generate_done", slot=slot, reason=reason,
                              n_tokens=len(r.tokens), version=r.version)

    def _maybe_retire(self, slot, now):
        r = self._active.get(slot)
        if r is None:
            return
        reason = None
        if r.stop_id is not None and r.tokens and r.tokens[-1] == r.stop_id:
            reason = "stop"
        elif len(r.tokens) >= r.max_new_tokens:
            reason = "length"
        elif len(r.prompt) + len(r.tokens) >= self.max_len:
            reason = "capacity"
        elif r.expired(now):
            # the per-token deadline budget: the client gets what was
            # generated before the budget ran out, marked as such
            reason = "deadline"
        if reason is None:
            return
        self._retire(slot, r, reason)
