"""Sampled decoding: temperature / top-k / top-p with per-request seeds,
as BATCH-SHAPED OPERANDS of the one decode step executable.

The recompile trap this module exists to avoid: the obvious way to add
sampling to a compiled decode step is to close over (or pass as jit static
args) the request's temperature / top_k / top_p / seed — and then every
creative-workload request with a new temperature mints a new executable,
exactly the per-shape explosion GL011 banned for shapes. Here every
sampling parameter is an ARRAY operand of the step:

  temperature f32[slots]   <= 0 means greedy (argmax) for that slot
  top_k       i32[slots]   <= 0 means off (full vocab)
  top_p       f32[slots]   >= 1 means off; always keeps the top-1 token
  seed        u32[slots]   per-request RNG seed
  step        i32[slots]   index of the token being sampled (0 = the
                           prefill's first token), the fold_in counter

so one executable serves every mix of greedy and sampled slots, and the
graftlint GL016 rule (`sampling-recompile-key`) flags any hot-path code
that demotes these back to static args or dict-key components.

Determinism: slot s draws token t from
``jax.random.categorical(fold_in(PRNGKey(seed[s]), step[s]), ...)`` — a
pure function of (seed, token index). The sequence therefore reproduces
across runs, across hot-swaps of the same weights, and across a paged-pool
preemption that re-prefills prompt+partial (the re-prefill passes the
SAME step index the lost step would have used).

Top-k / top-p run INSIDE the trace via sort+cumsum (no dynamic shapes):
top-k keeps probs >= the k-th largest (ties may keep a few extra — the
standard tie-handling caveat), top-p keeps the smallest prefix of the
descending-sorted probs whose *exclusive* cumulative sum is < p (so the
top-1 token always survives, even at p=0). Masked tokens are excluded at
the LOGIT level (finite NEG_INF after the temperature divide), not by
renormalizing probabilities, so high temperatures cannot leak mass back
into masked tokens.

`filter_probs_np` is the numpy mirror of the same filter (parity-tested)
for host-side consumers — the speculative engine's accept/rollback math
needs the filtered target/draft distributions without another executable.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

NEG_INF = -1e30

_FIELDS = ("temperature", "top_k", "top_p", "seed", "step")


class SamplerConfig:
    """One request's sampling parameters (host-side, JSON round-trip).

    The default config IS greedy decoding: temperature 0 short-circuits to
    argmax inside the trace, so greedy and sampled requests co-batch in the
    same step executable.
    """

    __slots__ = ("temperature", "top_k", "top_p", "seed")

    def __init__(self, temperature=0.0, top_k=0, top_p=1.0, seed=0):
        self.temperature = float(temperature)
        self.top_k = int(top_k)
        self.top_p = float(top_p)
        self.seed = int(seed) & 0xFFFFFFFF
        if not np.isfinite(self.temperature):
            raise ValueError("temperature must be finite")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0 (0 = off)")
        if not (0.0 <= self.top_p):
            raise ValueError("top_p must be >= 0")

    @property
    def is_greedy(self):
        return self.temperature <= 0.0

    @classmethod
    def from_request(cls, d):
        """Build from a /generate JSON body; None when the body carries no
        sampling field (the greedy fast path skips operand building)."""
        if not any(k in d for k in ("temperature", "top_k", "top_p", "seed")):
            return None
        return cls(temperature=d.get("temperature", 0.0),
                   top_k=d.get("top_k", 0),
                   top_p=d.get("top_p", 1.0),
                   seed=d.get("seed", 0))

    def to_dict(self):
        return {"temperature": self.temperature, "top_k": self.top_k,
                "top_p": self.top_p, "seed": self.seed}

    def __repr__(self):
        return (f"SamplerConfig(temperature={self.temperature}, "
                f"top_k={self.top_k}, top_p={self.top_p}, seed={self.seed})")


GREEDY = SamplerConfig()


def batch_operands(slots, configs=None, steps=None):
    """The step executable's sampling operand dict: numpy [slots] arrays.

    configs: {slot: SamplerConfig} (missing slots decode greedily);
    steps: {slot: token index} for the fold_in counter. Plain arrays in,
    plain arrays out — nothing here is ever a hashable jit key.
    """
    ops = {"temperature": np.zeros((slots,), np.float32),
           "top_k": np.zeros((slots,), np.int32),
           "top_p": np.ones((slots,), np.float32),
           "seed": np.zeros((slots,), np.uint32),
           "step": np.zeros((slots,), np.int32)}
    for slot, cfg in (configs or {}).items():
        if cfg is None:
            continue
        ops["temperature"][slot] = cfg.temperature
        ops["top_k"][slot] = cfg.top_k
        ops["top_p"][slot] = cfg.top_p
        ops["seed"][slot] = cfg.seed
    for slot, t in (steps or {}).items():
        ops["step"][slot] = int(t)
    return ops


def slot_operands(config, step):
    """[1]-shaped operand dict for the prefill leg (one slot at a time).
    `step` is the index of the token this prefill emits — 0 on a fresh
    admission, len(partial tokens) on a post-preemption re-prefill, so the
    seeded stream continues exactly where the preempted request left off."""
    cfg = config if config is not None else GREEDY
    return batch_operands(1, {0: cfg}, {0: step})


def keep_mask(probs, top_k, top_p):
    """Traced [S, V] bool mask of tokens that survive top-k AND top-p.

    top-k: token survives when its prob >= the k-th largest of its row
    (k <= 0 or k >= V disables). top-p: survives when its prob >= the
    smallest prob kept by the nucleus — the descending-sorted prefix whose
    EXCLUSIVE cumsum is < p, top-1 always kept (p >= 1 disables). Both are
    fixed-shape sort/cumsum/threshold chains: no dynamic slicing, so the
    mask composes into the one decode executable."""
    V = probs.shape[-1]
    sorted_p = jnp.sort(probs, axis=-1)[:, ::-1]              # descending
    # ---- top-k: threshold at the k-th largest probability
    k = jnp.clip(top_k, 1, V)
    kth = jnp.take_along_axis(sorted_p, (k - 1)[:, None], axis=-1)   # [S,1]
    k_on = ((top_k > 0) & (top_k < V))[:, None]
    keep_k = jnp.where(k_on, probs >= kth, True)
    # ---- top-p: exclusive cumsum over the sorted row; map the boundary
    # back to prob space as "the minimum kept probability"
    csum = jnp.cumsum(sorted_p, axis=-1)
    excl = csum - sorted_p
    pos0 = jnp.arange(V, dtype=jnp.int32)[None, :] == 0
    keep_sorted = (excl < top_p[:, None]) | pos0              # top-1 stays
    min_kept = jnp.min(jnp.where(keep_sorted, sorted_p, jnp.inf),
                       axis=-1, keepdims=True)
    keep_p = jnp.where((top_p < 1.0)[:, None], probs >= min_kept, True)
    return keep_k & keep_p


def sample_tokens(probs, operands):
    """Traced per-slot token choice: [S, V] f32 probs + the operand dict
    from `batch_operands` -> [S] int32 ids.

    Greedy slots (temperature <= 0) take the argmax; sampled slots draw
    from categorical(logits/T) with the top-k/top-p mask applied at the
    LOGIT level (NEG_INF) and a per-slot key
    fold_in(PRNGKey(seed), step)."""
    temperature = operands["temperature"]
    greedy_ids = jnp.argmax(probs, axis=-1).astype(jnp.int32)
    keep = keep_mask(probs, operands["top_k"], operands["top_p"])
    t = jnp.maximum(temperature, 1e-6)[:, None]
    logits = jnp.log(jnp.clip(probs, 1e-30, None)) / t
    logits = jnp.where(keep, logits, NEG_INF)

    def draw(seed, step, row):
        key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
        return jax.random.categorical(key, row)

    sampled = jax.vmap(draw)(operands["seed"].astype(jnp.uint32),
                             operands["step"], logits).astype(jnp.int32)
    return jnp.where(temperature > 0, sampled, greedy_ids)


def filter_probs_np(probs, config):
    """Host-side numpy mirror of the traced filter+temperature chain:
    returns the NORMALIZED distribution a sampled slot draws from (greedy
    configs return a one-hot argmax row). The speculative engine's
    accept/rollback math runs on these without minting an executable;
    parity with `keep_mask`/`sample_tokens` is pinned in tests."""
    p = np.asarray(probs, np.float64).reshape(-1)
    V = p.shape[0]
    if config is None or config.is_greedy:
        out = np.zeros_like(p)
        out[int(np.argmax(p))] = 1.0
        return out
    order = np.argsort(-p, kind="stable")
    sorted_p = p[order]
    keep = np.ones((V,), bool)
    if 0 < config.top_k < V:
        keep &= p >= sorted_p[config.top_k - 1]
    if config.top_p < 1.0:
        excl = np.cumsum(sorted_p) - sorted_p
        keep_sorted = excl < config.top_p
        keep_sorted[0] = True
        keep &= p >= sorted_p[keep_sorted].min()
    logits = np.log(np.clip(p, 1e-30, None)) / max(config.temperature, 1e-6)
    logits[~keep] = -np.inf
    logits -= logits.max()
    e = np.exp(logits)
    return e / e.sum()
