"""Training UI server.

Reference: deeplearning4j-play play/PlayUIServer.java:120-152 (embedded Play
HTTP server, UIModule SPI with routes + StatsStorage subscription, i18n,
Scala templates) and modules module/{train/TrainModule.java,
remote/RemoteReceiverModule.java, defaultModule/DefaultModule.java}.

Redesign: the embedded Play framework becomes a stdlib http.server in a
daemon thread serving the same shape of endpoints — JSON APIs per UIModule +
one self-contained HTML page that polls /train/overview and draws the score
chart on a <canvas> (no external assets; zero-egress friendly).
"""
from __future__ import annotations

import json

from ..util.http import BackgroundHttpServer, QuietHandler, dumps_http
from .storage import InMemoryStatsStorage

# report types that are not per-iteration training stats (activation grids,
# serving-subsystem metrics, telemetry registry flushes) — excluded from
# score/param time-series views
_NON_TRAINING_TYPES = ("activations", "serving", "telemetry")


def _dumps(obj) -> bytes:
    """Strict-JSON response body (GL002): a NaN score or an np.float32 in a
    stats report must serve as valid JSON (non-finite -> null, numpy values
    via tolist), never as a bare NaN that strict decoders reject."""
    return dumps_http(obj).encode()


def _latest_training(updates):
    """Newest update that is a real training report, or None."""
    return next((u for u in reversed(updates)
                 if u.get("type") not in _NON_TRAINING_TYPES), None)


class UIModule:
    """SPI (reference: api/UIModule.java — getRoutes + storage subscription)."""

    def routes(self):
        """{(method, path): handler(query, body) -> (status, content_type, bytes)}"""
        return {}

    def on_attach(self, storage):
        pass


class DefaultModule(UIModule):
    """Landing page (reference: module/defaultModule/DefaultModule.java)."""

    def routes(self):
        return {("GET", "/"): lambda q, b: (200, "text/html", _INDEX_HTML)}


class TrainModule(UIModule):
    """Training dashboard endpoints (reference: module/train/TrainModule.java
    — overview/model/system endpoints backed by the subscribed storage)."""

    def __init__(self):
        self.storage = None

    def on_attach(self, storage):
        self.storage = storage

    def routes(self):
        return {
            ("GET", "/train/sessions"): self._sessions,
            ("GET", "/train/overview"): self._overview,
            ("GET", "/train/model"): self._model,
        }

    def _json(self, obj):
        return 200, "application/json", _dumps(obj)

    def _sessions(self, query, body):
        return self._json(self.storage.list_session_ids())

    def _pick_session(self, query):
        sid = query.get("sid")
        ids = self.storage.list_session_ids()
        if sid is None and ids:
            sid = ids[-1]
        return sid

    def _overview(self, query, body):
        sid = self._pick_session(query)
        all_updates = self.storage.get_all_updates(sid) if sid else []
        # a session may carry serving-type reports (serving.ServingMetrics
        # routes through the same storage tier); the training overview plots
        # only iteration-scored updates
        updates = [u for u in all_updates if "score" in u]
        return self._json({
            "session": sid,
            "iterations": [u.get("iteration") for u in updates],
            "scores": [u["score"] for u in updates],
            "durations_ms": [u.get("duration_ms") for u in updates],
            "memory": updates[-1].get("memory", {}) if updates else {},
        })

    def _model(self, query, body):
        sid = self._pick_session(query)
        static = self.storage.get_static_info(sid) if sid else None
        # fast path: the indexed latest-update read almost always IS a
        # training update; when a serving/activations report is newest, scan
        # a bounded tail rather than the whole session history
        latest = self.storage.get_latest_update(sid) if sid else None
        if latest is not None and \
                latest.get("type") in _NON_TRAINING_TYPES:
            tail_n = 256
            tail = getattr(self.storage, "get_updates_tail", None)
            updates = (tail(sid, tail_n) if tail is not None
                       else self.storage.get_all_updates(sid))
            latest = _latest_training(updates)
            if latest is None and tail is not None and len(updates) == tail_n:
                # >256 consecutive non-training reports: fall back to the
                # full history rather than blanking real training stats
                latest = _latest_training(self.storage.get_all_updates(sid))
        return self._json({
            "session": sid,
            "static": static,
            "param_stats": (latest or {}).get("param_stats", {}),
            "gradient_stats": (latest or {}).get("gradient_stats", {}),
        })


class HistogramModule(UIModule):
    """Weight/gradient histograms + mean-magnitude time series (reference:
    module/histogram/HistogramModule.java — the /weights page data API)."""

    def __init__(self):
        self.storage = None

    def on_attach(self, storage):
        self.storage = storage

    def routes(self):
        return {("GET", "/weights/data"): self._data}

    def _data(self, query, body):
        sid = query.get("sid")
        ids = self.storage.list_session_ids()
        if sid is None and ids:
            sid = ids[-1]
        updates = [u for u in (self.storage.get_all_updates(sid) if sid else [])
                   if u.get("type") not in _NON_TRAINING_TYPES]
        latest = updates[-1] if updates else {}
        series = {}
        for u in updates:
            for name, st in (u.get("param_stats") or {}).items():
                series.setdefault(name, []).append(st.get("mean_magnitude"))
        payload = {
            "session": sid,
            "iteration": latest.get("iteration"),
            "param_histograms": {n: {"bins": st.get("histogram"),
                                     "range": st.get("histogram_edges")}
                                 for n, st in (latest.get("param_stats") or {}).items()},
            "gradient_histograms": {n: {"bins": st.get("histogram"),
                                        "range": st.get("histogram_edges")}
                                    for n, st in (latest.get("gradient_stats") or {}).items()},
            "mean_magnitudes": series,
            "scores": [u.get("score") for u in updates],
        }
        return 200, "application/json", _dumps(payload)


class FlowModule(UIModule):
    """Network-structure (flow) view data (reference:
    module/flow/FlowListenerModule.java + FlowIterationListener — nodes/edges
    of the layer graph plus per-layer perf from the latest update)."""

    def __init__(self):
        self.storage = None

    def on_attach(self, storage):
        self.storage = storage

    def routes(self):
        return {("GET", "/flow/info"): self._info}

    def _info(self, query, body):
        sid = query.get("sid")
        ids = self.storage.list_session_ids()
        if sid is None and ids:
            sid = ids[-1]
        static = self.storage.get_static_info(sid) if sid else None
        stats = [u for u in (self.storage.get_all_updates(sid) if sid else [])
                 if u.get("type") not in _NON_TRAINING_TYPES]
        latest = stats[-1] if stats else None
        return 200, "application/json", _dumps({
            "session": sid,
            "graph": (static or {}).get("graph", {"nodes": [], "edges": []}),
            "score": (latest or {}).get("score"),
            "iteration": (latest or {}).get("iteration"),
        })


class ConvolutionalModule(UIModule):
    """Convolutional activation render data (reference:
    module/convolutional/ConvolutionalListenerModule.java +
    ConvolutionalIterationListener — the listener posts normalized uint8
    activation grids; this serves the latest one per layer)."""

    def __init__(self):
        self.storage = None

    def on_attach(self, storage):
        self.storage = storage

    def routes(self):
        return {("GET", "/activations/data"): self._data}

    def _data(self, query, body):
        sid = query.get("sid")
        ids = self.storage.list_session_ids()
        if sid is None and ids:
            sid = ids[-1]
        updates = self.storage.get_all_updates(sid) if sid else []
        for u in reversed(updates):
            if u.get("type") == "activations":
                return 200, "application/json", _dumps(u)
        return 200, "application/json", _dumps(
            {"session": sid, "layers": {}})


class TsneModule(UIModule):
    """t-SNE coordinate serving (reference: module/tsne/TsneModule.java —
    upload/serve word coordinate files). POST /tsne/upload a JSON
    {"words": [...], "coords": [[x,y],...]}; GET /tsne/coords returns it."""

    def __init__(self):
        self._payload = {"words": [], "coords": []}

    def routes(self):
        return {("POST", "/tsne/upload"): self._upload,
                ("GET", "/tsne/coords"): self._coords}

    def _upload(self, query, body):
        d = json.loads(body)
        if "words" not in d or "coords" not in d:
            return 400, "application/json", b'{"error":"need words+coords"}'
        self._payload = {"words": list(d["words"]),
                         "coords": [list(map(float, c)) for c in d["coords"]]}
        return 200, "application/json", b'{"status":"ok"}'

    def _coords(self, query, body):
        return 200, "application/json", _dumps(self._payload)


class MetricsModule(UIModule):
    """Scrape endpoint for the central telemetry registry: `GET /metrics`
    returns the registry snapshot as JSON (default, back-compat with the
    serving endpoint's shape) or Prometheus text exposition with
    `?format=prometheus` — so the training UI process is scrapeable exactly
    like a ServingServer."""

    def __init__(self, registry=None):
        if registry is None:
            from ..telemetry.registry import get_registry
            registry = get_registry()
        self.registry = registry

    def routes(self):
        return {("GET", "/metrics"): self._metrics}

    def _metrics(self, query, body):
        if query.get("format") == "prometheus":
            from ..telemetry.prometheus import CONTENT_TYPE
            return 200, CONTENT_TYPE, self.registry.to_prometheus().encode()
        return (200, "application/json",
                _dumps(self.registry.snapshot()))


class ProfileModule(UIModule):
    """`GET /profile/cost` (the sortable per-executable FLOPs/bytes/roofline
    table) and `GET /profile/trace?steps=N` (bounded on-demand span capture)
    for the training/UI process — the training-side mirror of the
    ServingServer's /profile plane. The cost registry resolves at request
    time, so a trainer that calls telemetry.set_cost_registry() after the
    UI started is picked up; with none installed the table is empty, never
    an error."""

    def __init__(self, cost=None, tracer=None):
        self.cost = cost
        self.tracer = tracer            # None -> the process-default tracer

    def routes(self):
        return {("GET", "/profile/cost"): self._cost,
                ("GET", "/profile/trace"): self._trace}

    def _cost(self, query, body):
        from ..telemetry.cost import get_cost_registry
        from ..util.http import dumps_safe
        cr = self.cost if self.cost is not None else get_cost_registry()
        payload = {"ceilings": None, "executables": []} if cr is None \
            else cr.to_dict(sort=query.get("sort", "hbm_bytes_per_sample"),
                            family=query.get("family"))
        return (200, "application/json",
                dumps_safe(payload, default=str).encode())

    def _trace(self, query, body):
        from ..telemetry.cost import capture_trace
        from ..util.http import dumps_safe
        try:
            steps = int(query.get("steps", ""))
            timeout_s = min(float(query.get("timeout_s", 2.0)), 10.0)
            payload = capture_trace(steps, tracer=self.tracer,
                                    timeout_s=timeout_s)
        except (TypeError, ValueError) as e:
            return (400, "application/json",
                    dumps_safe({"error": f"bad query: {e}"}).encode())
        return 200, "application/json", dumps_safe(payload).encode()


class HealthModule(UIModule):
    """Deep `GET /healthz` for the training/UI process: aggregates the
    HealthMonitor's component probes (ETL pipelines, the trainer via
    TrainingHealthListener, anything else registered) and answers 503 when
    any component is unhealthy — the training-side mirror of the
    ServingServer's deep health endpoint."""

    def __init__(self, monitor=None):
        if monitor is None:
            from ..telemetry.health import get_monitor
            monitor = get_monitor()
        self.monitor = monitor

    def routes(self):
        return {("GET", "/healthz"): self._healthz}

    def _healthz(self, query, body):
        from ..util.http import dumps_safe
        report = self.monitor.check()
        status = self.monitor.http_status(report)
        # dumps_safe + default=str: a trainer probe may carry a NaN
        # last_loss, and custom probe detail may hold arbitrary objects
        return (status, "application/json",
                dumps_safe(report, default=str).encode())


class AlertsModule(UIModule):
    """`GET /alerts`: the rule lifecycle state of an AlertEngine (pass one
    watching the training registry; defaults to an empty, rule-less engine
    over the process registry so the endpoint always answers)."""

    def __init__(self, engine=None):
        if engine is None:
            from ..telemetry.alerts import AlertEngine
            engine = AlertEngine(interval_s=0)
        self.engine = engine

    def routes(self):
        return {("GET", "/alerts"): self._alerts}

    def _alerts(self, query, body):
        from ..util.http import dumps_safe
        return 200, "application/json", dumps_safe(
            self.engine.state(), default=str).encode()


class LogsModule(UIModule):
    """`GET /logs`: the structured logger's bounded ring buffer
    (?level=error&n=100&trace_id=N), trace/span-correlated records."""

    def __init__(self, logger=None):
        if logger is None:
            from ..telemetry.logging import get_logger
            logger = get_logger()
        self.logger = logger

    def routes(self):
        return {("GET", "/logs"): self._logs}

    def _logs(self, query, body):
        from ..util.http import dumps_safe
        try:
            payload = self.logger.buffer.to_dict(
                level=query.get("level"), n=int(query.get("n", 256)),
                trace_id=query.get("trace_id"))
        except ValueError as e:           # ?n=all / ?trace_id=abc -> 400
            return (400, "application/json",
                    dumps_safe({"error": f"bad query: {e}"}).encode())
        return (200, "application/json",
                dumps_safe(payload, default=str).encode())


class RemoteReceiverModule(UIModule):
    """Accepts POSTed reports from RemoteUIStatsStorageRouter (reference:
    module/remote/RemoteReceiverModule.java)."""

    def __init__(self):
        self.storage = None

    def on_attach(self, storage):
        self.storage = storage

    def routes(self):
        return {("POST", "/remoteReceive"): self._receive}

    def _receive(self, query, body):
        d = json.loads(body)
        if d.get("type") == "init":
            self.storage.put_static_info(d)
        else:
            self.storage.put_update(d)
        return 200, "application/json", b'{"status":"ok"}'


class UIServer(BackgroundHttpServer):
    """(reference: PlayUIServer — getInstance().attach(statsStorage))"""

    _instance = None

    def __init__(self, port=9000, modules=None, registry=None, health=None,
                 alerts=None, logger=None, cost=None):
        super().__init__(host="127.0.0.1", port=port)
        self.storage = None
        self.modules = modules or [DefaultModule(), TrainModule(),
                                   HistogramModule(), FlowModule(),
                                   ConvolutionalModule(), TsneModule(),
                                   MetricsModule(registry),
                                   ProfileModule(cost),
                                   HealthModule(health),
                                   AlertsModule(alerts),
                                   LogsModule(logger),
                                   RemoteReceiverModule()]
        self._routes = {}
        for m in self.modules:
            self._routes.update(m.routes())

    @classmethod
    def get_instance(cls, port=9000):
        if cls._instance is None:
            cls._instance = UIServer(port)
            cls._instance.start()
        return cls._instance

    def attach(self, stats_storage):
        self.storage = stats_storage
        for m in self.modules:
            m.on_attach(stats_storage)
        return self

    def start(self):
        if self.storage is None:
            self.attach(InMemoryStatsStorage())
        routes = self._routes

        class Handler(QuietHandler):
            def _dispatch(self, method):
                from urllib.parse import urlparse, parse_qs
                u = urlparse(self.path)
                query = {k: v[0] for k, v in parse_qs(u.query).items()}
                handler = routes.get((method, u.path))
                if handler is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length) if length else b""
                # W3C traceparent from util.http clients: serve inside a
                # server span with the remote parent, so the caller's trace
                # continues through this process's spans and /logs records
                # (the process-default tracer is a no-op unless enabled)
                from ..telemetry.propagation import server_span
                from ..telemetry.trace import get_tracer
                with server_span(get_tracer(), self.headers,
                                 f"http {u.path}"):
                    status, ctype, content = handler(query, body)
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(content)))
                self.end_headers()
                self.wfile.write(content)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

        return self.start_with(Handler)

    def stop(self):
        super().stop()
        if UIServer._instance is self:
            UIServer._instance = None


_INDEX_HTML = b"""<!doctype html>
<html><head><title>deeplearning4j-tpu training UI</title>
<style>body{font-family:sans-serif;margin:2em}canvas{border:1px solid #ccc}</style>
</head><body>
<h2>Training overview</h2>
<div id="meta"></div>
<canvas id="score" width="900" height="300"></canvas>
<script>
async function refresh(){
  const r = await fetch('/train/overview'); const d = await r.json();
  document.getElementById('meta').textContent =
    'session: ' + d.session + '  iterations: ' + d.iterations.length;
  const c = document.getElementById('score').getContext('2d');
  c.clearRect(0,0,900,300);
  const ys = d.scores; if (!ys.length) return;
  const ymax = Math.max(...ys), ymin = Math.min(...ys);
  c.beginPath(); c.strokeStyle = '#2060c0';
  ys.forEach((y,i)=>{
    const px = 20 + i*(860/Math.max(ys.length-1,1));
    const py = 280 - 260*(y-ymin)/Math.max(ymax-ymin,1e-9);
    i ? c.lineTo(px,py) : c.moveTo(px,py);
  });
  c.stroke();
}
refresh(); setInterval(refresh, 2000);
</script></body></html>"""
