"""Training statistics collection.

Reference: deeplearning4j-ui-model stats/BaseStatsListener.java:43,273,419-436
(samples score, param/gradient/update/activation histograms and mean
magnitudes, JVM+off-heap memory, GC counts, hardware info, encoded with SBE)
and stats/impl/SbeStatsReport.java.

Redesign: SBE wire codecs (22 generated files) are replaced by plain
JSON-serializable report dicts — compact enough for stats traffic and
human-debuggable; the storage layer (ui/storage.py) persists them.
"""
from __future__ import annotations

import gc
import json
import os

import numpy as np

from ..util.http import dumps_http
from ..util.time_source import monotonic_s, now_ms, now_s


class StatsInitReport:
    """Static session info (reference: SbeStatsInitializationReport —
    hardware/software/model info)."""

    def __init__(self, session_id, model):
        import jax
        self.data = {
            "type": "init",
            "session_id": session_id,
            "time": now_s(),
            "backend": jax.default_backend(),
            "devices": [str(d) for d in jax.devices()],
            "n_params": int(model.num_params()) if model.params is not None else 0,
            "model_class": type(model).__name__,
            "pid": os.getpid(),
            "graph": self._graph_info(model),
        }

    @staticmethod
    def _graph_info(model):
        """Layer/vertex topology for the flow (network-structure) UI module
        (reference: FlowIterationListener builds this from the model)."""
        try:
            conf = model.conf
            if hasattr(conf, "vertices"):  # ComputationGraph
                nodes, edges = [], []
                for name in model.order:
                    spec = conf.vertices[name]
                    kind = (type(spec.layer_conf).__name__ if spec.kind == "layer"
                            else type(spec.vertex_conf).__name__
                            if spec.kind == "vertex" else "Input")
                    nodes.append({"name": name, "type": kind})
                    for src in (spec.inputs or []):
                        edges.append([src, name])
                return {"nodes": nodes, "edges": edges}
            nodes = [{"name": str(i), "type": type(lc).__name__}
                     for i, lc in enumerate(conf.layers)]
            edges = [[str(i), str(i + 1)] for i in range(len(nodes) - 1)]
            return {"nodes": nodes, "edges": edges}
        except Exception:
            return {"nodes": [], "edges": []}

    def to_json(self):
        # reports are HTTP payloads (POSTed to /remoteReceive, served back by
        # UI endpoints): strict JSON only — NaN -> null, numpy via tolist
        return dumps_http(self.data)


class StatsReport:
    """Per-iteration report (reference: SbeStatsReport)."""

    def __init__(self, session_id, iteration, score, *, param_stats=None,
                 gradient_stats=None, update_stats=None, activation_stats=None,
                 memory=None, gc_counts=None, duration_ms=None):
        self.data = {
            "type": "stats",
            "session_id": session_id,
            "iteration": iteration,
            "time": now_s(),
            "score": score,
            "param_stats": param_stats or {},
            "gradient_stats": gradient_stats or {},
            "update_stats": update_stats or {},
            "activation_stats": activation_stats or {},
            "memory": memory or {},
            "gc_counts": gc_counts or [],
            "duration_ms": duration_ms,
        }

    def to_json(self):
        return dumps_http(self.data)

    @staticmethod
    def from_json(s):
        r = StatsReport.__new__(StatsReport)
        r.data = json.loads(s)
        return r


class ServingStatsReport:
    """Serving-side report (type "serving"): latency percentiles, queue depth,
    batch-size histogram, shed/expired counts from serving.ServingMetrics —
    routed through the same StatsStorageRouter tier as training reports so a
    UI server tails a live serving process like a training run."""

    def __init__(self, session_id, snapshot):
        self.data = {
            "type": "serving",
            "session_id": session_id,
            "time": now_s(),
            **snapshot,
        }

    def to_json(self):
        return dumps_http(self.data)


def _array_stats(arr, histogram_bins=20):
    a = np.asarray(arr).ravel()
    if a.size == 0:
        return {}
    hist, edges = np.histogram(a, bins=histogram_bins)
    return {
        "mean_magnitude": float(np.mean(np.abs(a))),
        "mean": float(a.mean()),
        "stdev": float(a.std()),
        "min": float(a.min()),
        "max": float(a.max()),
        "histogram": hist.tolist(),
        "histogram_edges": [float(edges[0]), float(edges[-1])],
    }


class StatsListener:
    """(reference: BaseStatsListener.java — IterationListener feeding a
    StatsStorageRouter). collect_* flags mirror StatsUpdateConfiguration."""

    def __init__(self, storage_router, frequency=1, session_id=None,
                 collect_params=True, collect_gradients=True,
                 collect_activations=False, collect_memory=True,
                 histogram_bins=20, registry=None):
        self.router = storage_router
        self.frequency = max(1, int(frequency))
        self.session_id = session_id or f"session_{now_ms()}"
        self.collect_params = collect_params
        self.collect_gradients = collect_gradients
        self.wants_gradients = collect_gradients  # models keep last_gradients alive
        self.collect_activations = collect_activations
        self.collect_memory = collect_memory
        self.histogram_bins = histogram_bins
        self._initialized = False
        self._last_time = None
        # central-registry mirror: the iteration timing/score this listener
        # measures also lands in the shared telemetry.MetricsRegistry, so a
        # Prometheus scrape of the UI server sees the same numbers as the
        # stats storage tier (pass registry=... to share a specific one)
        self.registry = registry
        if registry is not None:
            self._reg_iter_ms = registry.histogram(
                "training_iteration_ms", "Wall ms per training iteration")
            self._reg_score = registry.gauge(
                "training_score", "Latest training loss/score")

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def iteration_done(self, model, iteration):
        if not self._initialized:
            self.router.put_static_info(StatsInitReport(self.session_id, model))
            self._initialized = True
        if iteration % self.frequency != 0:
            return
        now = monotonic_s()
        duration = None if self._last_time is None else \
            (now - self._last_time) * 1000.0
        self._last_time = now
        if self.registry is not None:
            if duration is not None:
                # `duration` spans `frequency` iterations (time between two
                # OBSERVED iterations); mirror the per-iteration cost so the
                # shared histogram stays comparable with other recorders
                self._reg_iter_ms.observe(duration / self.frequency)
            try:
                self._reg_score.set(float(model.score_value))
            except (TypeError, ValueError):
                pass

        param_stats = {}
        if self.collect_params and model.params is not None:
            for name, p in model.param_table().items():
                param_stats[name] = _array_stats(p, self.histogram_bins)
        grad_stats = {}
        if self.collect_gradients:
            grads = getattr(model, "last_gradients", None)
            if grads is not None:
                import jax
                flat = jax.tree_util.tree_flatten_with_path(grads)[0]
                for path, g in flat:
                    grad_stats[jax.tree_util.keystr(path)] = \
                        _array_stats(g, self.histogram_bins)
        memory = {}
        if self.collect_memory:
            memory = self._memory_stats()
        report = StatsReport(
            self.session_id, iteration, float(model.score_value),
            param_stats=param_stats, gradient_stats=grad_stats,
            memory=memory, gc_counts=list(gc.get_count()),
            duration_ms=duration)
        self.router.put_update(report)

    @staticmethod
    def _memory_stats():
        out = {}
        try:
            import resource
            out["max_rss_kb"] = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
        except Exception:
            pass
        try:
            import jax
            for d in jax.local_devices():
                ms = d.memory_stats()
                if ms:
                    out[f"device_{d.id}_bytes_in_use"] = ms.get("bytes_in_use")
                    break
        except Exception:
            pass
        return out


class ProfilerListener:
    """XLA/TPU profiler hook (the TPU analog of the reference's absent tracer —
    SURVEY.md §5 'no tracer'; jax.profiler traces go to TensorBoard format).

    The trace window is [start_iteration, start_iteration + n_iterations);
    if training ends (or the epoch ends) before the window closes, the
    active trace is stopped rather than leaked — a leaked jax.profiler trace
    keeps buffering device events for the life of the process and makes the
    next start_trace raise. `close()` is idempotent and safe to call from a
    finally block."""

    def __init__(self, log_dir, start_iteration=10, n_iterations=5):
        self.log_dir = str(log_dir)
        self.start_iteration = start_iteration
        self.end_iteration = start_iteration + n_iterations
        self._active = False

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        # training may end (or be interrupted) before end_iteration is
        # reached; an epoch boundary is the last hook we reliably get
        self.close()

    def iteration_done(self, model, iteration):
        import jax
        if iteration == self.start_iteration and not self._active:
            jax.profiler.start_trace(self.log_dir)
            self._active = True
        elif iteration >= self.end_iteration and self._active:
            self._stop()

    def _stop(self):
        import jax
        self._active = False      # never retry a failing stop
        jax.profiler.stop_trace()

    def close(self):
        """Stop any still-active trace (idempotent)."""
        if self._active:
            self._stop()

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
