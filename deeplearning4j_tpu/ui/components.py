"""JSON-serializable UI component model.

Reference: deeplearning4j-ui-components components/{chart,component,table,
text,decorator}/ (2 163 LoC) — ChartLine, ChartScatter, ChartHistogram,
ComponentTable, ComponentText, ComponentDiv, styles; serialized to JSON for
arbitrary front-ends.
"""
from __future__ import annotations

import json


class Style:
    def __init__(self, width=None, height=None, background_color=None,
                 margin=None):
        self.data = {k: v for k, v in {
            "width": width, "height": height,
            "backgroundColor": background_color, "margin": margin,
        }.items() if v is not None}

    def to_dict(self):
        return dict(self.data)


class Component:
    TYPE = "Component"

    def __init__(self, style=None, title=None):
        self.style = style
        self.title = title

    def _base(self):
        d = {"componentType": self.TYPE}
        if self.title is not None:
            d["title"] = self.title
        if self.style is not None:
            d["style"] = self.style.to_dict()
        return d

    def to_dict(self):
        return self._base()

    def to_json(self):
        return json.dumps(self.to_dict())


class ComponentText(Component):
    TYPE = "ComponentText"

    def __init__(self, text, **kw):
        super().__init__(**kw)
        self.text = text

    def to_dict(self):
        d = self._base()
        d["text"] = self.text
        return d


class ComponentTable(Component):
    TYPE = "ComponentTable"

    def __init__(self, header=None, content=None, **kw):
        super().__init__(**kw)
        self.header = header or []
        self.content = content or []

    def to_dict(self):
        d = self._base()
        d["header"] = list(self.header)
        d["content"] = [list(r) for r in self.content]
        return d


class ComponentDiv(Component):
    TYPE = "ComponentDiv"

    def __init__(self, *children, **kw):
        super().__init__(**kw)
        self.children = list(children)

    def to_dict(self):
        d = self._base()
        d["components"] = [c.to_dict() for c in self.children]
        return d


class ChartLine(Component):
    TYPE = "ChartLine"

    def __init__(self, **kw):
        super().__init__(**kw)
        self.series = []  # (name, x, y)

    def add_series(self, name, x, y):
        self.series.append((name, [float(v) for v in x], [float(v) for v in y]))
        return self

    def to_dict(self):
        d = self._base()
        d["series"] = [{"name": n, "x": x, "y": y} for n, x, y in self.series]
        return d


class ChartScatter(ChartLine):
    TYPE = "ChartScatter"


class ChartHistogram(Component):
    TYPE = "ChartHistogram"

    def __init__(self, **kw):
        super().__init__(**kw)
        self.bins = []  # (lower, upper, y)

    def add_bin(self, lower, upper, y):
        self.bins.append((float(lower), float(upper), float(y)))
        return self

    def to_dict(self):
        d = self._base()
        d["bins"] = [{"lower": l, "upper": u, "y": y} for l, u, y in self.bins]
        return d


def component_from_dict(d):
    table = {c.TYPE: c for c in
             (ComponentText, ComponentTable, ComponentDiv, ChartLine,
              ChartScatter, ChartHistogram)}
    cls = table[d["componentType"]]
    obj = cls.__new__(cls)
    Component.__init__(obj, title=d.get("title"))
    if cls is ComponentText:
        obj.text = d["text"]
    elif cls is ComponentTable:
        obj.header = d.get("header", [])
        obj.content = d.get("content", [])
    elif cls is ComponentDiv:
        obj.children = [component_from_dict(c) for c in d.get("components", [])]
    elif cls in (ChartLine, ChartScatter):
        obj.series = [(s["name"], s["x"], s["y"]) for s in d.get("series", [])]
    elif cls is ChartHistogram:
        obj.bins = [(b["lower"], b["upper"], b["y"]) for b in d.get("bins", [])]
    return obj
