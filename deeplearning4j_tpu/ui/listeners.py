"""Legacy render listeners: convolutional activations + flow view.

Reference: deeplearning4j-ui/.../weights/ConvolutionalIterationListener.java
(renders per-channel activation tiles of conv layers every N iterations) and
flow/FlowIterationListener.java (pushes the network-structure view). The Play
rendering stack is replaced by JSON posts into the StatsStorage router; the
matching UI modules (ui/server.py ConvolutionalModule / FlowModule) serve the
latest payloads.
"""
from __future__ import annotations

import numpy as np

from ..util.time_source import now_ms, now_s


class ConvolutionalIterationListener:
    """Every `frequency` iterations, run the model forward on a reference
    batch and publish normalized uint8 activation grids for every 4-D (NHWC)
    activation (reference: ConvolutionalIterationListener.java)."""

    def __init__(self, storage_router, reference_input, frequency=10,
                 session_id=None, max_channels=16):
        self.router = storage_router
        self.x = np.asarray(reference_input)[:1]  # first example only
        self.frequency = max(1, int(frequency))
        self.session_id = session_id or f"conv_{now_ms()}"
        self.max_channels = int(max_channels)

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def record_batch_size(self, b):
        pass

    def iteration_done(self, model, iteration):
        if iteration % self.frequency != 0:
            return
        layers = {}
        acts = self._collect(model)
        for name, a in acts.items():
            a = np.asarray(a)
            if a.ndim != 4:
                continue
            grid = a[0]  # [h, w, c]
            c = min(grid.shape[-1], self.max_channels)
            chans = []
            for i in range(c):
                g = grid[..., i]
                lo, hi = float(g.min()), float(g.max())
                scale = 255.0 / (hi - lo) if hi > lo else 0.0
                chans.append(((g - lo) * scale).astype(np.uint8).tolist())
            layers[name] = {"height": int(grid.shape[0]),
                            "width": int(grid.shape[1]),
                            "channels": chans}
        self.router.put_update({
            "type": "activations",
            "session_id": self.session_id,
            "iteration": iteration,
            "time": now_s(),
            "layers": layers,
        })

    def _collect(self, model):
        """Activation map per layer/vertex name on the reference input."""
        from ..nn.multilayer.network import MultiLayerNetwork
        x = self.x.astype(np.float32)
        if isinstance(model, MultiLayerNetwork):
            _, _, _, _, collected = model._forward(
                model.params, model.states, x, train=False, rng=None,
                collect=True)
            return {str(i): a for i, a in enumerate(collected)}
        return dict(model.feed_forward(x))


class FlowIterationListener:
    """Publishes the network-structure (flow) snapshot through the stats
    router so the FlowModule can serve it (reference:
    flow/FlowIterationListener.java)."""

    def __init__(self, storage_router, frequency=10, session_id=None):
        from .stats import StatsListener
        self._inner = StatsListener(storage_router, frequency=frequency,
                                    session_id=session_id,
                                    collect_params=False,
                                    collect_gradients=False,
                                    collect_memory=False)
        self.wants_gradients = False

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass

    def record_batch_size(self, b):
        pass

    def iteration_done(self, model, iteration):
        self._inner.iteration_done(model, iteration)
