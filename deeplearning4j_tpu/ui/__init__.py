"""Observability/UI stack (reference: deeplearning4j-ui-parent — stats
collection, pluggable stats storage, embedded web UI with UIModule SPI,
JSON chart/table components). See SURVEY.md §2.8.
"""
from .stats import StatsListener, StatsReport, StatsInitReport, ProfilerListener
from .storage import (StatsStorageRouter, CollectionStatsStorageRouter,
                      InMemoryStatsStorage, FileStatsStorage,
                      SqliteStatsStorage, RemoteUIStatsStorageRouter)
from .server import (UIServer, UIModule, TrainModule, DefaultModule,
                     MetricsModule, RemoteReceiverModule)
from . import components

__all__ = [
    "StatsListener", "StatsReport", "StatsInitReport", "ProfilerListener",
    "StatsStorageRouter", "CollectionStatsStorageRouter",
    "InMemoryStatsStorage", "FileStatsStorage", "SqliteStatsStorage",
    "RemoteUIStatsStorageRouter",
    "UIServer", "UIModule", "TrainModule", "DefaultModule",
    "MetricsModule", "RemoteReceiverModule", "components",
]
