"""Stats storage + routing.

Reference: deeplearning4j-core api/storage/{StatsStorage.java,
StatsStorageRouter.java, Persistable.java} and impl/
{CollectionStatsStorageRouter, RemoteUIStatsStorageRouter.java (HTTP POST)};
deeplearning4j-ui-model storage/{InMemoryStatsStorage, FileStatsStorage,
mapdb/MapDBStatsStorage, sqlite/J7FileStatsStorage}.

The reports are JSON (ui/stats.py). Two durable tiers, mirroring the
reference: FileStatsStorage is a JSONL append log (FileStatsStorage.java
role), SqliteStatsStorage is the indexed store with a concurrent-reader
story (J7FileStatsStorage/MapDBStatsStorage role; stdlib sqlite3, WAL).
"""
from __future__ import annotations

import contextlib
import json
import os
import threading


class StatsStorageRouter:
    """Write-side API (reference: api/storage/StatsStorageRouter.java)."""

    def put_static_info(self, report):
        raise NotImplementedError

    def put_update(self, report):
        raise NotImplementedError


class CollectionStatsStorageRouter(StatsStorageRouter):
    """Collects into plain lists (reference:
    impl/CollectionStatsStorageRouter.java)."""

    def __init__(self):
        self.static_info = []
        self.updates = []

    def put_static_info(self, report):
        self.static_info.append(report)

    def put_update(self, report):
        self.updates.append(report)


def _as_dict(report):
    """Unwrap a StatsReport (or accept a plain mapping)."""
    return report.data if hasattr(report, "data") else dict(report)


class _ListenerHub:
    """Subscription side shared by the read+write storages (StatsStorage
    listener semantics the UI server attaches to)."""

    def register_listener(self, fn):
        self._listeners.append(fn)

    def _notify(self, d):
        for fn in self._listeners:
            fn(d)


class InMemoryStatsStorage(StatsStorageRouter, _ListenerHub):
    """Read+write storage (reference: InMemoryStatsStorage.java). Also the
    subscription hub the UI server attaches to (StatsStorage listeners)."""

    def __init__(self):
        self._static = {}     # session_id -> report dict
        self._updates = {}    # session_id -> [report dict]
        self._listeners = []
        self._lock = threading.Lock()

    # ---- router (write) ---------------------------------------------------
    def put_static_info(self, report):
        d = _as_dict(report)
        with self._lock:
            self._static[d["session_id"]] = d
        self._notify(d)

    def put_update(self, report):
        d = _as_dict(report)
        with self._lock:
            self._updates.setdefault(d["session_id"], []).append(d)
        self._notify(d)

    # ---- storage (read) ---------------------------------------------------
    def list_session_ids(self):
        with self._lock:
            ids = set(self._static) | set(self._updates)
        return sorted(ids)

    def get_static_info(self, session_id):
        with self._lock:
            return self._static.get(session_id)

    def get_all_updates(self, session_id):
        with self._lock:
            return list(self._updates.get(session_id, []))

    def get_latest_update(self, session_id):
        with self._lock:
            ups = self._updates.get(session_id)
            return ups[-1] if ups else None

    def get_updates_tail(self, session_id, n):
        """Last n updates in order (bounded read for latest-of-type scans)."""
        n = int(n)
        if n <= 0:                 # ups[-0:] would be the WHOLE history
            return []
        with self._lock:
            ups = self._updates.get(session_id, [])
            return list(ups[-n:])


class FileStatsStorage(InMemoryStatsStorage):
    """Durable JSONL-backed storage (reference: FileStatsStorage.java /
    MapDBStatsStorage role). Appends every report; reloads on open."""

    def __init__(self, path):
        super().__init__()
        self.path = str(path)
        if os.path.exists(self.path):
            with open(self.path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    d = json.loads(line)
                    if d.get("type") == "init":
                        super().put_static_info(d)
                    else:
                        super().put_update(d)
        self._fh = open(self.path, "a")
        # the router may be multi-writer (training listener thread + serving
        # metrics flushes); interleaved writes would corrupt the JSONL log
        self._fh_lock = threading.Lock()
        self.dropped_writes = 0    # reports that raced close(): not on disk

    def _append(self, d):
        with self._fh_lock:
            if self._fh.closed:
                # a report racing close() stays visible in memory but is
                # not durable; surface the divergence instead of raising
                # out of a metrics scrape or swallowing it silently
                self.dropped_writes += 1
                if self.dropped_writes == 1:
                    import warnings
                    warnings.warn(
                        f"FileStatsStorage({self.path}): report arrived "
                        "after close(); not written to disk")
                return
            self._fh.write(json.dumps(d) + "\n")
            self._fh.flush()

    def put_static_info(self, report):
        d = _as_dict(report)
        self._append(d)
        super().put_static_info(d)

    def put_update(self, report):
        d = _as_dict(report)
        self._append(d)
        super().put_update(d)

    def close(self):
        with self._fh_lock:     # don't close mid-write from another thread
            self._fh.close()


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """HTTP POST of reports to a remote UI server (reference:
    impl/RemoteUIStatsStorageRouter.java; receiver = the UI server's
    RemoteReceiverModule). Retries with backoff like the reference
    (maxRetryCount/retryBackoffBase)."""

    def __init__(self, url, max_retries=3, backoff_base_ms=100):
        self.url = url.rstrip("/") + "/remoteReceive"
        self.max_retries = max_retries
        self.backoff_base_ms = backoff_base_ms
        # one RetryPolicy instead of the hand-rolled loop (GL009): jittered
        # exponential backoff between attempts, retrying on ANY failure like
        # the reference's maxRetryCount semantics (stats delivery is
        # fire-and-forget; a 4xx here is still just "report not delivered")
        from ..resilience.policy import RetryPolicy
        self._retry = RetryPolicy(max_attempts=max_retries + 1,
                                  base_s=backoff_base_ms / 1000.0,
                                  cap_s=backoff_base_ms / 1000.0
                                  * (2 ** max(max_retries - 1, 0)),
                                  retry_on=lambda e: True)

    def _post(self, d):
        # util.http.post_json is the outbound choke point (GL008): strict
        # JSON body (NaN scores/numpy scalars survive, GL002) AND the
        # current trace context injected as a traceparent header
        from ..util.http import post_json
        try:
            self._retry.call(post_json, self.url, d, timeout=5)
            return True
        except Exception:
            return False

    def put_static_info(self, report):
        self._post(_as_dict(report))

    def put_update(self, report):
        self._post(_as_dict(report))


class SqliteStatsStorage(StatsStorageRouter, _ListenerHub):
    """Durable INDEXED stats storage on sqlite3 (reference:
    ui/storage/sqlite/J7FileStatsStorage.java and
    mapdb/MapDBStatsStorage.java — the reference's durable/indexed tier above
    the flat file). WAL journal mode gives the concurrent-reader story for
    long runs: writers go through one serialized connection, while any number
    of reader connections (other threads OR other processes, e.g. a UI server
    tailing a live training run) see consistent snapshots without blocking
    the trainer. Updates are indexed by (session_id, iteration) so range
    queries don't scan the run history."""

    def __init__(self, path):
        import sqlite3
        self.path = str(path)
        self._sqlite3 = sqlite3
        self._w = sqlite3.connect(self.path, check_same_thread=False)
        self._w.execute("PRAGMA journal_mode=WAL")
        self._w.execute(
            "CREATE TABLE IF NOT EXISTS static_info ("
            " session_id TEXT PRIMARY KEY, json TEXT NOT NULL)")
        self._w.execute(
            "CREATE TABLE IF NOT EXISTS updates ("
            " id INTEGER PRIMARY KEY AUTOINCREMENT,"
            " session_id TEXT NOT NULL,"
            " iteration INTEGER NOT NULL DEFAULT 0,"
            " ts REAL NOT NULL DEFAULT 0,"
            " json TEXT NOT NULL)")
        self._w.execute(
            "CREATE INDEX IF NOT EXISTS idx_updates_session_iter"
            " ON updates (session_id, iteration)")
        self._w.commit()
        self._lock = threading.Lock()
        self._listeners = []

    def _read_conn(self):
        # short-lived per-call connection: safe from any thread/process
        return self._sqlite3.connect(self.path, check_same_thread=False)

    # ---- router (write) ---------------------------------------------------
    def put_static_info(self, report):
        d = _as_dict(report)
        with self._lock:
            self._w.execute(
                "INSERT OR REPLACE INTO static_info (session_id, json)"
                " VALUES (?, ?)", (d["session_id"], json.dumps(d)))
            self._w.commit()
        self._notify(d)

    def put_update(self, report):
        d = _as_dict(report)
        with self._lock:
            self._w.execute(
                "INSERT INTO updates (session_id, iteration, ts, json)"
                " VALUES (?, ?, ?, ?)",
                (d["session_id"], int(d.get("iteration", 0)),
                 float(d.get("timestamp", 0.0)), json.dumps(d)))
            self._w.commit()
        self._notify(d)

    # ---- storage (read) ---------------------------------------------------
    def list_session_ids(self):
        with contextlib.closing(self._read_conn()) as c:
            rows = c.execute(
                "SELECT session_id FROM static_info UNION "
                "SELECT DISTINCT session_id FROM updates").fetchall()
        return sorted(r[0] for r in rows)

    def get_static_info(self, session_id):
        with contextlib.closing(self._read_conn()) as c:
            row = c.execute("SELECT json FROM static_info WHERE session_id=?",
                            (session_id,)).fetchone()
        return json.loads(row[0]) if row else None

    def get_all_updates(self, session_id):
        with contextlib.closing(self._read_conn()) as c:
            rows = c.execute(
                "SELECT json FROM updates WHERE session_id=? ORDER BY id",
                (session_id,)).fetchall()
        return [json.loads(r[0]) for r in rows]

    def get_latest_update(self, session_id):
        with contextlib.closing(self._read_conn()) as c:
            row = c.execute(
                "SELECT json FROM updates WHERE session_id=?"
                " ORDER BY id DESC LIMIT 1", (session_id,)).fetchone()
        return json.loads(row[0]) if row else None

    def get_updates_tail(self, session_id, n):
        """Last n updates in order via the id index (bounded read)."""
        n = int(n)
        if n <= 0:                 # negative LIMIT means unlimited in sqlite
            return []
        with contextlib.closing(self._read_conn()) as c:
            rows = c.execute(
                "SELECT json FROM updates WHERE session_id=?"
                " ORDER BY id DESC LIMIT ?",
                (session_id, n)).fetchall()
        return [json.loads(r[0]) for r in reversed(rows)]

    def get_updates_since(self, session_id, iteration):
        """Indexed range read (J7FileStatsStorage.getAllUpdatesAfter role)."""
        with contextlib.closing(self._read_conn()) as c:
            rows = c.execute(
                "SELECT json FROM updates WHERE session_id=? AND iteration>?"
                " ORDER BY iteration", (session_id, int(iteration))).fetchall()
        return [json.loads(r[0]) for r in rows]

    def count_updates(self, session_id):
        with contextlib.closing(self._read_conn()) as c:
            (n,) = c.execute("SELECT COUNT(*) FROM updates WHERE session_id=?",
                             (session_id,)).fetchone()
        return n

    def close(self):
        with self._lock:
            self._w.close()
