"""Stats storage + routing.

Reference: deeplearning4j-core api/storage/{StatsStorage.java,
StatsStorageRouter.java, Persistable.java} and impl/
{CollectionStatsStorageRouter, RemoteUIStatsStorageRouter.java (HTTP POST)};
deeplearning4j-ui-model storage/{InMemoryStatsStorage, FileStatsStorage,
mapdb/MapDBStatsStorage, sqlite/J7FileStatsStorage}.

The reports are JSON (ui/stats.py) so FileStatsStorage is a JSONL append log
(replacing MapDB/SQLite — same durability role, zero dependencies).
"""
from __future__ import annotations

import json
import os
import threading


class StatsStorageRouter:
    """Write-side API (reference: api/storage/StatsStorageRouter.java)."""

    def put_static_info(self, report):
        raise NotImplementedError

    def put_update(self, report):
        raise NotImplementedError


class CollectionStatsStorageRouter(StatsStorageRouter):
    """Collects into plain lists (reference:
    impl/CollectionStatsStorageRouter.java)."""

    def __init__(self):
        self.static_info = []
        self.updates = []

    def put_static_info(self, report):
        self.static_info.append(report)

    def put_update(self, report):
        self.updates.append(report)


class InMemoryStatsStorage(StatsStorageRouter):
    """Read+write storage (reference: InMemoryStatsStorage.java). Also the
    subscription hub the UI server attaches to (StatsStorage listeners)."""

    def __init__(self):
        self._static = {}     # session_id -> report dict
        self._updates = {}    # session_id -> [report dict]
        self._listeners = []
        self._lock = threading.Lock()

    # ---- router (write) ---------------------------------------------------
    def put_static_info(self, report):
        d = report.data if hasattr(report, "data") else dict(report)
        with self._lock:
            self._static[d["session_id"]] = d
        self._notify(d)

    def put_update(self, report):
        d = report.data if hasattr(report, "data") else dict(report)
        with self._lock:
            self._updates.setdefault(d["session_id"], []).append(d)
        self._notify(d)

    # ---- storage (read) ---------------------------------------------------
    def list_session_ids(self):
        with self._lock:
            ids = set(self._static) | set(self._updates)
        return sorted(ids)

    def get_static_info(self, session_id):
        with self._lock:
            return self._static.get(session_id)

    def get_all_updates(self, session_id):
        with self._lock:
            return list(self._updates.get(session_id, []))

    def get_latest_update(self, session_id):
        with self._lock:
            ups = self._updates.get(session_id)
            return ups[-1] if ups else None

    # ---- listeners --------------------------------------------------------
    def register_listener(self, fn):
        self._listeners.append(fn)

    def _notify(self, d):
        for fn in self._listeners:
            fn(d)


class FileStatsStorage(InMemoryStatsStorage):
    """Durable JSONL-backed storage (reference: FileStatsStorage.java /
    MapDBStatsStorage role). Appends every report; reloads on open."""

    def __init__(self, path):
        super().__init__()
        self.path = str(path)
        if os.path.exists(self.path):
            with open(self.path) as fh:
                for line in fh:
                    line = line.strip()
                    if not line:
                        continue
                    d = json.loads(line)
                    if d.get("type") == "init":
                        super().put_static_info(d)
                    else:
                        super().put_update(d)
        self._fh = open(self.path, "a")

    def put_static_info(self, report):
        d = report.data if hasattr(report, "data") else dict(report)
        self._fh.write(json.dumps(d) + "\n")
        self._fh.flush()
        super().put_static_info(d)

    def put_update(self, report):
        d = report.data if hasattr(report, "data") else dict(report)
        self._fh.write(json.dumps(d) + "\n")
        self._fh.flush()
        super().put_update(d)

    def close(self):
        self._fh.close()


class RemoteUIStatsStorageRouter(StatsStorageRouter):
    """HTTP POST of reports to a remote UI server (reference:
    impl/RemoteUIStatsStorageRouter.java; receiver = the UI server's
    RemoteReceiverModule). Retries with backoff like the reference
    (maxRetryCount/retryBackoffBase)."""

    def __init__(self, url, max_retries=3, backoff_base_ms=100):
        self.url = url.rstrip("/") + "/remoteReceive"
        self.max_retries = max_retries
        self.backoff_base_ms = backoff_base_ms

    def _post(self, d):
        import time
        import urllib.request
        body = json.dumps(d).encode()
        for attempt in range(self.max_retries + 1):
            try:
                req = urllib.request.Request(
                    self.url, data=body,
                    headers={"Content-Type": "application/json"})
                with urllib.request.urlopen(req, timeout=5) as resp:
                    resp.read()
                return True
            except Exception:
                if attempt == self.max_retries:
                    return False
                time.sleep(self.backoff_base_ms / 1000.0 * (2 ** attempt))

    def put_static_info(self, report):
        self._post(report.data if hasattr(report, "data") else dict(report))

    def put_update(self, report):
        self._post(report.data if hasattr(report, "data") else dict(report))
