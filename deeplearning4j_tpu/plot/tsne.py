"""t-SNE embedding.

Reference: deeplearning4j-core plot/BarnesHutTsne.java (850 LoC; perplexity
binary search over conditional Gaussians, early exaggeration, momentum
gradient descent, Barnes-Hut O(N log N) force approximation via SpTree +
VPTree-kNN sparse input similarities) and plot/Tsne.java (exact O(N^2)).

TPU-first split: the exact path runs the WHOLE gradient loop as jitted XLA
(pairwise matrices are MXU-friendly; N<=a few thousand fits easily) — this is
the default and is typically faster on accelerators than Barnes-Hut up to
~10k points. The Barnes-Hut path (theta>0) keeps the reference's host-side
tree algorithm for very large N.
"""
from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp


def _hbeta(d_row, beta):
    p = np.exp(-d_row * beta)
    sum_p = max(p.sum(), 1e-12)
    h = np.log(sum_p) + beta * (d_row @ p) / sum_p
    return h, p / sum_p


def _binary_search_perplexity(D, perplexity, tol=1e-5, max_tries=50):
    """Per-row beta search so each conditional distribution has the requested
    perplexity (reference: BarnesHutTsne.computeGaussianPerplexity)."""
    n = D.shape[0]
    target = np.log(perplexity)
    P = np.zeros_like(D)
    for i in range(n):
        beta, beta_min, beta_max = 1.0, -np.inf, np.inf
        d_row = D[i].copy()
        d_row[i] = 0.0
        for _ in range(max_tries):
            h, p = _hbeta(d_row, beta)
            if abs(h - target) < tol:
                break
            if h > target:
                beta_min = beta
                beta = beta * 2 if beta_max == np.inf else (beta + beta_max) / 2
            else:
                beta_max = beta
                beta = beta / 2 if beta_min == -np.inf else (beta + beta_min) / 2
        p[i] = 0.0
        P[i] = p
    return P


@functools.partial(jax.jit, static_argnames=("n_iter", "switch_momentum"))
def _tsne_loop(P, Y0, lr, n_iter, early_exaggeration, switch_momentum):
    """Exact-gradient t-SNE loop compiled as one XLA while-program."""
    def grad(P_eff, Y):
        sum_y = jnp.sum(Y ** 2, 1)
        num = 1.0 / (1.0 + sum_y[:, None] + sum_y[None, :] -
                     2.0 * (Y @ Y.T))                           # student-t kernel
        num = num.at[jnp.diag_indices(Y.shape[0])].set(0.0)
        Q = num / jnp.maximum(num.sum(), 1e-12)
        PQ = P_eff - jnp.maximum(Q, 1e-12)
        W = PQ * num
        # grad_i = 4 * sum_j W_ij (y_i - y_j)
        g = 4.0 * (W.sum(1)[:, None] * Y - W @ Y)
        return g

    def body(i, state):
        Y, vel, gains = state
        momentum = jnp.where(i < switch_momentum, 0.5, 0.8)
        exag = jnp.where(i < switch_momentum, early_exaggeration, 1.0)
        g = grad(P * exag, Y)
        gains = jnp.where(jnp.sign(g) != jnp.sign(vel),
                          gains + 0.2, gains * 0.8)
        gains = jnp.maximum(gains, 0.01)
        vel = momentum * vel - lr * gains * g
        Y = Y + vel
        Y = Y - Y.mean(0)
        return Y, vel, gains

    vel = jnp.zeros_like(Y0)
    gains = jnp.ones_like(Y0)
    Y, _, _ = jax.lax.fori_loop(0, n_iter, body, (Y0, vel, gains))
    return Y


class Tsne:
    """Exact t-SNE (reference: plot/Tsne.java). Builder-compatible with the
    reference's Tsne.Builder."""

    def __init__(self, n_components=2, perplexity=30.0, learning_rate=200.0,
                 n_iter=1000, early_exaggeration=12.0, seed=0, theta=0.0):
        self.n_components = n_components
        self.perplexity = perplexity
        self.learning_rate = learning_rate
        self.n_iter = n_iter
        self.early_exaggeration = early_exaggeration
        self.seed = seed
        self.theta = theta
        self.Y = None

    class Builder:
        def __init__(self):
            self._kw = {}

        def perplexity(self, p):
            self._kw["perplexity"] = p
            return self

        def learning_rate(self, lr):
            self._kw["learning_rate"] = lr
            return self

        def set_max_iter(self, n):
            self._kw["n_iter"] = n
            return self

        def theta(self, t):
            self._kw["theta"] = t
            return self

        def seed(self, s):
            self._kw["seed"] = s
            return self

        def build(self):
            return Tsne(**self._kw)

    @staticmethod
    def builder():
        return Tsne.Builder()

    def _input_similarities(self, X):
        X = np.asarray(X, np.float64)
        sum_x = (X ** 2).sum(1)
        D = np.maximum(sum_x[:, None] + sum_x[None] - 2 * X @ X.T, 0.0)
        P = _binary_search_perplexity(D, self.perplexity)
        P = P + P.T
        P = P / max(P.sum(), 1e-12)
        return np.maximum(P, 1e-12)

    def fit_transform(self, X):
        n = len(X)
        P = jnp.asarray(self._input_similarities(X), jnp.float32)
        rng = np.random.default_rng(self.seed)
        Y0 = jnp.asarray(rng.normal(scale=1e-4,
                                    size=(n, self.n_components)),
                         jnp.float32)
        switch = min(250, self.n_iter // 4)
        self.Y = np.asarray(_tsne_loop(P, Y0, self.learning_rate, self.n_iter,
                                       self.early_exaggeration, switch))
        return self.Y

    fit = fit_transform


class BarnesHutTsne(Tsne):
    """Barnes-Hut t-SNE (reference: plot/BarnesHutTsne.java). theta controls
    the accuracy/speed tradeoff; theta=0 delegates to the exact compiled
    path, theta>0 runs the host-side SpTree approximation with VPTree-kNN
    sparse similarities (3*perplexity neighbours like the reference)."""

    def __init__(self, n_components=2, perplexity=30.0, learning_rate=200.0,
                 n_iter=1000, early_exaggeration=12.0, seed=0, theta=0.5):
        super().__init__(n_components, perplexity, learning_rate, n_iter,
                         early_exaggeration, seed, theta)

    def fit_transform(self, X):
        if self.theta <= 0:
            return super().fit_transform(X)
        return self._fit_bh(np.asarray(X, np.float64))

    fit = fit_transform

    def _sparse_similarities(self, X):
        from ..clustering.vptree import VPTree
        n = len(X)
        k = min(n - 1, int(3 * self.perplexity))
        tree = VPTree(X, seed=self.seed)
        rows, cols, vals = [], [], []
        target = np.log(self.perplexity)
        for i in range(n):
            idxs, dists = tree.search(X[i], k + 1)
            pairs = [(j, d) for j, d in zip(idxs, dists) if j != i][:k]
            js = np.array([j for j, _ in pairs])
            d2 = np.array([d for _, d in pairs]) ** 2
            beta, bmin, bmax = 1.0, -np.inf, np.inf
            for _ in range(50):
                h, p = _hbeta(d2, beta)   # shared with the exact path
                if abs(h - target) < 1e-5:
                    break
                if h > target:
                    bmin = beta
                    beta = beta * 2 if bmax == np.inf else (beta + bmax) / 2
                else:
                    bmax = beta
                    beta = beta / 2 if bmin == -np.inf else (beta + bmin) / 2
            rows.extend([i] * len(js))
            cols.extend(js.tolist())
            vals.extend(p.tolist())
        # symmetrize
        P = {}
        for r, c, v in zip(rows, cols, vals):
            P[(r, c)] = P.get((r, c), 0.0) + v
            P[(c, r)] = P.get((c, r), 0.0) + v
        total = sum(P.values())
        return {k2: v / total for k2, v in P.items()}

    def _fit_bh(self, X):
        from ..clustering.sptree import SpTree
        n = len(X)
        P = self._sparse_similarities(X)
        edges = [[] for _ in range(n)]
        for (i, j), v in P.items():
            edges[i].append((j, v))
        rng = np.random.default_rng(self.seed)
        Y = rng.normal(scale=1e-4, size=(n, self.n_components))
        vel = np.zeros_like(Y)
        gains = np.ones_like(Y)
        switch = min(250, self.n_iter // 4)
        for it in range(self.n_iter):
            exag = self.early_exaggeration if it < switch else 1.0
            momentum = 0.5 if it < switch else 0.8
            tree = SpTree(Y)
            pos_f = np.zeros_like(Y)
            neg_f = np.zeros_like(Y)
            z = 0.0
            for i in range(n):
                nf = np.zeros(self.n_components)
                z += tree.compute_non_edge_forces(Y[i], self.theta, nf)
                neg_f[i] = nf
                for j, p in edges[i]:
                    diff = Y[i] - Y[j]
                    q = 1.0 / (1.0 + diff @ diff)
                    pos_f[i] += exag * p * q * diff
            g = pos_f - neg_f / max(z, 1e-12)
            gains = np.where(np.sign(g) != np.sign(vel), gains + 0.2,
                             gains * 0.8)
            gains = np.maximum(gains, 0.01)
            vel = momentum * vel - self.learning_rate * gains * g
            Y = Y + vel
            Y = Y - Y.mean(0)
        self.Y = Y
        return Y
