"""Embedding/visualization algorithms (reference: deeplearning4j-core plot/ —
BarnesHutTsne.java 850 LoC, Tsne.java)."""
from .tsne import BarnesHutTsne, Tsne

__all__ = ["BarnesHutTsne", "Tsne"]
