// Native host-side IO runtime: CSV parsing, IDX (MNIST) decoding, batch
// assembly and pixel normalization.
//
// Reference analog: the external DataVec library + libnd4j host-side helpers
// the DL4J layer depends on (SURVEY.md L0/§2.9 — the reference's data path is
// native via nd4j/JavaCPP; RecordReaderDataSetIterator feeds the accelerator
// from natively parsed records). This library plays that role for the TPU
// build: the Python layer (datasets/records/*) keeps the contract, and when
// this .so is present the hot parsing/assembly loops run here instead of the
// Python interpreter. Exposed as a plain C ABI consumed via ctypes (the
// environment has no pybind11).
//
// Build: python -m deeplearning4j_tpu.native.build  (g++ -O3 -shared -fPIC)

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <cstdio>
#include <atomic>
#include <thread>
#include <vector>
#include <locale.h>

extern "C" {

// ------------------------------------------------------------ numerics ----
// The fast path must produce EXACTLY what Python's float() would, or defer.
// strtod alone can't guarantee that: it is LC_NUMERIC-dependent (decimal
// comma locales) and accepts hex floats ("0x1p3") and "nan(chars)" that
// float() spells differently or rejects. So fields are first validated
// against the strict decimal grammar  [+-]?(d+[.d*]|.d+)([eE][+-]?d+)?
// (hex / inf / nan / underscores all fail -> caller falls back to the
// Python parser, which handles them consistently), then converted with
// strtod_l under a pinned "C" locale for exact double parity.
static locale_t c_locale() {
    static locale_t loc = newlocale(LC_ALL_MASK, "C", (locale_t)0);
    return loc;
}

// returns length of the valid strict-decimal prefix ending at delim/EOL,
// or -1 if the field (up to delim/'\n'/'\r') is not strict-decimal
static int64_t strict_decimal_len(const char* p, int64_t len, char delim) {
    int64_t i = 0;
    if (i < len && (p[i] == '+' || p[i] == '-')) ++i;
    int64_t digits = 0, frac_digits = 0;
    while (i < len && p[i] >= '0' && p[i] <= '9') { ++i; ++digits; }
    if (i < len && p[i] == '.') {
        ++i;
        while (i < len && p[i] >= '0' && p[i] <= '9') { ++i; ++frac_digits; }
    }
    if (digits + frac_digits == 0) return -1;
    if (i < len && (p[i] == 'e' || p[i] == 'E')) {
        ++i;
        if (i < len && (p[i] == '+' || p[i] == '-')) ++i;
        int64_t exp_digits = 0;
        while (i < len && p[i] >= '0' && p[i] <= '9') { ++i; ++exp_digits; }
        if (exp_digits == 0) return -1;
    }
    if (i < len && p[i] != delim && p[i] != '\n' && p[i] != '\r') return -1;
    return i;
}

// ---------------------------------------------------------------- CSV -----
// Parse a numeric CSV buffer into a dense float64 matrix (row-major).
// Supports a single-char delimiter, optional lines to skip, blank-line
// tolerance. Returns 0 on success; fills *out_rows/*out_cols and writes into
// caller-provided `out` when non-null (two-phase: first call with out=null to
// size, then with the allocated buffer). Values are float64 so parity with
// the Python float() path is exact. Non-numeric or empty fields fail with -2
// (the Python caller falls back to its general quote-aware parser).
int dl4j_csv_parse(const char* buf, int64_t len, char delim, int64_t skip,
                   double* out, int64_t* out_rows, int64_t* out_cols) {
    int64_t rows = 0, cols = -1;
    int64_t i = 0;
    // skip leading lines
    for (int64_t s = 0; s < skip && i < len; ++s) {
        while (i < len && buf[i] != '\n') ++i;
        if (i < len) ++i;
    }
    int64_t write = 0;
    while (i < len) {
        // skip blank lines
        if (buf[i] == '\n' || buf[i] == '\r') { ++i; continue; }
        int64_t line_cols = 0;
        while (i < len && buf[i] != '\n') {
            // parse one field: validate strict decimal grammar first (see
            // strict_decimal_len), then convert locale-pinned
            int64_t flen = strict_decimal_len(buf + i, len - i, delim);
            if (flen <= 0) return -2;  // non-numeric / non-strict field
            char tmp[64];
            double v;
            if (flen < (int64_t)sizeof(tmp)) {
                memcpy(tmp, buf + i, flen);
                tmp[flen] = '\0';
                char* end = nullptr;
                v = strtod_l(tmp, &end, c_locale());
                if (end != tmp + flen) return -2;
            } else {
                return -2;  // absurdly long field: defer to Python
            }
            i += flen;
            // (strict_decimal_len guarantees buf[i] is delim/EOL/EOF here —
            // e.g. "1 2" with internal whitespace already deferred above)
            if (out) out[write] = v;
            ++write;
            ++line_cols;
            while (i < len && buf[i] == '\r') ++i;
            if (i < len && buf[i] == delim) {
                ++i;
                // a trailing delimiter means an empty final field — the
                // Python csv module keeps it; defer to that parser
                if (i >= len || buf[i] == '\n' || buf[i] == '\r') return -2;
            } else {
                break;
            }
        }
        if (i < len && buf[i] == '\n') ++i;
        if (line_cols > 0) {
            if (cols == -1) cols = line_cols;
            else if (cols != line_cols) return -3;  // ragged rows
            ++rows;
        }
    }
    *out_rows = rows;
    *out_cols = cols < 0 ? 0 : cols;
    return 0;
}

// ---------------------------------------------------------------- IDX -----
// Decode the IDX format (MNIST images/labels). Returns 0 on success and
// fills dims (up to 4); `out` sized by the product of dims, written as uint8.
int dl4j_idx_info(const uint8_t* buf, int64_t len, int64_t* dims,
                  int32_t* ndim) {
    if (len < 4 || buf[0] != 0 || buf[1] != 0) return -1;
    if (buf[2] != 0x08) return -2;  // only uint8 payloads (MNIST)
    int n = buf[3];
    if (n < 1 || n > 4 || len < 4 + 4 * n) return -3;
    for (int d = 0; d < n; ++d) {
        const uint8_t* p = buf + 4 + 4 * d;
        dims[d] = ((int64_t)p[0] << 24) | ((int64_t)p[1] << 16)
                | ((int64_t)p[2] << 8) | (int64_t)p[3];
    }
    *ndim = n;
    return 0;
}

int dl4j_idx_read(const uint8_t* buf, int64_t len, uint8_t* out,
                  int64_t out_len) {
    int64_t dims[4];
    int32_t nd;
    int rc = dl4j_idx_info(buf, len, dims, &nd);
    if (rc != 0) return rc;
    int64_t total = 1;
    for (int d = 0; d < nd; ++d) total *= dims[d];
    if (total > out_len || 4 + 4 * nd + total > len) return -4;
    memcpy(out, buf + 4 + 4 * nd, total);
    return 0;
}

// ------------------------------------------------------- batch assembly ---
// Gather `batch` rows of `row_elems` f32 elements from `src` at `indices`
// into a contiguous batch buffer — the shuffle-gather hot loop of
// RecordReaderDataSetIterator / MagicQueue, parallelized across threads.
void dl4j_gather_rows_f32(const float* src, const int64_t* indices,
                          int64_t batch, int64_t row_elems, float* out,
                          int32_t n_threads) {
    if (n_threads < 1) n_threads = 1;
    if (n_threads == 1 || batch < 64) {
        for (int64_t b = 0; b < batch; ++b)
            memcpy(out + b * row_elems, src + indices[b] * row_elems,
                   row_elems * sizeof(float));
        return;
    }
    std::vector<std::thread> ts;
    std::atomic<int64_t> next(0);
    for (int32_t t = 0; t < n_threads; ++t) {
        ts.emplace_back([&]() {
            int64_t b;
            while ((b = next.fetch_add(1)) < batch)
                memcpy(out + b * row_elems, src + indices[b] * row_elems,
                       row_elems * sizeof(float));
        });
    }
    for (auto& th : ts) th.join();
}

// uint8 pixels -> f32 in [min_range, max_range] (host-side fallback of the
// on-device ImageScalerPreProcessor for CPU-bound pipelines)
void dl4j_normalize_u8_f32(const uint8_t* src, int64_t n, float min_range,
                           float max_range, float* out) {
    const float scale = (max_range - min_range) / 255.0f;
    for (int64_t i = 0; i < n; ++i)
        out[i] = (float)src[i] * scale + min_range;
}

// one-hot encode int labels into a zeroed f32 matrix [n, n_classes]
int dl4j_one_hot_f32(const int64_t* labels, int64_t n, int64_t n_classes,
                     float* out) {
    memset(out, 0, (size_t)(n * n_classes) * sizeof(float));
    for (int64_t i = 0; i < n; ++i) {
        if (labels[i] < 0 || labels[i] >= n_classes) return -1;
        out[i * n_classes + labels[i]] = 1.0f;
    }
    return 0;
}

int dl4j_io_version() { return 1; }

}  // extern "C"
