"""Build the native IO runtime: g++ -O3 -shared -fPIC -> libdl4jtpu_io.so.

Run as `python -m deeplearning4j_tpu.native.build` or let
`deeplearning4j_tpu.native.load()` build lazily on first use.
"""
from __future__ import annotations

import os
import subprocess
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(_HERE, "src", "dl4jtpu_io.cpp")
LIB = os.path.join(_HERE, "libdl4jtpu_io.so")


def build(force=False):
    """Compile the shared library if missing or stale. Returns the .so path,
    or None when no C++ toolchain is available."""
    if not force and os.path.exists(LIB) and \
            os.path.getmtime(LIB) >= os.path.getmtime(SRC):
        return LIB
    cmd = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
           SRC, "-o", LIB]
    try:
        subprocess.run(cmd, check=True, capture_output=True)
    except FileNotFoundError:
        return None  # no g++ on this machine; Python fallbacks stay active
    except subprocess.CalledProcessError as e:
        raise RuntimeError(
            f"native build failed:\n{e.stderr.decode()}") from e
    return LIB


if __name__ == "__main__":
    out = build(force="--force" in sys.argv)
    print(out or "no C++ toolchain found; skipped")
