"""Native host-side IO runtime bindings (ctypes over libdl4jtpu_io.so).

Reference analog: SURVEY.md §2.9 — the reference's data/runtime path is
native (libnd4j + DataVec behind JavaCPP); this module is the TPU build's
equivalent seam. The C++ side (src/dl4jtpu_io.cpp) implements the host hot
loops — CSV parse, IDX decode, threaded batch gather, pixel normalize,
one-hot — and the Python data pipeline uses them when the library is present,
falling back to pure Python otherwise (`load()` returns None when no
toolchain/lib exists, so the framework never hard-requires the build step).
"""
from __future__ import annotations

import ctypes
import os

import numpy as np

_lib = None
_tried = False


def load(build_if_missing=True):
    """Return the loaded CDLL (building it on demand) or None. A failed
    build is reported once and cached — callers with pure-Python fallbacks
    (CSV/IDX readers) must keep working, and the compiler must not be
    re-invoked per parse call."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    _tried = True
    from .build import LIB, build
    path = LIB if os.path.exists(LIB) else None
    if path is None and build_if_missing:
        try:
            path = build()
        except RuntimeError as e:
            import warnings
            warnings.warn(f"native IO build failed; using Python fallbacks "
                          f"({e})", stacklevel=2)
            return None
    if path is None or not os.path.exists(path):
        return None
    lib = ctypes.CDLL(path)
    lib.dl4j_csv_parse.restype = ctypes.c_int
    lib.dl4j_csv_parse.argtypes = [
        ctypes.c_char_p, ctypes.c_int64, ctypes.c_char, ctypes.c_int64,
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64),
        ctypes.POINTER(ctypes.c_int64)]
    lib.dl4j_idx_info.restype = ctypes.c_int
    lib.dl4j_idx_info.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.POINTER(ctypes.c_int64),
                                  ctypes.POINTER(ctypes.c_int32)]
    lib.dl4j_idx_read.restype = ctypes.c_int
    lib.dl4j_idx_read.argtypes = [ctypes.c_char_p, ctypes.c_int64,
                                  ctypes.c_void_p, ctypes.c_int64]
    lib.dl4j_gather_rows_f32.restype = None
    lib.dl4j_gather_rows_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
        ctypes.c_void_p, ctypes.c_int32]
    lib.dl4j_normalize_u8_f32.restype = None
    lib.dl4j_normalize_u8_f32.argtypes = [
        ctypes.c_void_p, ctypes.c_int64, ctypes.c_float, ctypes.c_float,
        ctypes.c_void_p]
    lib.dl4j_one_hot_f32.restype = ctypes.c_int
    lib.dl4j_one_hot_f32.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                     ctypes.c_int64, ctypes.c_void_p]
    lib.dl4j_io_version.restype = ctypes.c_int
    _lib = lib
    return _lib


def available():
    return load(build_if_missing=True) is not None


# ------------------------------------------------------------ wrappers ----

def csv_parse(data: bytes, delimiter=",", skip_lines=0):
    """Parse a numeric CSV byte buffer -> float64 [rows, cols] ndarray
    (float64 so values match the Python float() parser bit-for-bit), or
    None when the native lib is absent or the content needs the general
    (quote-aware / non-numeric) Python parser."""
    lib = load()
    if lib is None or len(delimiter) != 1:
        return None
    rows = ctypes.c_int64()
    cols = ctypes.c_int64()
    rc = lib.dl4j_csv_parse(data, len(data), delimiter.encode(), skip_lines,
                            None, ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        return None
    out = np.empty((rows.value, cols.value), np.float64)
    rc = lib.dl4j_csv_parse(data, len(data), delimiter.encode(), skip_lines,
                            out.ctypes.data_as(ctypes.c_void_p),
                            ctypes.byref(rows), ctypes.byref(cols))
    if rc != 0:
        return None
    return out


def idx_read(data: bytes):
    """Decode an IDX (MNIST) buffer -> uint8 ndarray, or None if unavailable."""
    lib = load()
    if lib is None:
        return None
    dims = (ctypes.c_int64 * 4)()
    nd = ctypes.c_int32()
    if lib.dl4j_idx_info(data, len(data), dims, ctypes.byref(nd)) != 0:
        return None
    shape = tuple(dims[i] for i in range(nd.value))
    out = np.empty(shape, np.uint8)
    rc = lib.dl4j_idx_read(data, len(data),
                           out.ctypes.data_as(ctypes.c_void_p), out.size)
    return out if rc == 0 else None


def gather_rows(src, indices, n_threads=0):
    """Shuffle-gather rows of a 2-D f32 array into a fresh batch buffer."""
    lib = load()
    src = np.ascontiguousarray(src, np.float32)
    idx = np.ascontiguousarray(indices, np.int64)
    if lib is None:
        return src[idx]
    out = np.empty((len(idx),) + src.shape[1:], np.float32)
    row_elems = int(np.prod(src.shape[1:])) if src.ndim > 1 else 1
    if n_threads <= 0:
        n_threads = min(8, os.cpu_count() or 1)
    lib.dl4j_gather_rows_f32(src.ctypes.data_as(ctypes.c_void_p),
                             idx.ctypes.data_as(ctypes.c_void_p),
                             len(idx), row_elems,
                             out.ctypes.data_as(ctypes.c_void_p), n_threads)
    return out


def normalize_u8(src, min_range=0.0, max_range=1.0):
    lib = load()
    src = np.ascontiguousarray(src, np.uint8)
    if lib is None:
        return src.astype(np.float32) * ((max_range - min_range) / 255.0) \
            + min_range
    out = np.empty(src.shape, np.float32)
    lib.dl4j_normalize_u8_f32(src.ctypes.data_as(ctypes.c_void_p), src.size,
                              min_range, max_range,
                              out.ctypes.data_as(ctypes.c_void_p))
    return out


def one_hot(labels, n_classes):
    lib = load()
    lab = np.ascontiguousarray(labels, np.int64)
    if lib is None:
        return np.eye(n_classes, dtype=np.float32)[lab]
    out = np.empty((len(lab), n_classes), np.float32)
    rc = lib.dl4j_one_hot_f32(lab.ctypes.data_as(ctypes.c_void_p), len(lab),
                              n_classes, out.ctypes.data_as(ctypes.c_void_p))
    if rc != 0:
        raise ValueError("label out of range for one_hot")
    return out
