"""TelemetryListener: the bridge from the central MetricsRegistry into the
existing ui/storage router tier.

Attached like any training listener, it (a) records per-iteration training
metrics (iteration time histogram, iteration counter, score gauge) into a
MetricsRegistry, and (b) every `frequency` iterations flushes the whole
registry snapshot as a `type: "telemetry"` report through a
StatsStorageRouter — so a UI server (or a FileStatsStorage/Sqlite tier)
tails live metrics exactly like training stats, and a Prometheus scraper
hitting the UI server's `/metrics` sees the same registry.
"""
from __future__ import annotations

from .registry import get_registry
from ..util.time_source import monotonic_s, now_s


class TelemetryReport:
    """`type: "telemetry"` report dict for the stats storage tier."""

    def __init__(self, session_id, snapshot):
        self.data = {"type": "telemetry", "session_id": session_id,
                     "time": now_s(), "metrics": snapshot}

    def to_json(self):
        import json
        return json.dumps(self.data)


class TelemetryListener:
    """IterationListener recording training metrics into a registry and
    periodically flushing the registry into a stats storage router."""

    def __init__(self, router=None, registry=None, frequency=10,
                 session_id="telemetry"):
        self.router = router
        self.registry = registry if registry is not None else get_registry()
        self.frequency = max(1, int(frequency))
        self.session_id = session_id
        self._last_mono = None
        self.iterations = self.registry.counter(
            "training_iterations_total", "Parameter updates completed")
        self.epochs = self.registry.counter(
            "training_epochs_total", "Training epochs completed")
        self.iteration_ms = self.registry.histogram(
            "training_iteration_ms", "Wall ms per training iteration")
        self.score = self.registry.gauge(
            "training_score", "Latest training loss/score")

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        self.epochs.inc(1)
        self.flush()

    def iteration_done(self, model, iteration):
        now = monotonic_s()
        if self._last_mono is not None:
            self.iteration_ms.observe((now - self._last_mono) * 1000.0)
        self._last_mono = now
        self.iterations.inc(1)
        try:
            self.score.set(float(model.score_value))
        except (TypeError, ValueError):
            pass
        if iteration % self.frequency == 0:
            self.flush()

    def flush(self):
        """Route one registry snapshot into the storage tier (no-op without
        a router; a broken router must not abort training)."""
        if self.router is None:
            return None
        report = TelemetryReport(self.session_id, self.registry.snapshot())
        try:
            self.router.put_update(report)
        except Exception:
            return None
        return report
