"""Deep health: per-component probes aggregated into one liveness answer.

The seed `/healthz` always said 200 — a serving process with a dead batcher
thread, a drained admission queue, or a NaN-looping trainer looked exactly
as healthy as a working one. Here components (batcher, model registry,
admission queue, ETL pipelines, the trainer via TrainingHealthListener)
register *probes* — zero-argument callables returning one of

    "healthy" | "degraded" | "unhealthy"
    (status, {detail...})
    {"status": ..., detail...}

and `HealthMonitor.check()` aggregates them: overall status is the worst
component status, and the report carries per-component detail JSON. The
HTTP layer maps unhealthy -> 503 (load balancers pull the replica),
healthy/degraded -> 200 (degraded is visible in the body but still serves).

A probe that *raises* is itself an unhealthy signal (the component's own
introspection is broken), never a 500 on the scrape. Status transitions are
logged through the structured logger so `/logs` shows when and why a
component flipped.
"""
from __future__ import annotations

import threading

from ..util.time_source import now_s

HEALTHY = "healthy"
DEGRADED = "degraded"
UNHEALTHY = "unhealthy"
_RANK = {HEALTHY: 0, DEGRADED: 1, UNHEALTHY: 2}


def _normalize(result):
    """Probe result -> {"status": str, **detail}."""
    if isinstance(result, str):
        status, detail = result, {}
    elif isinstance(result, dict):
        d = dict(result)
        status = d.pop("status", UNHEALTHY)
        detail = d
    elif isinstance(result, (tuple, list)) and len(result) == 2:
        status, detail = result[0], dict(result[1] or {})
    else:
        raise TypeError(f"bad probe result {result!r}")
    status = str(status).lower()
    if status == "ok":                 # tolerated legacy spelling
        status = HEALTHY
    if status not in _RANK:
        raise ValueError(f"unknown health status {status!r}")
    return {"status": status, **detail}


class _StaticProbe:
    """Backing store for `set_status` push-style components."""

    def __init__(self, status, detail):
        self.status = status
        self.detail = detail

    def __call__(self):
        return self.status, self.detail


class HealthMonitor:
    """Registry of component probes + worst-status aggregation."""

    def __init__(self, logger=None):
        self._probes = {}
        self._last = {}               # component -> last seen status
        self._lock = threading.Lock()
        self.logger = logger

    # ---- registration ------------------------------------------------------
    def register(self, component, probe):
        """Register (or replace) a pull-style probe for `component`."""
        if not callable(probe):
            raise TypeError("probe must be callable")
        with self._lock:
            self._probes[str(component)] = probe
        return probe

    def register_unique(self, component, probe):
        """Register under `component`, or `component-N` when taken — one
        atomic check-and-insert, so concurrently-built components sharing a
        base name (e.g. two pipelines named "etl") never clobber each
        other's probe. Returns the key actually used (pass to unregister)."""
        if not callable(probe):
            raise TypeError("probe must be callable")
        with self._lock:
            key, i = str(component), 1
            while key in self._probes:
                i += 1
                key = f"{component}-{i}"
            self._probes[key] = probe
            return key

    def set_status(self, component, status, **detail):
        """Push-style API: record a component's status directly (repeat
        calls update in place)."""
        status = _normalize(status)["status"]
        with self._lock:
            probe = self._probes.get(str(component))
            if isinstance(probe, _StaticProbe):
                probe.status, probe.detail = status, detail
            else:
                self._probes[str(component)] = _StaticProbe(status, detail)

    def unregister(self, component):
        with self._lock:
            self._probes.pop(str(component), None)
            self._last.pop(str(component), None)

    def components(self):
        with self._lock:
            return sorted(self._probes)

    # ---- reading -----------------------------------------------------------
    def check(self):
        """{"status": worst, "time", "components": {name: {...}}} — probes
        run outside the lock (a slow probe must not block registration)."""
        with self._lock:
            probes = dict(self._probes)
        components = {}
        for name in sorted(probes):
            try:
                components[name] = _normalize(probes[name]())
            except Exception as e:
                components[name] = {"status": UNHEALTHY,
                                    "error": f"{type(e).__name__}: {e}"}
        overall = HEALTHY
        for name, comp in components.items():
            if _RANK[comp["status"]] > _RANK[overall]:
                overall = comp["status"]
            self._log_transition(name, comp)
        return {"status": overall, "time": now_s(), "components": components}

    def _log_transition(self, name, comp):
        with self._lock:
            prev = self._last.get(name)
            self._last[name] = comp["status"]
        if self.logger is None or comp["status"] == prev:
            return
        level = {HEALTHY: "info", DEGRADED: "warning",
                 UNHEALTHY: "error"}[comp["status"]]
        self.logger.log(level, "health_transition", component=name,
                        status=comp["status"], previous=prev)

    @staticmethod
    def http_status(report):
        """HTTP code for a check() report: only unhealthy takes the replica
        out of rotation; degraded still serves (visible in the body)."""
        return 503 if report["status"] == UNHEALTHY else 200


# ---- process-default monitor ------------------------------------------------
_default_monitor = None
_default_lock = threading.Lock()


def get_monitor() -> HealthMonitor:
    """Process-default monitor (ETL pipelines, training listeners, and the
    UI server's /healthz all meet here unless given an explicit one)."""
    global _default_monitor
    with _default_lock:
        if _default_monitor is None:
            _default_monitor = HealthMonitor()
        return _default_monitor


def set_monitor(monitor) -> HealthMonitor:
    global _default_monitor
    with _default_lock:
        _default_monitor = monitor
    return monitor
