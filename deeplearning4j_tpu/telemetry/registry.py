"""Central metrics registry: thread-safe counters, gauges, and bounded
histograms with exact-bucket percentiles.

One registry replaces the three ad-hoc metric stores the stack grew
(`serving/metrics.py` private counters+reservoir, `ui/stats.py` listener
state, `optimize/listeners` throughput fields): producers get-or-create
named instruments here, and every consumer (JSON snapshot, Prometheus text
exposition, the ui/storage router flush) reads the same state.

Instruments support labels Prometheus-style: `c.inc(2, bucket="8")` keeps
one value per label-set inside the instrument. Histograms keep, per
label-set, the fixed-bucket cumulative counts (for Prometheus `_bucket`
series) plus a bounded most-recent-sample reservoir for exact percentiles —
the reservoir is COPIED under the lock and sorted outside it, so a
percentile read never stalls the recording hot path (the old
ServingMetrics.snapshot sorted 4096 samples while holding the lock).
"""
from __future__ import annotations

import threading

from .trace import current_span
from ..util.time_source import now_s


def _labelkey(labels):
    return tuple(sorted(labels.items()))


def _quantile(sorted_vals, q):
    """Exact quantile over an already-sorted list, or None when empty (the
    one implementation behind percentile/percentiles/percentile_merged)."""
    if not sorted_vals:
        return None
    idx = min(len(sorted_vals) - 1,
              int(round(float(q) * (len(sorted_vals) - 1))))
    return sorted_vals[idx]


class _Instrument:
    kind = "untyped"

    def __init__(self, name, help=""):
        self.name = str(name)
        self.help = str(help)
        self._lock = threading.Lock()

    def series(self):
        """[(labels_dict, value)] for exposition."""
        raise NotImplementedError


class Counter(_Instrument):
    """Monotonically increasing. `add`/`get` mirror util.concurrency
    .AtomicCounter so existing callers swap in without code changes."""

    kind = "counter"

    def __init__(self, name, help=""):
        super().__init__(name, help)
        self._values = {}

    def inc(self, n=1, **labels):
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease")
        key = _labelkey(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0) + n
            return self._values[key]

    add = inc                       # AtomicCounter-compatible spelling

    def get(self, **labels):
        """Value for one label-set, or the sum over all when unlabeled."""
        with self._lock:
            if labels:
                return self._values.get(_labelkey(labels), 0)
            return sum(self._values.values()) if self._values else 0

    @property
    def value(self):
        return self.get()

    def series(self):
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._values.items())]


class Gauge(_Instrument):
    """Point-in-time value; either set explicitly or computed by a callback
    at collection time (queue depth, device memory)."""

    kind = "gauge"

    def __init__(self, name, help="", fn=None):
        super().__init__(name, help)
        self._values = {}
        self._fn = fn
        self.fn_label = "name"      # label key for dict-returning callbacks

    def set(self, value, **labels):
        with self._lock:
            self._values[_labelkey(labels)] = float(value)

    def inc(self, n=1, **labels):
        key = _labelkey(labels)
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + n

    def dec(self, n=1, **labels):
        self.inc(-n, **labels)

    def set_function(self, fn):
        self._fn = fn

    def get(self, **labels):
        if self._fn is not None:
            try:
                return self._fn()
            except Exception as e:
                self._log_callback_error(e)
                return None
        with self._lock:
            return self._values.get(_labelkey(labels))

    def _log_callback_error(self, exc):
        # prefer the owning registry's logger (a ServingServer wires its own
        # StructuredLogger there, so the error shows on THAT server's /logs);
        # lazy import: logging builds its counter on this module's registry
        try:
            logger = getattr(getattr(self, "_owner", None), "logger", None)
            if logger is None:
                from .logging import get_logger
                logger = get_logger()
            logger.warning("gauge_callback_error", metric=self.name,
                           error=f"{type(exc).__name__}: {exc}")
        except Exception:
            pass                       # logging must never break a scrape

    def series(self):
        if self._fn is not None:
            try:
                v = self._fn()
            except Exception as e:     # a dead callback must not kill scrape
                self._log_callback_error(e)
                return []
            if v is None:
                return []
            if isinstance(v, dict):    # callback may return {label: value}
                return [({self.fn_label: str(k)}, float(x)) for k, x in
                        sorted(v.items())]
            return [({}, float(v))]
        with self._lock:
            return [(dict(k), v) for k, v in sorted(self._values.items())]


DEFAULT_LATENCY_BUCKETS_MS = (1.0, 2.5, 5.0, 10.0, 25.0, 50.0, 100.0, 250.0,
                              500.0, 1000.0, 2500.0, 5000.0, 10000.0)


class _HistState:
    __slots__ = ("count", "sum", "bucket_counts", "reservoir", "_cap",
                 "exemplars", "_ex_cap")

    def __init__(self, n_buckets, reservoir_cap, exemplar_cap):
        self.count = 0
        self.sum = 0.0
        self.bucket_counts = [0] * n_buckets   # non-cumulative, per bound
        self.reservoir = []                    # most-recent cap samples
        self._cap = reservoir_cap
        # bounded latest-wins (value, trace_id) exemplars: the join key from
        # a metric anomaly back to its /trace spans and /logs records
        self.exemplars = []
        self._ex_cap = exemplar_cap

    def observe(self, v, bounds, trace_id=None):
        self.count += 1
        self.sum += v
        for i, b in enumerate(bounds):
            if v <= b:
                self.bucket_counts[i] += 1
                break
        self.reservoir.append(v)
        if len(self.reservoir) > self._cap:
            del self.reservoir[:len(self.reservoir) - self._cap]
        if trace_id is not None and self._ex_cap > 0:
            self.exemplars.append({"value": v, "trace_id": trace_id,
                                   "time": now_s()})
            if len(self.exemplars) > self._ex_cap:
                del self.exemplars[:len(self.exemplars) - self._ex_cap]


class Histogram(_Instrument):
    """Fixed-bound buckets (+inf implicit) plus a bounded most-recent
    reservoir for exact percentiles over recent traffic."""

    kind = "histogram"
    RESERVOIR = 4096
    EXEMPLARS = 10      # per label-set: bounded, latest-wins

    def __init__(self, name, help="", buckets=DEFAULT_LATENCY_BUCKETS_MS,
                 reservoir=RESERVOIR, exemplars=EXEMPLARS):
        super().__init__(name, help)
        self.bounds = tuple(sorted(float(b) for b in buckets))
        self.reservoir_cap = int(reservoir)
        self.exemplar_cap = int(exemplars)
        self._states = {}

    def _state(self, labels):
        key = _labelkey(labels)
        st = self._states.get(key)
        if st is None:
            st = self._states[key] = _HistState(len(self.bounds) + 1,
                                                self.reservoir_cap,
                                                self.exemplar_cap)
        return st

    def observe(self, value, trace_id=None, **labels):
        """Record one observation. `trace_id` (or, by default, the calling
        thread's current span) becomes a bounded OpenMetrics exemplar —
        the pointer from "p99 spiked" to the exact trace that spiked it."""
        v = float(value)
        if trace_id is None:
            span = current_span()
            if span is not None:
                trace_id = span.trace_id
        with self._lock:
            st = self._state(labels)
            bounded = self.bounds + (float("inf"),)
            st.observe(v, bounded, trace_id=trace_id)

    def exemplars(self, **labels):
        """Recorded exemplars, oldest first: one label-set's when labels are
        given, else the union across every label-set (the alert-rule read)."""
        with self._lock:
            if labels:
                st = self._states.get(_labelkey(labels))
                return [dict(e) for e in st.exemplars] if st else []
            out = [e for st in self._states.values() for e in st.exemplars]
        out.sort(key=lambda e: e["time"])
        return [dict(e) for e in out]

    def count(self, **labels):
        with self._lock:
            st = self._states.get(_labelkey(labels))
            return st.count if st else 0

    def sum(self, **labels):
        with self._lock:
            st = self._states.get(_labelkey(labels))
            return st.sum if st else 0.0

    def _reservoir_copy(self, labels):
        with self._lock:
            st = self._states.get(_labelkey(labels))
            return list(st.reservoir) if st else []

    def percentile(self, q, **labels):
        """Exact percentile over the recent reservoir (sorted OUTSIDE the
        lock), or None when empty."""
        vals = self._reservoir_copy(labels)
        vals.sort()
        return _quantile(vals, q)

    def percentile_merged(self, q):
        """Exact percentile over the UNION of every label-set's reservoir —
        the read an alert rule wants when it names no labels (e.g. consumer
        wait across all ETL pipelines, which record under pipeline=<name>)."""
        with self._lock:
            vals = [v for st in self._states.values() for v in st.reservoir]
        vals.sort()
        return _quantile(vals, q)

    def percentiles(self, qs=(0.50, 0.95, 0.99), **labels):
        """One reservoir copy + one sort for several quantiles; returns
        {"count", "p50", ..., "max"} (the old ServingMetrics latency shape)."""
        vals = self._reservoir_copy(labels)
        vals.sort()
        out = {"count": len(vals)}
        for q in qs:
            out[f"p{int(round(q * 100))}"] = _quantile(vals, q)
        out["max"] = vals[-1] if vals else None
        return out

    def series(self):
        """[(labels, {"count", "sum", "buckets": [(le, cumulative)...],
        "exemplars": [...]})]."""
        with self._lock:
            out = []
            for key, st in sorted(self._states.items()):
                cum, buckets = 0, []
                bounded = self.bounds + (float("inf"),)
                for b, c in zip(bounded, st.bucket_counts):
                    cum += c
                    buckets.append((b, cum))
                out.append((dict(key), {"count": st.count, "sum": st.sum,
                                        "buckets": buckets,
                                        "exemplars": [dict(e) for e in
                                                      st.exemplars]}))
            return out


class MetricsRegistry:
    """Get-or-create named instruments; collect them all for exposition.
    `logger` (optional, a StructuredLogger) receives instrument-level
    problems like raising gauge callbacks — a server wires its own logger
    here so those records show on that server's /logs."""

    def __init__(self, logger=None):
        self._metrics = {}
        self._lock = threading.Lock()
        self.logger = logger

    def _get_or_create(self, cls, name, help, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help=help, **kw)
                m._owner = self
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name, help="") -> Counter:
        return self._get_or_create(Counter, name, help)

    def gauge(self, name, help="", fn=None) -> Gauge:
        g = self._get_or_create(Gauge, name, help)
        if fn is not None:
            g.set_function(fn)
        return g

    def histogram(self, name, help="",
                  buckets=DEFAULT_LATENCY_BUCKETS_MS) -> Histogram:
        return self._get_or_create(Histogram, name, help, buckets=buckets)

    def get(self, name):
        with self._lock:
            return self._metrics.get(name)

    def unregister(self, name):
        with self._lock:
            self._metrics.pop(name, None)

    def collect(self):
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    # ---- consumers ---------------------------------------------------------
    def snapshot(self):
        """JSON-friendly dump of every instrument (counters/gauges by
        label-set; histograms as count/sum/percentiles)."""
        out = {"time": now_s()}
        for m in self.collect():
            if m.kind == "histogram":
                d = m.percentiles()
                d["sum"] = m.sum()
                ex = m.exemplars()
                if ex:
                    d["exemplars"] = ex
                out[m.name] = d
            else:
                series = m.series()
                if len(series) == 1 and not series[0][0]:
                    out[m.name] = series[0][1]
                else:
                    out[m.name] = {
                        ",".join(f"{k}={v}" for k, v in sorted(ls.items()))
                        or "": v for ls, v in series}
        return out

    def to_prometheus(self):
        from .prometheus import render
        return render(self)


_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    """Process-default registry (training listeners, streaming, the UI
    server's /metrics endpoint)."""
    return _default_registry
