"""W3C trace-context propagation: carry a span's identity across processes.

Everything in `telemetry/` was single-process until this module: the Tracer's
current-span context is a thread-local, so a trace died at every HTTP hop and
every broker frame. Here the active span's identity travels as a `traceparent`
header (https://www.w3.org/TR/trace-context/):

    traceparent: 00-<32 hex trace-id>-<16 hex span-id>-<2 hex flags>

- `inject(headers)` stamps the CURRENT span's context into an outbound
  header dict (util.http.post_json/get_json call it on every request — the
  one choke point graftlint GL008 protects).
- `extract(headers)` parses an inbound header into a `SpanContext`, which any
  Tracer accepts as `parent=`: the server-side span then carries the caller's
  trace_id, so one request is ONE trace across client and server `/trace`
  exports and `/logs` correlation.
- `inject_message`/`extract_message` do the same for broker message dicts
  (streaming registry fan-out), under a `traceparent` key in the envelope.

Parsing is deliberately forgiving in exactly one direction: anything
malformed — wrong version, truncated, bad hex, all-zero ids — degrades to
"no parent" (None), NEVER an exception. A bad header from a foreign client
must not 500 the request it decorates.
"""
from __future__ import annotations

import re
from contextlib import contextmanager

from .trace import current_span

HEADER = "traceparent"
_TRACEPARENT_RE = re.compile(
    r"^00-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


class SpanContext:
    """A remote span identity: just enough to parent under (`Tracer.span(...,
    parent=ctx)` reads only .trace_id/.span_id)."""

    __slots__ = ("trace_id", "span_id")

    def __init__(self, trace_id, span_id):
        self.trace_id = trace_id
        self.span_id = span_id

    def __repr__(self):
        return f"SpanContext({self.trace_id}, {self.span_id})"

    def __eq__(self, other):
        return (isinstance(other, SpanContext)
                and other.trace_id == self.trace_id
                and other.span_id == self.span_id)


def format_traceparent(span_or_ctx) -> str | None:
    """The traceparent header value for a span/context, or None when it has
    no identity (NOOP_SPAN, None)."""
    if span_or_ctx is None:
        return None
    trace_id = getattr(span_or_ctx, "trace_id", None)
    span_id = getattr(span_or_ctx, "span_id", None)
    if trace_id is None or span_id is None:
        return None
    return f"00-{trace_id}-{span_id}-01"


def parse_traceparent(value) -> SpanContext | None:
    """Parse a traceparent header value; ANY malformation (wrong version,
    truncated, non-hex, all-zero ids) returns None — never raises."""
    if not isinstance(value, str):
        return None
    m = _TRACEPARENT_RE.match(value.strip().lower())
    if m is None:
        return None
    trace_id, span_id = m.group(1), m.group(2)
    if trace_id == "0" * 32 or span_id == "0" * 16:
        return None                    # all-zero ids are explicitly invalid
    return SpanContext(trace_id, span_id)


def _header_value(headers, name):
    """Case-insensitive header lookup that works for plain dicts AND
    email.message.Message (what http.server hands out, already
    case-insensitive)."""
    if headers is None:
        return None
    get = getattr(headers, "get", None)
    if get is not None:
        v = get(name)
        if v is not None:
            return v
    try:
        items = headers.items()
    except AttributeError:
        return None
    for k, v in items:
        if str(k).lower() == name:
            return v
    return None


def inject(headers, span=None):
    """Stamp the span's (default: thread-current span's) context into a
    mutable header dict; returns the dict. No active context = no header.
    A header already carrying a traceparent wins — a relay forwarding an
    explicit context must not sever the originating request's trace with
    its own (same rule inject_message enforces)."""
    if _header_value(headers, HEADER) is not None:
        return headers
    value = format_traceparent(span if span is not None else current_span())
    if value is not None:
        headers[HEADER] = value
    return headers


def extract(headers) -> SpanContext | None:
    """SpanContext from an inbound header collection, or None."""
    return parse_traceparent(_header_value(headers, HEADER))


@contextmanager
def server_span(tracer, headers, name):
    """Run an HTTP handler body inside a server span with the caller's
    REMOTE parent, iff the request carried a traceparent header — the one
    pattern both ServingServer and UIServer handlers need, kept here so a
    propagation change (tracestate, sampling flags) lands once. Requests
    without the header pay a single header lookup and open no span."""
    ctx = extract(headers)
    if ctx is None:
        yield None
        return
    with tracer.span(name, parent=ctx, remote=True) as span:
        yield span


def inject_message(msg_dict, span=None):
    """Copy of a broker/streaming message dict with the active trace context
    under a `traceparent` key. The original dict passes through untouched
    when there is no context (the hot publish path pays a copy only when
    actually traced) or when the message already carries one (a relay must
    not overwrite the originating request's context with its own)."""
    if isinstance(msg_dict, dict) and HEADER in msg_dict:
        return msg_dict
    value = format_traceparent(span if span is not None else current_span())
    if value is None:
        return msg_dict
    out = dict(msg_dict)
    out[HEADER] = value
    return out


def extract_message(msg_dict) -> SpanContext | None:
    """SpanContext from a message dict's `traceparent` key, or None."""
    if not isinstance(msg_dict, dict):
        return None
    return parse_traceparent(msg_dict.get(HEADER))
