"""Fleet aggregation plane: one scrape surface over N telemetry peers.

Every observability endpoint so far describes ONE process: a ServingServer's
`/metrics`, `/healthz`, `/alerts`, `/trace` each stop at its own registry.
A multi-replica serving fleet (ROADMAP item 1) needs the cross-host view:
which replica is slow, which is firing, one merged trace with a lane per
host. `FleetCollector` polls peer base-URLs over `util.http.get_json` (the
propagation choke point, so fleet scrapes are themselves traceable) and
aggregates:

- `metrics()`  — per-`instance` snapshots + merged numeric totals;
  `prometheus()` re-emits every peer's exposition text with an
  `instance="<peer>"` label injected into each sample line.
- `healthz()`  — worst-status aggregation, one component per peer. A DOWN
  peer is a `degraded` probe (visible, still scraping) — never a 500 from
  the fleet endpoint itself, and not `unhealthy` (the peer may be
  restarting; its own load balancer already pulled it).
- `alerts()`   — merged rule states with an `instance` field, firing first.
- `trace()`    — merged Chrome trace: each host's spans in a distinct `pid`
  lane with a `process_name` metadata record, so ui.perfetto.dev shows the
  fleet timeline host-by-host.
- `profile()`  — merged per-executable cost table (`/profile/cost` rows)
  with an `instance` field, fleet-sorted by HBM bytes per sample.

Polling is interval-gated through util.time_source (`maybe_poll`), so a
ManualClock drives staleness in tests with zero sleeps; `FleetServer`
exposes the aggregate at `GET /fleet/*`.
"""
from __future__ import annotations

import re
import threading
from urllib.parse import urlparse

from .health import DEGRADED, HEALTHY, UNHEALTHY, _RANK
from ..util.http import (BackgroundHttpServer, QuietHandler, get_json,
                         send_json, send_text)
from ..util.time_source import monotonic_s, now_s

# the label body must tolerate '}' INSIDE quoted label values (legal in the
# exposition format): match runs of non-brace/non-quote chars or whole quoted
# strings with escapes, not just [^}]*
_PROM_SAMPLE_RE = re.compile(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
                             r"(?:\{((?:[^{}\"]|\"(?:[^\"\\]|\\.)*\")*)\})?"
                             r"\s+(.*)$")


def _peer_name(url):
    """Default instance label for a peer base URL: host:port."""
    p = urlparse(url)
    return p.netloc or url


def _health_word(body):
    """Normalize a peer /healthz body to healthy/degraded/unhealthy."""
    if not isinstance(body, dict):
        return DEGRADED
    word = str(body.get("health") or body.get("status") or "").lower()
    if word == "ok":
        word = HEALTHY
    return word if word in _RANK else DEGRADED


def _mergeable_number(v):
    return isinstance(v, (int, float)) and not isinstance(v, bool)


_PERCENTILE_KEY = re.compile(r"^(p\d{1,2}|max|min)$")


def _merge_totals(snapshots):
    """Key-wise sum of the numeric parts of per-instance metric snapshots.
    Plain numbers sum; dicts of plain numbers sum key-wise UNLESS they carry
    percentile-shaped keys (p50/p99/max — quantiles of different reservoirs
    do NOT sum; the per-instance sections keep the honest values). A key
    whose shape DISAGREES across peers (dict on one, number on another —
    mixed server versions) keeps the first-seen shape rather than raising;
    the per-instance sections still show each peer's raw value."""
    totals = {}
    for snap in snapshots:
        if not isinstance(snap, dict):
            continue
        for key, v in snap.items():
            if key == "time":
                continue
            if _mergeable_number(v):
                cur = totals.get(key, 0)
                if _mergeable_number(cur):
                    totals[key] = cur + v
            elif isinstance(v, dict) and v and \
                    all(_mergeable_number(x) for x in v.values()) and \
                    not any(_PERCENTILE_KEY.match(str(k)) for k in v):
                sub = totals.setdefault(key, {})
                if isinstance(sub, dict):
                    for k, x in v.items():
                        sub[k] = sub.get(k, 0) + x
    return totals


def _relabel_prometheus(text, instance):
    """Peer exposition text with instance="..." injected into every sample
    line (comments and blank lines pass through; exemplar suffixes after
    ` # ` are preserved untouched)."""
    out = []
    esc = instance.replace("\\", "\\\\").replace('"', '\\"')
    for line in str(text).splitlines():
        if not line or line.startswith("#"):
            out.append(line)
            continue
        m = _PROM_SAMPLE_RE.match(line)
        if m is None:
            out.append(line)
            continue
        name, labels, rest = m.group(1), m.group(2), m.group(3)
        merged = f'instance="{esc}"' + (f",{labels}" if labels else "")
        out.append(f"{name}{{{merged}}} {rest}")
    return out


class FleetCollector:
    """Polls peer telemetry endpoints and serves merged views. `peers` is a
    list of base URLs (e.g. a ServingServer's `.url`); `names` optionally
    overrides the instance labels (default host:port)."""

    # (state key, peer path) — _fetch_peer scrapes exactly these, and a peer
    # is down only when every one of them fails; healthz additionally
    # records the HTTP status code
    ENDPOINTS = (("metrics", "/metrics"),
                 ("healthz", "/healthz"),
                 ("alerts", "/alerts"),
                 ("trace", "/trace"),
                 ("profile", "/profile/cost"),
                 ("prometheus", "/metrics?format=prometheus"))

    def __init__(self, peers, names=None, interval_s=10.0, timeout_s=2.0):
        self.peers = [str(p).rstrip("/") for p in peers]
        names = list(names) if names is not None else [None] * len(self.peers)
        if len(names) != len(self.peers):
            raise ValueError("names must match peers 1:1")
        self.names = [n if n else _peer_name(p)
                      for n, p in zip(names, self.peers)]
        if len(set(self.names)) != len(self.names):
            raise ValueError(f"duplicate instance names: {self.names}")
        self.interval_s = float(interval_s)
        self.timeout_s = float(timeout_s)
        self.polls = 0
        self._last_poll = None          # monotonic_s of last completed poll
        self._poll_lock = threading.Lock()
        self._data_lock = threading.Lock()
        self._data = {}                 # name -> peer state dict

    # ---- polling -----------------------------------------------------------
    def _fetch_peer(self, url):
        """Each endpoint fetches under its OWN try: one missing or slow
        endpoint (a peer type without /trace -> 404, one timed-out GET) must
        not classify a live peer as down and discard the data that DID
        arrive. A peer is `down` only when NO endpoint answered; partial
        failures keep `up` with per-endpoint detail in `errors`."""
        state = {"url": url, "status": "up", "error": None}
        errors = {}
        for key, path in self.ENDPOINTS:
            kw = {"with_status": True} if key == "healthz" else {}
            try:
                got = get_json(url + path, timeout=self.timeout_s, **kw)
            except Exception as e:      # connection refused/timeout/bad body
                errors[key] = f"{type(e).__name__}: {e}"
                got = (None, None) if key == "healthz" else None
            if key == "healthz":
                state["healthz_code"], state["healthz"] = got
            else:
                state[key] = got
        if len(errors) == len(self.ENDPOINTS):   # nothing answered at all
            state["status"] = "down"
            state["error"] = errors["metrics"]
        elif errors:
            state["errors"] = errors
        return state

    def poll_once(self):
        """Fetch every peer now; returns the per-instance state map.

        Peers are swept concurrently (one thread each): a wedged peer costs
        one peer's worth of timeouts per sweep, not len(peers) of them —
        _fetch_peer alone is up to 5 sequential GETs at `timeout_s` apiece,
        and every /fleet/* scrape waits on maybe_poll's single flight."""
        fresh = {}
        if len(self.peers) == 1:
            fresh[self.names[0]] = self._fetch_peer(self.peers[0])
        else:
            def fetch_into(name, url):
                fresh[name] = self._fetch_peer(url)   # per-key dict writes
            workers = [threading.Thread(target=fetch_into, args=(n, u),
                                        daemon=True)
                       for n, u in zip(self.names, self.peers)]
            for t in workers:
                t.start()
            for t in workers:
                t.join()
            fresh = {name: fresh[name] for name in self.names}  # stable order
        with self._data_lock:
            self._data = fresh
            self.polls += 1
            self._last_poll = monotonic_s()
        return fresh

    def maybe_poll(self):
        """poll_once() if the cached data is older than `interval_s` (or
        absent). The check-and-claim is serialized so concurrent fleet scrapes
        trigger one peer sweep, not one per scrape, but the sweep itself —
        minutes of network I/O in the worst case — runs OUTSIDE the lock
        (GL019): the winner stamps `_last_poll` up front to claim the
        interval, so racing scrapes return False and serve the cached data
        instead of queueing behind the sweep. Staleness reads the injected
        clock, so ManualClock tests drive re-polls with no sleeps."""
        with self._poll_lock:
            with self._data_lock:
                last = self._last_poll
                if last is not None \
                        and monotonic_s() - last < self.interval_s:
                    return False
                self._last_poll = monotonic_s()   # claim before the sweep
        self.poll_once()
        return True

    def _snapshot(self):
        with self._data_lock:
            return dict(self._data)

    # ---- aggregate views ---------------------------------------------------
    def metrics(self):
        data = self._snapshot()
        instances = {}
        for name, st in data.items():
            if st["status"] != "up":
                instances[name] = {"error": st["error"]}
            elif st.get("metrics") is None:   # up, but /metrics itself failed
                instances[name] = {"error": (st.get("errors") or {})
                                   .get("metrics", "no metrics data")}
            else:
                instances[name] = st["metrics"]
        return {"time": now_s(),
                "instances": instances,
                "instances_up": sum(1 for s in data.values()
                                    if s["status"] == "up"),
                "instances_down": sum(1 for s in data.values()
                                      if s["status"] == "down"),
                "totals": _merge_totals(
                    [st.get("metrics") for st in data.values()
                     if st["status"] == "up"])}

    def prometheus(self):
        """Merged exposition text: every up peer's samples with an
        `instance` label, regrouped BY METRIC FAMILY (OpenMetrics requires
        each family's lines contiguous — naive per-peer concatenation would
        reopen family `requests` after `latency_ms` began and fail strict
        parsers); HELP/TYPE/UNIT keep only the FIRST peer's line per family
        (mixed-version peers may word help text differently, and OpenMetrics
        allows at most one HELP/TYPE/UNIT per family)."""
        families, order = {}, []       # family -> {comments, samples, kinds}

        def block(fam):
            if fam not in families:
                families[fam] = {"comments": [], "samples": [],
                                 "kinds": set()}
                order.append(fam)
            return families[fam]

        for name, st in self._snapshot().items():
            if st["status"] != "up" or not st.get("prometheus"):
                continue
            fam = None
            for line in _relabel_prometheus(st["prometheus"], name):
                if not line or line == "# EOF":
                    continue            # one terminator for the merged doc
                if line.startswith("#"):
                    parts = line.split(None, 3)
                    kind = (parts[1] if len(parts) >= 3 and
                            parts[1] in ("HELP", "TYPE", "UNIT") else None)
                    if kind is not None:
                        fam = parts[2]
                        b = block(fam)
                        if kind not in b["kinds"]:
                            b["kinds"].add(kind)
                            b["comments"].append(line)
                    elif fam is not None and \
                            line not in block(fam)["comments"]:
                        block(fam)["comments"].append(line)
                    continue
                m = _PROM_SAMPLE_RE.match(line)
                sample = m.group(1) if m else line
                if fam is None or not (sample == fam or
                                       sample.startswith(fam + "_")):
                    fam = sample        # comment-less family: its own block
                block(fam)["samples"].append(line)
        lines = []
        for fam in order:
            lines.extend(families[fam]["comments"])
            lines.extend(families[fam]["samples"])
        # the collector's own liveness series, so a scrape can alert on
        # fleet_instances_down without parsing JSON
        data = self._snapshot()
        up = sum(1 for s in data.values() if s["status"] == "up")
        lines.append("# HELP fleet_instances_up Peers answering scrapes")
        lines.append("# TYPE fleet_instances_up gauge")
        lines.append(f"fleet_instances_up {up}")
        lines.append("# HELP fleet_instances_down Peers failing scrapes")
        lines.append("# TYPE fleet_instances_down gauge")
        lines.append(f"fleet_instances_down {len(data) - up}")
        lines.append("# EOF")
        return "\n".join(lines) + "\n"

    def healthz(self):
        """Worst-status aggregation with one component per peer. Down peers
        report `degraded` (never a fleet-level 500/unhealthy: the peer's own
        balancer handles ejection; the fleet view must keep serving)."""
        components, overall = {}, HEALTHY
        for name, st in self._snapshot().items():
            if st["status"] == "down":
                comp = {"status": DEGRADED, "reason": "peer down",
                        "error": st["error"], "url": st["url"]}
            else:
                word = _health_word(st.get("healthz"))
                comp = {"status": word, "url": st["url"],
                        "code": st.get("healthz_code")}
                body = st.get("healthz")
                if isinstance(body, dict) and \
                        isinstance(body.get("components"), dict):
                    # the peer's own component map rides along: an elastic
                    # trainer's membership/iteration probe (or a replica's
                    # batcher/registry detail) is answerable from ONE
                    # /fleet/healthz scrape instead of a per-host hop
                    comp["components"] = body["components"]
            components[name] = comp
            if _RANK[comp["status"]] > _RANK[overall]:
                overall = comp["status"]
        return {"status": overall, "time": now_s(), "components": components}

    def alerts(self):
        """Merged rule lifecycle states, firing first, instance-tagged."""
        rows, firing = [], 0
        instances = {}
        for name, st in self._snapshot().items():
            if st["status"] != "up" or not isinstance(st.get("alerts"), dict):
                instances[name] = {"error": (st.get("errors") or {})
                                   .get("alerts") or st["error"]
                                   or "no alert data"}
                continue
            body = st["alerts"]
            instances[name] = {"firing": body.get("firing", 0)}
            firing += int(body.get("firing", 0) or 0)
            for rule in body.get("rules", []):
                rows.append({**rule, "instance": name})
        order = {"firing": 0, "pending": 1, "inactive": 2}
        rows.sort(key=lambda r: (order.get(r.get("state"), 3),
                                 str(r.get("name")), r["instance"]))
        return {"time": now_s(), "firing": firing, "instances": instances,
                "rules": rows}

    def profile(self):
        """Merged per-executable cost table: every up peer's /profile/cost
        rows with an `instance` field, fleet-sorted by hbm_bytes_per_sample
        (the roofline-dominant axis on v5e) so the most bandwidth-hungry
        executable anywhere in the fleet tops the table; per-instance
        sections keep each peer's own ceilings and full table."""
        rows, instances = [], {}
        for name, st in self._snapshot().items():
            body = st.get("profile")
            if st["status"] != "up" or not isinstance(body, dict):
                instances[name] = {"error": (st.get("errors") or {})
                                   .get("profile") or st["error"]
                                   or "no profile data"}
                continue
            instances[name] = body
            for row in body.get("executables", []):
                if isinstance(row, dict):
                    rows.append({**row, "instance": name})
        rows.sort(key=lambda r: -float(r.get("hbm_bytes_per_sample") or 0.0))
        return {"time": now_s(), "instances": instances,
                "executables": rows}

    def trace(self):
        """Merged Chrome trace: peer i's events move to pid lane i with a
        process_name metadata record, so one ui.perfetto.dev load shows the
        whole fleet host-by-host (cross-host spans of one trace_id still
        correlate through their args)."""
        events, other = [], {}
        data = self._snapshot()
        for lane, name in enumerate(self.names):
            st = data.get(name)
            if st is None or st["status"] != "up" or \
                    not isinstance(st.get("trace"), dict):
                continue
            events.append({"name": "process_name", "ph": "M", "pid": lane,
                           "args": {"name": name}})
            for e in st["trace"].get("traceEvents", []):
                ev = dict(e)
                ev["pid"] = lane
                if ev.get("ph") in ("s", "t", "f") and "id" in ev:
                    # Chrome/Perfetto bind flow events by (cat, id)
                    # GLOBALLY, not per pid: namespace each peer's ids so
                    # host A's request->batch arrow never lands on host B
                    ev["id"] = f"{lane}:{ev['id']}"
                events.append(ev)
            other[name] = st["trace"].get("otherData", {})
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"instances": other}}


class FleetServer(BackgroundHttpServer):
    """HTTP front for a FleetCollector:

      GET /fleet/metrics   JSON aggregate (?format=prometheus for merged
                           instance-labeled exposition text)
      GET /fleet/healthz   worst-status fleet health; 503 only when some
                           peer itself reports unhealthy
      GET /fleet/alerts    merged alert states, firing first
      GET /fleet/trace     merged Chrome trace, one pid lane per host
      GET /fleet/profile   merged per-executable cost table, instance-tagged
      GET /fleet/peers     raw collector status per peer

    Every GET first calls `maybe_poll()` — the interval gate means a
    monitoring stack scraping all four endpoints still sweeps the peers at
    most once per `interval_s`."""

    def __init__(self, peers, names=None, host="127.0.0.1", port=0,
                 interval_s=10.0, timeout_s=2.0, collector=None):
        super().__init__(host=host, port=port)
        self.collector = collector if collector is not None else \
            FleetCollector(peers, names=names, interval_s=interval_s,
                           timeout_s=timeout_s)

    def start(self):
        if self._httpd is not None:
            return self
        collector = self.collector
        from .prometheus import CONTENT_TYPE as PROM_CONTENT_TYPE

        class Handler(QuietHandler):
            def do_GET(self):
                from urllib.parse import parse_qs, urlparse
                u = urlparse(self.path)
                query = {k: v[0] for k, v in parse_qs(u.query).items()}
                try:
                    collector.maybe_poll()
                    if u.path == "/fleet/metrics":
                        if query.get("format") == "prometheus":
                            send_text(self, 200, collector.prometheus(),
                                      content_type=PROM_CONTENT_TYPE)
                        else:
                            send_json(self, 200, collector.metrics(),
                                      default=str)
                    elif u.path == "/fleet/healthz":
                        report = collector.healthz()
                        send_json(self, 503 if report["status"] == UNHEALTHY
                                  else 200, report, default=str)
                    elif u.path == "/fleet/alerts":
                        send_json(self, 200, collector.alerts(), default=str)
                    elif u.path == "/fleet/trace":
                        send_json(self, 200, collector.trace(), default=str)
                    elif u.path == "/fleet/profile":
                        send_json(self, 200, collector.profile(),
                                  default=str)
                    elif u.path == "/fleet/peers":
                        send_json(self, 200, {
                            "peers": {name: {"url": st["url"],
                                             "status": st["status"],
                                             "error": st["error"]}
                                      for name, st in
                                      collector._snapshot().items()},
                            "polls": collector.polls}, default=str)
                    else:
                        send_json(self, 404, {"error": "not found"})
                except Exception as e:   # aggregation must never drop a scrape
                    send_json(self, 500,
                              {"error": f"{type(e).__name__}: {e}"})

        return self.start_with(Handler)
