"""Unified telemetry subsystem (SURVEY.md §5: the reference stack has *no
tracer* — this is the observability layer the north-star production system
runs on).

Four cooperating parts, one import surface:

- `trace` — structured tracing: `Tracer` producing nested `Span`s with
  ids/attributes, a thread-local current-span context propagated through the
  serving hot path (admission -> micro-batch coalesce -> registry dispatch
  -> model step) and training (epoch -> iteration -> jit step), exportable
  as Chrome-trace/Perfetto JSON.
- `registry` — central `MetricsRegistry`: thread-safe counters, gauges, and
  bounded histograms with exact-bucket percentiles; ServingMetrics, the
  training listeners, and streaming all register here instead of keeping
  private state.
- `prometheus` — text exposition (`/metrics?format=prometheus` on the
  ServingServer and the UI server).
- `xla` — compile/recompile cost accounting (`compiles_total`,
  `compile_ms_total`, per-bucket compile counts) and device-memory gauges,
  per the compile-vs-run accounting of the Julia-to-TPU paper (PAPERS.md).

`TelemetryListener` flushes the registry into the existing ui/storage
router tier so the UI can tail live metrics like training stats.

The health & alerting tier sits on top and closes observe -> detect ->
react:

- `logging` — structured JSON log records with automatic trace/span-id
  correlation, a bounded ring buffer (`GET /logs`), pluggable sinks, and
  `log_events_total{level}`.
- `health` — `HealthMonitor` aggregating per-component probes (batcher,
  registry, admission queue, ETL pipelines, trainer) into a deep `/healthz`
  that answers 503 when any component is unhealthy.
- `alerts` — `AlertEngine` evaluating declarative threshold / ratio /
  SLO-burn-rate rules over the registry on a ManualClock-testable interval,
  with a pending -> firing -> resolved lifecycle and log/webhook/router
  sinks (`GET /alerts`); `optimize.listeners.TrainingHealthListener` is the
  training watchdog feeding it (NaN loss/gradients, divergence, step-time
  regression) and the checkpoint-and-halt trigger for FaultTolerantTrainer.

The fleet tier makes every signal above cross-process:

- `propagation` — W3C `traceparent` inject/extract (`SpanContext`): the
  util/http clients inject the active span's context, server handlers and
  broker messages extract it, so one request is ONE trace across hosts;
  span/trace ids are collision-free random hex (kernel CSPRNG).
- `fleet` — `FleetCollector`/`FleetServer`: poll N peer base-URLs and
  aggregate `GET /fleet/{metrics,healthz,alerts,trace}` (per-`instance`
  labels + merged totals, worst-status health with down-peers-as-degraded,
  one Chrome-trace `pid` lane per host).
- Histograms carry bounded `(value, trace_id)` exemplars, rendered as
  OpenMetrics exemplars in the Prometheus exposition and attached to firing
  alert events — the alert → trace → logs pivot.

The ETL subsystem (deeplearning4j_tpu/etl) instruments through this layer
too: per-stage spans (etl_read/etl_transform), `etl_batches_total` /
`etl_records_total`, the `etl_queue_depth` gauge, and the
`etl_consumer_wait_ms` histogram — the device-starvation signal (prefetch
working = consumer wait ~0).
"""
from .alerts import (AlertEngine, AlertRule, LogAlertSink, RouterAlertSink,
                     WebhookAlertSink, default_serving_rules,
                     default_training_rules)
from .cost import (ExecutableCostRegistry, abstractify, capture_trace,
                   classify, compiled_costs, get_cost_registry,
                   install_donation_watch, set_cost_registry)
from .fleet import FleetCollector, FleetServer
from .health import (DEGRADED, HEALTHY, UNHEALTHY, HealthMonitor,
                     get_monitor, set_monitor)
from .listener import TelemetryListener, TelemetryReport
from .logging import (FileJsonSink, LogBuffer, StderrJsonSink,
                      StructuredLogger, get_logger, set_logger)
from .prometheus import CONTENT_TYPE as PROMETHEUS_CONTENT_TYPE
from .prometheus import render as render_prometheus
from .propagation import (SpanContext, extract, extract_message,
                          format_traceparent, inject, inject_message,
                          parse_traceparent)
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       get_registry)
from .trace import (NOOP_SPAN, Span, Tracer, current_span, enable_tracing,
                    get_tracer, new_span_id, new_trace_id, set_tracer)
from .xla import (CompileTracker, record_jit_compile,
                  register_device_memory_gauges, timed_first_call)

__all__ = ["AlertEngine", "AlertRule", "LogAlertSink", "RouterAlertSink",
           "WebhookAlertSink", "default_serving_rules",
           "default_training_rules",
           "FleetCollector", "FleetServer",
           "DEGRADED", "HEALTHY", "UNHEALTHY", "HealthMonitor",
           "get_monitor", "set_monitor",
           "FileJsonSink", "LogBuffer", "StderrJsonSink", "StructuredLogger",
           "get_logger", "set_logger",
           "TelemetryListener", "TelemetryReport",
           "PROMETHEUS_CONTENT_TYPE", "render_prometheus",
           "SpanContext", "extract", "extract_message", "format_traceparent",
           "inject", "inject_message", "parse_traceparent",
           "Counter", "Gauge", "Histogram", "MetricsRegistry",
           "get_registry",
           "NOOP_SPAN", "Span", "Tracer", "current_span", "enable_tracing",
           "get_tracer", "new_span_id", "new_trace_id", "set_tracer",
           "CompileTracker", "record_jit_compile",
           "register_device_memory_gauges", "timed_first_call",
           "ExecutableCostRegistry", "abstractify", "capture_trace",
           "classify", "compiled_costs", "get_cost_registry",
           "install_donation_watch", "set_cost_registry"]
