"""Structured JSON logging with automatic trace correlation.

Third telemetry tier (after spans and metrics): every log record is a plain
dict carrying wall time, level, logger name, message, free-form fields, and
— when the calling thread is inside a `Tracer` span — the current
trace_id/span_id, so a `/logs` line can be joined against the `/trace`
export without any manual plumbing (the operator greps one id across both).

Records land in a bounded in-memory ring buffer (served at `GET /logs` on
the ServingServer and the UI server) and fan out to pluggable sinks
(stderr JSON-lines, append-to-file, or anything callable). Every record
also increments `log_events_total{level}` in a MetricsRegistry, which makes
"error logs per second" an alertable series like any other counter.

Timestamps come from util/time_source, so a ManualClock makes log tests
deterministic; sink failures are swallowed (counted on the logger) — an
observability tier must never take down the path it observes.
"""
from __future__ import annotations

import sys
import threading

from .trace import current_span
from ..util.time_source import now_s


def _dumps(record):
    """Strict JSON line for a record: non-finite floats (e.g. a logged NaN
    loss) become null so every emitted line stays machine-parseable."""
    from ..util.http import dumps_safe
    return dumps_safe(record, default=str)

LEVELS = ("debug", "info", "warning", "error")
_RANK = {name: i for i, name in enumerate(LEVELS)}


def level_rank(level):
    """Numeric severity for a level name (unknown names rank as error)."""
    return _RANK.get(str(level).lower(), _RANK["error"])


class LogBuffer:
    """Bounded most-recent ring of log record dicts."""

    def __init__(self, capacity=2048):
        self.capacity = max(1, int(capacity))
        self._items = []
        self._lock = threading.Lock()
        self.dropped = 0          # records evicted by the ring bound
        self.total = 0            # records ever appended

    def append(self, record):
        with self._lock:
            self._items.append(record)
            self.total += 1
            if len(self._items) > self.capacity:
                del self._items[:len(self._items) - self.capacity]
                self.dropped += 1

    def records(self, level=None, n=None, trace_id=None):
        """Most-recent records, oldest first. `level` is a minimum severity;
        `trace_id` filters to one request/iteration's records."""
        with self._lock:
            out = list(self._items)
        if level is not None:
            floor = level_rank(level)
            out = [r for r in out if level_rank(r["level"]) >= floor]
        if trace_id is not None:
            # trace ids are W3C hex strings (telemetry.trace.new_trace_id);
            # string compare so /logs?trace_id=<hex> joins against /trace
            want = str(trace_id)
            out = [r for r in out if str(r.get("trace_id")) == want]
        if n is not None:
            n = int(n)
            out = out[-n:] if n > 0 else []   # -0 would slice the WHOLE list
        return out

    def to_dict(self, level=None, n=None, trace_id=None):
        records = self.records(level=level, n=n, trace_id=trace_id)
        with self._lock:    # counters move with _items; snapshot under lock
            total, dropped = self.total, self.dropped
        return {"records": records, "count": total, "dropped": dropped,
                "capacity": self.capacity}

    def clear(self):
        with self._lock:
            self._items = []
            self.dropped = 0


class StderrJsonSink:
    """One JSON line per record to stderr (or any text stream)."""

    def __init__(self, stream=None):
        self.stream = stream

    def __call__(self, record):
        stream = self.stream if self.stream is not None else sys.stderr
        stream.write(_dumps(record) + "\n")


class FileJsonSink:
    """Append-a-JSON-line-per-record file sink (JSONL, like ui/storage's
    FileStatsStorage log)."""

    def __init__(self, path):
        self.path = str(path)
        self._fh = open(self.path, "a")
        self._lock = threading.Lock()

    def __call__(self, record):
        with self._lock:
            if self._fh.closed:
                return
            self._fh.write(_dumps(record) + "\n")
            self._fh.flush()

    def close(self):
        with self._lock:
            self._fh.close()


class StructuredLogger:
    """Producer of structured records: ring buffer + sinks + level counter.

    `logger.info("deploy", version="v2")` appends
    `{"time", "level", "logger", "message", "trace_id", "span_id",
      "fields": {"version": "v2"}}` — trace/span ids resolved from the
    thread-current span at call time.
    """

    def __init__(self, name="root", buffer=None, sinks=None, registry=None,
                 level="debug"):
        self.name = str(name)
        self.buffer = buffer if buffer is not None else LogBuffer()
        self.sinks = list(sinks or [])
        if registry is None:
            from .registry import get_registry
            registry = get_registry()
        self.registry = registry
        self._counter = registry.counter(
            "log_events_total", "Structured log records by level")
        self._floor = level_rank(level)
        self.sink_errors = 0

    def set_level(self, level):
        self._floor = level_rank(level)

    def add_sink(self, sink):
        self.sinks.append(sink)
        return sink

    def child(self, name):
        """A logger sharing this one's buffer/sinks/counter under a
        dotted name (`serving.batcher`)."""
        c = StructuredLogger.__new__(StructuredLogger)
        c.name = f"{self.name}.{name}"
        c.buffer = self.buffer
        c.sinks = self.sinks           # shared on purpose
        c.registry = self.registry
        c._counter = self._counter
        c._floor = self._floor
        c.sink_errors = 0
        return c

    # ---- producing ---------------------------------------------------------
    def log(self, level, message, **fields):
        level = str(level).lower()
        if level_rank(level) < self._floor:
            return None
        record = {"time": now_s(), "level": level, "logger": self.name,
                  "message": str(message)}
        span = current_span()
        if span is not None and span.trace_id is not None:
            record["trace_id"] = span.trace_id
            record["span_id"] = span.span_id
        if fields:
            record["fields"] = fields
        self._counter.inc(1, level=level)
        self.buffer.append(record)
        for sink in self.sinks:
            try:
                sink(record)
            except Exception:
                self.sink_errors += 1   # a dead sink must not kill the caller
        return record

    def debug(self, message, **fields):
        return self.log("debug", message, **fields)

    def info(self, message, **fields):
        return self.log("info", message, **fields)

    def warning(self, message, **fields):
        return self.log("warning", message, **fields)

    def error(self, message, **fields):
        return self.log("error", message, **fields)


# ---- process-default logger -------------------------------------------------
_default_logger = None
_default_lock = threading.Lock()


def get_logger() -> StructuredLogger:
    """Process-default logger (lazy: instruments register into the default
    MetricsRegistry on first use, not at import)."""
    global _default_logger
    with _default_lock:
        if _default_logger is None:
            _default_logger = StructuredLogger(name="root")
        return _default_logger


def set_logger(logger) -> StructuredLogger:
    global _default_logger
    with _default_lock:
        _default_logger = logger
    return logger
