"""Structured tracing: nested spans with ids/attributes, cross-thread
context propagation, Chrome-trace/Perfetto JSON export.

The reference DL4J stack has *no tracer* (SURVEY.md §5 — its only
observability is the StatsListener/UI path); this is the TPU analog of the
per-kernel timing discipline in the cuDNN paper and the compile-vs-run
accounting of the Julia-to-TPU paper (PAPERS.md): every serving request and
training step becomes a span tree you can load into chrome://tracing or
ui.perfetto.dev.

Design notes:
- The *current span* is a module-level thread-local shared by every Tracer,
  so code that only wants to parent under "whatever is active here" (e.g.
  admission capturing the handler's request span) needs no tracer handle.
- Cross-thread propagation is explicit: a producer stores `tracer.current()`
  on its work item; the consumer passes it as `parent=`. That is how the
  serving hot path threads one request context through
  admission -> batcher coalesce -> registry dispatch -> model step.
- `record_span` creates spans retroactively from (start, end) monotonic
  timestamps already measured elsewhere (e.g. queue wait), so instrumenting
  an existing timing never means timing it twice.
- Clocks come from util/time_source (monotonic for durations, wall for the
  trace epoch), so a ManualClock makes span tests deterministic.
- Ids are W3C-sized random hex (128-bit trace / 64-bit span) from the
  kernel CSPRNG — collision-free across threads, forks, and hosts, and
  directly usable in `traceparent` headers (telemetry/propagation.py).
  `parent=` accepts any object with .trace_id/.span_id, including a remote
  SpanContext extracted from an inbound header.
- Spans can also LINK to other spans (`add_link`) — the batch<->request
  association without a parent edge; links export as Chrome-trace flow
  events.
"""
from __future__ import annotations

import collections
import json
import os
import threading

from ..util.time_source import monotonic_s, now_s

_tls = threading.local()          # .span: innermost active Span, any tracer


def new_trace_id() -> str:
    """W3C-sized 128-bit trace id as 32 lowercase hex chars. os.urandom reads
    the kernel CSPRNG, so ids never collide across forked/parallel processes
    (the old process-local itertools.count restarted at 1 in every process —
    two hosts' traces merged into one indistinguishable id space)."""
    return os.urandom(16).hex()


def new_span_id() -> str:
    """W3C-sized 64-bit span id as 16 lowercase hex chars."""
    return os.urandom(8).hex()


def current_span():
    """The innermost active span on THIS thread (any tracer), or None."""
    return getattr(_tls, "span", None)


class Span:
    """One timed operation. Use as a context manager (via Tracer.span) or
    end() it manually for cross-thread lifetimes."""

    __slots__ = ("tracer", "name", "trace_id", "span_id", "parent_id",
                 "attributes", "links", "start_mono", "end_mono", "_prev",
                 "_on_stack")

    def __init__(self, tracer, name, parent=None, attributes=None,
                 start_mono=None):
        self.tracer = tracer
        self.name = str(name)
        self.span_id = new_span_id()
        if parent is not None and parent.trace_id is not None:
            # `parent` may be a local Span or a remote SpanContext extracted
            # from a traceparent header — only .trace_id/.span_id are read
            self.trace_id = parent.trace_id
            self.parent_id = parent.span_id
        else:
            self.trace_id = new_trace_id()
            self.parent_id = None
        self.attributes = dict(attributes or {})
        self.links = []
        self.start_mono = monotonic_s() if start_mono is None else start_mono
        self.end_mono = None
        self._prev = None
        self._on_stack = False

    def set_attribute(self, key, value):
        self.attributes[str(key)] = value
        return self

    def add_link(self, ctx):
        """Record a LINK to another span (batch<->request association without
        a parent edge: the linked span stays the root of its own trace).
        `ctx` is anything with .trace_id/.span_id (Span, SpanContext); a
        None/contextless ctx is ignored so callers never need to guard."""
        if ctx is not None and getattr(ctx, "trace_id", None) is not None:
            self.links.append({"trace_id": ctx.trace_id,
                               "span_id": ctx.span_id})
        return self

    @property
    def duration_ms(self):
        if self.end_mono is None:
            return None
        return (self.end_mono - self.start_mono) * 1000.0

    def end(self, end_mono=None):
        if self.end_mono is not None:
            return self              # idempotent
        self.end_mono = monotonic_s() if end_mono is None else end_mono
        if self._on_stack and current_span() is self:
            _tls.span = self._prev
            self._on_stack = False
        self.tracer._finish(self)
        return self

    # context-manager protocol (entered spans also become thread-current)
    def __enter__(self):
        if not self._on_stack:
            self._prev = current_span()
            _tls.span = self
            self._on_stack = True
        return self

    def __exit__(self, exc_type, exc, tb):
        if exc_type is not None:
            self.attributes.setdefault("error", exc_type.__name__)
        self.end()
        return False

    def to_dict(self):
        d = {"name": self.name, "trace_id": self.trace_id,
             "span_id": self.span_id, "parent_id": self.parent_id,
             "start_ms": round((self.start_mono - self.tracer.epoch_mono)
                               * 1000.0, 3),
             "duration_ms": None if self.duration_ms is None
             else round(self.duration_ms, 3),
             "attributes": dict(self.attributes)}
        if self.links:
            d["links"] = [dict(l) for l in self.links]
        return d


class _NoopSpan:
    """Shared do-nothing span for disabled tracers: the hot path pays one
    attribute check, not an allocation."""

    __slots__ = ()
    trace_id = span_id = parent_id = None
    name = ""
    attributes = {}
    links = ()

    def set_attribute(self, key, value):
        return self

    def add_link(self, ctx):
        return self

    def end(self, end_mono=None):
        return self

    def __enter__(self):
        return self

    def __exit__(self, *a):
        return False


NOOP_SPAN = _NoopSpan()


class Tracer:
    """Produces spans and keeps the most recent `max_spans` finished ones in
    a bounded ring buffer for export."""

    def __init__(self, enabled=True, max_spans=8192):
        self.enabled = bool(enabled)
        self.max_spans = int(max_spans)
        self._finished = collections.deque(maxlen=self.max_spans)
        self._lock = threading.Lock()
        self.epoch_mono = monotonic_s()
        self.epoch_wall = now_s()
        self.dropped = 0

    # ---- producing ---------------------------------------------------------
    def span(self, name, parent=None, **attributes):
        """Context-manager span. With no explicit `parent`, nests under the
        thread-current span (of any tracer)."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is None:
            parent = current_span()
        if parent is NOOP_SPAN:
            parent = None
        return Span(self, name, parent=parent, attributes=attributes)

    def start_span(self, name, parent=None, **attributes):
        """Manually-ended span for cross-thread lifetimes. Does NOT become
        thread-current (enter it with `with` if you want nesting)."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is NOOP_SPAN:
            parent = None
        return Span(self, name, parent=parent, attributes=attributes)

    def record_span(self, name, start_mono, end_mono, parent=None,
                    **attributes):
        """Record an already-measured interval as a finished span."""
        if not self.enabled:
            return NOOP_SPAN
        if parent is NOOP_SPAN:
            parent = None
        s = Span(self, name, parent=parent, attributes=attributes,
                 start_mono=start_mono)
        s.end(end_mono)
        return s

    def current(self):
        """Thread-current span (shared across tracers), or None."""
        return current_span()

    def _finish(self, span):
        with self._lock:
            if len(self._finished) == self._finished.maxlen:
                self.dropped += 1
            self._finished.append(span)

    # ---- exporting ---------------------------------------------------------
    def finished_spans(self):
        with self._lock:
            return list(self._finished)

    def clear(self):
        with self._lock:
            self._finished.clear()
            self.dropped = 0

    def to_chrome_trace(self):
        """Chrome-trace ("traceEvents") dict: complete ("X") events with
        microsecond timestamps relative to the tracer epoch. Loadable by
        chrome://tracing and ui.perfetto.dev; span/parent ids ride in args
        so the tree survives the flat event encoding. Trace ids are random
        hex, so each distinct trace is assigned a small integer `tid` lane
        at export time (chrome's tid must be numeric); span LINKS export as
        flow-event pairs (ph "s"/"f") connecting the linked span's slice to
        the linking span's slice across lanes."""
        spans = self.finished_spans()
        with self._lock:               # dropped moves with _finished
            dropped = self.dropped
        lanes = {}                     # trace_id -> small int lane
        events = []
        by_span_id = {}
        for s in spans:
            by_span_id[s.span_id] = s
            lane = lanes.setdefault(s.trace_id, len(lanes) + 1)
            events.append({
                "name": s.name,
                "ph": "X",
                "ts": round((s.start_mono - self.epoch_mono) * 1e6, 1),
                "dur": round(((s.end_mono or s.start_mono) - s.start_mono)
                             * 1e6, 1),
                "pid": 0,
                "tid": lane,
                "args": {"span_id": s.span_id, "parent_id": s.parent_id,
                         "trace_id": s.trace_id, **s.attributes},
            })
        flow_n = 0
        for s in spans:
            for link in s.links:
                src = by_span_id.get(link["span_id"])
                if src is None:        # linked span evicted or remote: skip
                    continue
                flow_n += 1
                common = {"cat": "link", "name": "link", "id": flow_n,
                          "pid": 0}
                events.append({**common, "ph": "s", "tid": lanes[src.trace_id],
                               "ts": round((src.start_mono - self.epoch_mono)
                                           * 1e6, 1),
                               "args": {"span_id": src.span_id}})
                events.append({**common, "ph": "f", "bp": "e",
                               "tid": lanes[s.trace_id],
                               "ts": round((s.start_mono - self.epoch_mono)
                                           * 1e6, 1),
                               "args": {"span_id": s.span_id}})
        return {"traceEvents": events, "displayTimeUnit": "ms",
                "otherData": {"epoch_wall_s": self.epoch_wall,
                              "dropped_spans": dropped}}

    def export(self, path):
        """Write the Chrome-trace JSON to `path`; returns the path."""
        with open(path, "w") as fh:
            json.dump(self.to_chrome_trace(), fh)
        return path


# ---- process-default tracer -------------------------------------------------
# Disabled by default: training hot loops call get_tracer().span(...) per
# iteration and must pay a no-op, not an allocation, until someone opts in.

_default_tracer = Tracer(enabled=False)
_default_lock = threading.Lock()


def get_tracer() -> Tracer:
    return _default_tracer


def set_tracer(tracer) -> Tracer:
    global _default_tracer
    with _default_lock:
        _default_tracer = tracer
    return tracer


def enable_tracing(max_spans=8192) -> Tracer:
    """Switch the process-default tracer on IN PLACE (idempotent) and return
    it. Mutating the existing instance matters: components capture
    get_tracer() at construction time (e.g. a DynamicBatcher built before
    tracing was enabled), and swapping in a new object would leave them
    recording into a permanently-disabled tracer."""
    with _default_lock:
        t = _default_tracer
        if int(max_spans) != t.max_spans:
            t.max_spans = int(max_spans)
            with t._lock:
                t._finished = collections.deque(t._finished,
                                                maxlen=t.max_spans)
        t.enabled = True
        return t
