"""XLA cost accounting: compile/recompile tracking and device-memory gauges.

Following the Julia-to-TPU paper's central observation (PAPERS.md), compile
time is THE dominant hidden cost of an XLA-backed serving/training stack: a
shape the jit cache has not seen stalls the request that triggers it for
orders of magnitude longer than a steady-state dispatch. This module gives
that cost first-class metrics:

- `CompileTracker` wraps the jit-cache path the micro-batcher already
  tracks (its `observed` (signature, bucket) set): the first dispatch of a
  new bucket is the compile, and its wall time is attributed to
  `compile_ms_total` with a per-bucket labeled `compiles_total`.
- `timed_first_call` wraps a freshly-jitted callable so its first invocation
  (which triggers XLA compilation) is timed and counted in the process
  registry — the training-side (`network._jit_cache`) analog.
- `register_device_memory_gauges` installs callback gauges that read
  `jax.local_devices()[i].memory_stats()` at scrape time (periodic by virtue
  of the scraper's cadence; zero cost between scrapes).
"""
from __future__ import annotations

from .registry import get_registry
from ..util.time_source import monotonic_s


class CompileTracker:
    """Counts XLA (re)compiles and accumulates compile wall-time into a
    MetricsRegistry. One instance per serving stack, sharing the stack's
    registry so `/metrics` exposes `compiles_total` next to request counts."""

    def __init__(self, registry=None, prefix=""):
        self.registry = registry if registry is not None else get_registry()
        p = prefix
        self.compiles = self.registry.counter(
            p + "compiles_total",
            "XLA executable compiles, labeled by padded batch bucket")
        self.compile_ms = self.registry.counter(
            p + "compile_ms_total",
            "Wall milliseconds spent in XLA compiles (first-dispatch proxy)")
        self.compiles.inc(0)
        self.compile_ms.inc(0)

    def record(self, ms, bucket=None, **labels):
        """Record one compile of `ms` wall-milliseconds. The measured first
        dispatch includes one steady-state execution — an upper bound, same
        proxy the Julia-TPU paper reports as compile+first-run."""
        if bucket is not None:
            labels["bucket"] = str(bucket)
        self.compiles.inc(1, **labels)
        self.compile_ms.inc(ms)

    def total(self):
        return self.compiles.get()

    def total_ms(self):
        return self.compile_ms.get()

    def by_bucket(self):
        return {ls.get("bucket", ""): v for ls, v in self.compiles.series()
                if ls}


def record_jit_compile(label, ms, registry=None):
    """Count one training-side jit-cache compile in the (default) registry."""
    reg = registry if registry is not None else get_registry()
    reg.counter("jit_compiles_total",
                "jit-cache misses (new executables), labeled by fn"
                ).inc(1, fn=str(label))
    reg.counter("jit_compile_ms_total",
                "Wall ms spent compiling jit-cache entries "
                "(first-call proxy)").inc(ms)


class _TimedFirstCall:
    """Callable proxy timing only the FIRST invocation (where XLA actually
    compiles). Attribute access (e.g. jax's `_cache_size`) passes through to
    the wrapped jitted callable."""

    __slots__ = ("__wrapped__", "_label", "_registry", "_first")

    def __init__(self, fn, label, registry):
        self.__wrapped__ = fn
        self._label = label
        self._registry = registry
        self._first = True

    def __call__(self, *args, **kwargs):
        if self._first:
            self._first = False
            # Abstract-arg snapshot BEFORE the call: donated buffers are
            # invalidated by it, and cost capture re-lowers from shapes only.
            from .cost import abstractify, get_cost_registry
            cost = get_cost_registry()
            if cost is not None:
                try:
                    abs_args = abstractify(args)
                    abs_kwargs = abstractify(kwargs)
                except Exception:
                    cost = None
            t0 = monotonic_s()
            out = self.__wrapped__(*args, **kwargs)
            record_jit_compile(self._label, (monotonic_s() - t0) * 1000.0,
                               registry=self._registry)
            if cost is not None:
                cost.capture(self._label, self.__wrapped__,
                             abs_args, abs_kwargs)
            return out
        return self.__wrapped__(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self.__wrapped__, name)


def timed_first_call(fn, label, registry=None):
    """Wrap a jitted callable so its FIRST call (where XLA actually
    compiles) is timed and counted via `record_jit_compile`. Later calls
    pay one boolean check. Only the first shape's compile is attributed;
    per-shape recompiles inside jax's own cache stay invisible here (the
    serving path counts those per-bucket via CompileTracker instead)."""
    return _TimedFirstCall(fn, label, registry)


def register_device_memory_gauges(registry=None):
    """Install `device_memory_bytes_in_use` / `..._peak` callback gauges
    reading jax device memory stats at scrape time. Safe everywhere: on
    backends without memory_stats (CPU) the callbacks return {} and the
    gauges render no samples."""
    reg = registry if registry is not None else get_registry()

    def _read(key):
        def fn():
            try:
                import jax
                out = {}
                for d in jax.local_devices():
                    ms = d.memory_stats()
                    if ms and key in ms:
                        out[f"{d.platform}:{d.id}"] = float(ms[key])
                return out
            except Exception:
                return {}
        return fn

    g1 = reg.gauge("device_memory_bytes_in_use",
                   "Per-device bytes currently allocated (jax memory_stats)",
                   fn=_read("bytes_in_use"))
    g2 = reg.gauge("device_memory_peak_bytes",
                   "Per-device peak bytes allocated (jax memory_stats)",
                   fn=_read("peak_bytes_in_use"))
    g1.fn_label = g2.fn_label = "device"
    return g1, g2
