"""AlertEngine: declarative rules over the MetricsRegistry with a
pending -> firing -> resolved lifecycle.

This is the piece that closes observe -> detect -> react: PR 2's registry
records p99 latency, error counters, shed counts, and ETL starvation, but
nothing watched them. An `AlertRule` declares a condition over registry
instruments; the engine evaluates all rules on an interval (or on demand —
every timestamp comes from util/time_source, so ManualClock tests drive the
whole lifecycle with zero wall-clock sleeps) and pushes each firing/resolved
transition to sinks exactly once.

Rule kinds (all JSON-round-trippable via to_dict/from_dict):

- `threshold` — instantaneous value vs a bound: a gauge or counter's value,
  or a histogram percentile (`metric="latency_ms", percentile=0.99`).
- `ratio` — windowed counter-delta ratio, e.g. errors_total/requests_total
  over the last `window_s`. The denominator may be a list of counters
  (summed), so a true shed ratio is `shed/(requests+shed)`.
- `burn_rate` — multiwindow-style SLO burn: the ratio's windowed error rate
  divided by the SLO's error budget (`1 - slo`); `threshold` is the burn
  factor (14.4 ~ "exhausting a 30-day budget in 2 days").

Lifecycle per rule: inactive -> (condition true) pending -> (held for
`for_duration_s`) firing -> (condition false) resolved -> inactive.
Pending that recovers before `for_duration_s` never notifies — that is the
flap damping. Counter history for windowed rules is sampled at evaluation
time, so the engine needs no hooks inside the instruments.
"""
from __future__ import annotations

import threading

from ..util.time_source import monotonic_s, now_s

INACTIVE, PENDING, FIRING = "inactive", "pending", "firing"
_OPS = {
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
}


def _as_names(spec):
    """Metric spec -> tuple of names (a str or a list of summed counters)."""
    if spec is None:
        return ()
    if isinstance(spec, str):
        return (spec,)
    return tuple(str(s) for s in spec)


class AlertRule:
    """One declarative condition + its lifecycle state."""

    KINDS = ("threshold", "ratio", "burn_rate")

    def __init__(self, name, kind="threshold", *, metric=None, percentile=None,
                 labels=None, op=">", threshold=None, numerator=None,
                 denominator=None, window_s=60.0, slo=None,
                 for_duration_s=0.0, severity="warning", description=""):
        self.name = str(name)
        self.kind = str(kind)
        if self.kind not in self.KINDS:
            raise ValueError(f"unknown rule kind {kind!r}")
        if op not in _OPS:
            raise ValueError(f"unknown op {op!r}")
        if threshold is None:
            raise ValueError(f"rule {name!r} needs a threshold")
        if self.kind == "threshold" and not metric:
            raise ValueError(f"threshold rule {name!r} needs `metric`")
        if self.kind in ("ratio", "burn_rate") and \
                (not numerator or not denominator):
            raise ValueError(
                f"{self.kind} rule {name!r} needs numerator+denominator")
        if self.kind == "burn_rate":
            if slo is None or not (0.0 < float(slo) < 1.0):
                raise ValueError(
                    f"burn_rate rule {name!r} needs 0 < slo < 1")
            self.slo = float(slo)
        else:
            self.slo = None
        self.metric = metric
        self.percentile = None if percentile is None else float(percentile)
        self.labels = dict(labels or {})
        self.op = op
        self.threshold = float(threshold)
        self.numerator = _as_names(numerator)
        self.denominator = _as_names(denominator)
        self.window_s = float(window_s)
        self.for_duration_s = float(for_duration_s)
        self.severity = str(severity)
        self.description = str(description)
        # lifecycle state (engine-managed)
        self.state = INACTIVE
        self.pending_since = None      # monotonic_s of condition onset
        self.firing_since = None       # wall now_s when it fired
        self.last_value = None
        self.transitions = 0           # firing/resolved notifications sent

    # ---- declarative round-trip -------------------------------------------
    def to_dict(self):
        d = {"name": self.name, "kind": self.kind, "op": self.op,
             "threshold": self.threshold, "severity": self.severity,
             "for_duration_s": self.for_duration_s,
             "description": self.description}
        if self.labels:
            # labels scope ANY kind: a threshold on one label-set, or a
            # ratio/burn_rate over one cohort's counters (the canary case)
            d["labels"] = dict(self.labels)
        if self.kind == "threshold":
            d["metric"] = self.metric
            if self.percentile is not None:
                d["percentile"] = self.percentile
        else:
            d["numerator"] = list(self.numerator)
            d["denominator"] = list(self.denominator)
            d["window_s"] = self.window_s
            if self.slo is not None:
                d["slo"] = self.slo
        return d

    @classmethod
    def from_dict(cls, d):
        d = dict(d)
        return cls(d.pop("name"), d.pop("kind", "threshold"), **d)

    def status(self):
        """JSON state row for GET /alerts."""
        return {**self.to_dict(), "state": self.state,
                "value": self.last_value, "firing_since": self.firing_since,
                "transitions": self.transitions}


def _instrument_value(registry, name, percentile=None, labels=None):
    """Instantaneous value of one instrument, or None when absent/empty."""
    m = registry.get(name)
    if m is None:
        return None
    labels = labels or {}
    if m.kind == "histogram":
        q = 0.99 if percentile is None else percentile
        if labels:
            return m.percentile(q, **labels)
        # no labels named: aggregate across every label-set, so a rule like
        # etl_consumer_starvation sees pipeline=<name> observations too
        return m.percentile_merged(q)
    v = m.get(**labels)
    if isinstance(v, dict):            # fn-gauge returning {label: value}
        return None
    return v


class AlertEngine:
    """Evaluates rules against one MetricsRegistry; notifies sinks on
    firing/resolved transitions; optionally runs on a background interval."""

    def __init__(self, registry=None, rules=None, sinks=None, interval_s=5.0,
                 logger=None):
        if registry is None:
            from .registry import get_registry
            registry = get_registry()
        self.registry = registry
        self.rules = []
        self.sinks = list(sinks or [])
        self.interval_s = float(interval_s)
        self.logger = logger
        self._history = {}             # counter name -> [(mono_t, value)]
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        for r in (rules or []):
            self.add_rule(r)

    # ---- configuration -----------------------------------------------------
    def add_rule(self, rule):
        if isinstance(rule, dict):
            rule = AlertRule.from_dict(rule)
        with self._lock:
            old = [r for r in self.rules if r.name == rule.name]
            self.rules = [r for r in self.rules if r.name != rule.name]
            self.rules.append(rule)
        self._resolve_displaced(old)
        return rule

    def remove_rule(self, name):
        with self._lock:
            old = [r for r in self.rules if r.name == name]
            self.rules = [r for r in self.rules if r.name != name]
        self._resolve_displaced(old)

    def drop_history(self, names, labels=None):
        """Forget the windowed samples for `names` under the given label
        scope. Counter history outlives rules (so a re-added long-lived rule
        keeps its window), which means a windowed rule re-added over a
        REUSED label-set — back-to-back canary cohorts — would otherwise see
        the previous occupant's deltas in its window and could fire on
        traffic the new deploy never served."""
        lk = tuple(sorted((labels or {}).items()))
        with self._lock:
            for name in names:
                self._history.pop((name, lk), None)

    def _resolve_displaced(self, old_rules):
        """A FIRING rule that is replaced/removed must still resolve: its
        receiver (pager, Alertmanager) has an open incident keyed on the
        firing event and would otherwise never see it close."""
        for r in old_rules:
            if r.state == FIRING:
                self._notify(self._event(r, "resolved", r.last_value))
                r.state = INACTIVE

    def add_sink(self, sink):
        self.sinks.append(sink)
        return sink

    # ---- evaluation --------------------------------------------------------
    def _sample_counters(self, now):
        """Record current totals for every windowed rule's counters (per the
        rule's label scope — a labeled rule windows one label-set's series,
        an unlabeled one the summed total) and prune history past the
        largest window."""
        with self._lock:
            rules = list(self.rules)
        keys, max_window = set(), 0.0
        for r in rules:
            if r.kind in ("ratio", "burn_rate"):
                lk = tuple(sorted(r.labels.items()))
                keys.update((n, lk) for n in r.numerator)
                keys.update((n, lk) for n in r.denominator)
                max_window = max(max_window, r.window_s)
        for name, lk in keys:
            v = _instrument_value(self.registry, name, labels=dict(lk))
            hist = self._history.setdefault((name, lk), [])
            hist.append((now, 0.0 if v is None else float(v)))
            # keep one sample at-or-before the window edge as the baseline
            cut = now - max_window
            while len(hist) >= 2 and hist[1][0] <= cut:
                hist.pop(0)

    def _window_delta(self, names, window_s, now, labels=None):
        """Sum of counter increases over the last `window_s` (baseline = the
        newest sample at-or-before the window edge, else the oldest known —
        so a counter that was already nonzero at engine start never reads as
        a burst)."""
        lk = tuple(sorted((labels or {}).items()))
        total = 0.0
        for name in names:
            hist = self._history.get((name, lk))
            if not hist:
                return None
            base = hist[0][1]
            for t, v in hist:
                if t <= now - window_s:
                    base = v
                else:
                    break
            total += hist[-1][1] - base
        return total

    def _condition(self, rule, now):
        """(condition_bool, observed_value) — condition is False on no-data."""
        if rule.kind == "threshold":
            v = _instrument_value(self.registry, rule.metric,
                                  percentile=rule.percentile,
                                  labels=rule.labels)
            if v is None:
                return False, None
            return _OPS[rule.op](float(v), rule.threshold), float(v)
        dn = self._window_delta(rule.numerator, rule.window_s, now,
                                labels=rule.labels)
        dd = self._window_delta(rule.denominator, rule.window_s, now,
                                labels=rule.labels)
        if dn is None or dd is None or dd <= 0:
            return False, None
        v = dn / dd
        if rule.kind == "burn_rate":
            v = v / (1.0 - rule.slo)   # error rate over the error budget
        return _OPS[rule.op](v, rule.threshold), v

    def evaluate(self):
        """One evaluation pass over every rule; returns the transition
        events emitted (each already delivered to every sink exactly once)."""
        now = monotonic_s()
        self._sample_counters(now)
        with self._lock:
            rules = list(self.rules)
        events = []
        for rule in rules:
            cond, value = self._condition(rule, now)
            rule.last_value = value
            if cond:
                if rule.state == INACTIVE:
                    rule.state = PENDING
                    rule.pending_since = now
                if rule.state == PENDING and \
                        now - rule.pending_since >= rule.for_duration_s:
                    rule.state = FIRING
                    rule.firing_since = now_s()
                    events.append(self._event(rule, FIRING, value))
            else:
                if rule.state == FIRING:
                    events.append(self._event(rule, "resolved", value))
                rule.state = INACTIVE
                rule.pending_since = None
                rule.firing_since = None
        for ev in events:
            self._notify(ev)
        return events

    def _event(self, rule, transition, value):
        rule.transitions += 1
        ev = {"type": "alert", "rule": rule.name, "state": transition,
              "severity": rule.severity, "value": value,
              "threshold": rule.threshold, "kind": rule.kind,
              "description": rule.description, "time": now_s()}
        if transition == FIRING and rule.kind == "threshold" and rule.metric:
            # a histogram-backed alert carries its freshest exemplars: the
            # receiver pivots alert -> exemplar trace_id -> /trace + /logs
            # without scraping anything else
            m = self.registry.get(rule.metric)
            if m is not None and getattr(m, "kind", None) == "histogram":
                ex = m.exemplars(**rule.labels)
                if ex:
                    ev["exemplars"] = ex[-3:]
        return ev

    def _notify(self, event):
        if self.logger is not None:
            level = "error" if event["state"] == FIRING else "info"
            self.logger.log(level, f"alert_{event['state']}",
                            rule=event["rule"], value=event["value"],
                            severity=event["severity"])
        for sink in self.sinks:
            try:
                sink(event)
            except Exception:
                if self.logger is not None:
                    self.logger.warning("alert_sink_error",
                                        sink=type(sink).__name__,
                                        rule=event["rule"])

    # ---- reading -----------------------------------------------------------
    def state(self):
        """GET /alerts payload: every rule's full status, firing first."""
        with self._lock:
            rules = list(self.rules)
        order = {FIRING: 0, PENDING: 1, INACTIVE: 2}
        rows = sorted((r.status() for r in rules),
                      key=lambda s: (order[s["state"]], s["name"]))
        return {"time": now_s(),
                "firing": sum(1 for s in rows if s["state"] == FIRING),
                "rules": rows}

    # ---- background loop ---------------------------------------------------
    def start(self):
        """Evaluate every `interval_s` (real time) on a daemon thread; tests
        that want determinism call evaluate() themselves instead."""
        if self.interval_s <= 0 or \
                (self._thread is not None and self._thread.is_alive()):
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="alert-engine")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:
                if self.logger is not None:
                    self.logger.error("alert_engine_error")

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None


# ---- sinks ------------------------------------------------------------------

class LogAlertSink:
    """Route alert events into a StructuredLogger (they then show at /logs
    and in every attached log sink)."""

    def __init__(self, logger):
        self.logger = logger

    def __call__(self, event):
        level = "error" if event["state"] == FIRING else "info"
        self.logger.log(level, "alert", **event)


class WebhookAlertSink:
    """POST each transition event as JSON to a webhook URL (PagerDuty /
    Alertmanager-receiver shape: one POST per firing and per resolve)."""

    def __init__(self, url, timeout=5.0):
        self.url = str(url)
        self.timeout = float(timeout)
        self.delivered = 0

    def __call__(self, event):
        from ..util.http import post_json
        post_json(self.url, event, timeout=self.timeout)
        self.delivered += 1


class RouterAlertSink:
    """Append alert events to a ui/storage StatsStorageRouter as
    `type: "telemetry"` reports (excluded from training charts, durable in
    the File/Sqlite tiers like any other report)."""

    def __init__(self, router, session_id="alerts"):
        self.router = router
        self.session_id = str(session_id)

    def __call__(self, event):
        self.router.put_update({"type": "telemetry",
                                "session_id": self.session_id,
                                "time": event["time"], "alert": event})


# ---- stock rule sets --------------------------------------------------------

def default_serving_rules(max_p99_ms=1000.0, error_ratio=0.05,
                          shed_ratio=0.10, window_s=60.0,
                          for_duration_s=15.0, bytes_ratio=1.2):
    """The SLO set a ServingServer watches out of the box: dispatch error
    ratio, p99 latency, true shed ratio (shed/(requests+shed)), and the
    deploy-time bytes regression (a hot-swap that inflates an executable
    family's hbm_bytes_per_sample >20% vs the previous version — the alarm
    a quantized->f32 fallback trips; see telemetry/cost.py)."""
    return [
        AlertRule("serving_error_ratio", "ratio",
                  numerator="errors_total", denominator="requests_total",
                  threshold=error_ratio, window_s=window_s,
                  for_duration_s=for_duration_s, severity="page",
                  description="model dispatch errors per answered request"),
        AlertRule("serving_p99_latency_ms", "threshold",
                  metric="latency_ms", percentile=0.99,
                  threshold=max_p99_ms, for_duration_s=for_duration_s,
                  severity="page",
                  description="p99 request latency over the SLO bound"),
        AlertRule("serving_shed_ratio", "ratio",
                  numerator="shed_total",
                  denominator=["requests_total", "shed_total"],
                  threshold=shed_ratio, window_s=window_s,
                  for_duration_s=for_duration_s, severity="warning",
                  description="admission load-shedding (429) fraction"),
        AlertRule("deploy_bytes_regression", "threshold",
                  metric="deploy_hbm_bytes_per_sample_ratio",
                  threshold=bytes_ratio, op=">", for_duration_s=0.0,
                  severity="page",
                  description="a deploy/hot-swap raised an executable "
                              "family's HBM bytes per sample vs the "
                              "previous version (quantization fallback?)"),
    ]


def default_training_rules(max_consumer_wait_ms=250.0):
    """Watchdog set for a training process: NaN/divergence events from
    TrainingHealthListener and ETL consumer starvation."""
    return [
        AlertRule("training_nan", "threshold",
                  metric="training_nan_total", threshold=0, op=">",
                  severity="page",
                  description="non-finite loss or gradients observed"),
        AlertRule("training_divergence", "threshold",
                  metric="training_divergence_total", threshold=0, op=">",
                  severity="page",
                  description="loss diverged from its rolling best"),
        AlertRule("etl_consumer_starvation", "threshold",
                  metric="etl_consumer_wait_ms", percentile=0.5,
                  threshold=max_consumer_wait_ms, severity="warning",
                  description="device waiting on the host input pipeline"),
    ]
