"""OpenMetrics text exposition for a MetricsRegistry.

Renders `# HELP` / `# TYPE` headers and one sample line per label-set;
histograms expand to the standard cumulative `_bucket{le=...}` series plus
`_sum` and `_count`. This is the scrape side of `/metrics?format=prometheus`
on both the ServingServer and the UI server (JSON stays the default there
for back-compat).

Histogram bucket lines carry exemplars when the histogram recorded any
(` # {trace_id="..."} value timestamp` after the sample): the scrape-side
join from a latency bucket to the exact trace that landed in it, which
Grafana/Prometheus render as clickable exemplar points.

Exemplars are only legal in the OpenMetrics format — a scraper picks its
parser from the response Content-Type, and the classic text/plain 0.0.4
parser rejects the ` # {...}` suffix outright — so the exposition IS
OpenMetrics: `application/openmetrics-text` content type, a `# EOF`
terminator, and counter metric-family names with the `_total` sample
suffix stripped (the family is `requests`, the sample `requests_total`;
the spec reserves the suffix and Prometheus' OpenMetrics parser enforces
it). Prometheus has parsed this format since 2.5 (2018).
"""
from __future__ import annotations

CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def _escape_help(s):
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s):
    return str(s).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _fmt_value(v):
    if v is None:
        return "NaN"
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_labels(labels, extra=None):
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(items.items()))
    return "{" + body + "}"


def _le(bound):
    return "+Inf" if bound == float("inf") else _fmt_value(bound)


def _bucket_exemplar(exemplars, lo, hi):
    """Latest exemplar whose value falls in this bucket's (lo, hi] range,
    rendered as the OpenMetrics ` # {...} value ts` suffix (or "")."""
    for e in reversed(exemplars):
        if lo < e["value"] <= hi:
            return (f' # {{trace_id="{_escape_label(e["trace_id"])}"}}'
                    f' {_fmt_value(e["value"])} {_fmt_value(e["time"])}')
    return ""


def render(registry) -> str:
    """The full exposition text for every instrument in `registry`."""
    lines = []
    for m in registry.collect():
        # OpenMetrics counters: the `_total` suffix belongs to the SAMPLE,
        # not the family — `# TYPE requests counter` / `requests_total 5`
        family = m.name
        sample = m.name
        if m.kind == "counter":
            family = m.name[:-6] if m.name.endswith("_total") else m.name
            sample = family + "_total"
        lines.append(f"# HELP {family} {_escape_help(m.help)}")
        lines.append(f"# TYPE {family} {m.kind}")
        if m.kind == "histogram":
            for labels, data in m.series():
                exemplars = data.get("exemplars", ())
                lo = float("-inf")
                for bound, cum in data["buckets"]:
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(labels, {'le': _le(bound)})}"
                        f" {_fmt_value(cum)}"
                        f"{_bucket_exemplar(exemplars, lo, bound)}")
                    lo = bound
                lines.append(f"{m.name}_sum{_fmt_labels(labels)}"
                             f" {_fmt_value(data['sum'])}")
                lines.append(f"{m.name}_count{_fmt_labels(labels)}"
                             f" {_fmt_value(data['count'])}")
        else:
            series = m.series()
            if not series:
                continue
            for labels, value in series:
                lines.append(f"{sample}{_fmt_labels(labels)}"
                             f" {_fmt_value(value)}")
    lines.append("# EOF")
    return "\n".join(lines) + "\n"
