"""Prometheus text exposition (format version 0.0.4) for a MetricsRegistry.

Renders `# HELP` / `# TYPE` headers and one sample line per label-set;
histograms expand to the standard cumulative `_bucket{le=...}` series plus
`_sum` and `_count`. This is the scrape side of `/metrics?format=prometheus`
on both the ServingServer and the UI server (JSON stays the default there
for back-compat).
"""
from __future__ import annotations

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _escape_help(s):
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s):
    return str(s).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _fmt_value(v):
    if v is None:
        return "NaN"
    f = float(v)
    if f == float("inf"):
        return "+Inf"
    if f == float("-inf"):
        return "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def _fmt_labels(labels, extra=None):
    items = dict(labels)
    if extra:
        items.update(extra)
    if not items:
        return ""
    body = ",".join(f'{k}="{_escape_label(v)}"'
                    for k, v in sorted(items.items()))
    return "{" + body + "}"


def _le(bound):
    return "+Inf" if bound == float("inf") else _fmt_value(bound)


def render(registry) -> str:
    """The full exposition text for every instrument in `registry`."""
    lines = []
    for m in registry.collect():
        lines.append(f"# HELP {m.name} {_escape_help(m.help)}")
        lines.append(f"# TYPE {m.name} {m.kind}")
        if m.kind == "histogram":
            for labels, data in m.series():
                for bound, cum in data["buckets"]:
                    lines.append(
                        f"{m.name}_bucket"
                        f"{_fmt_labels(labels, {'le': _le(bound)})}"
                        f" {_fmt_value(cum)}")
                lines.append(f"{m.name}_sum{_fmt_labels(labels)}"
                             f" {_fmt_value(data['sum'])}")
                lines.append(f"{m.name}_count{_fmt_labels(labels)}"
                             f" {_fmt_value(data['count'])}")
        else:
            series = m.series()
            if not series:
                continue
            for labels, value in series:
                lines.append(f"{m.name}{_fmt_labels(labels)}"
                             f" {_fmt_value(value)}")
    return "\n".join(lines) + "\n"
