"""Live cost attribution: per-executable FLOPs / HBM bytes / roofline plane.

bench.py has always been able to say what the headline step COSTS — it asks
XLA directly (`Compiled.cost_analysis()` → flops + bytes accessed,
`memory_analysis()` → temp/argument/output buffer bytes) — but only offline,
in three hand-rolled places. The live system (serving batcher buckets, decode
step/prefill/verify, mesh dispatch, training jit caches) could not say which
executable is eating the bandwidth. This module closes that gap:

- `compiled_costs(compiled)` / `classify(...)` — ONE implementation of the
  cost-dict extraction and the roofline arithmetic bench.py previously
  hand-rolled (same legs, same binding rule: hbm leg vs the configured
  nominal bandwidth, matmul leg vs the measured/configured MXU ceiling).
- `ExecutableCostRegistry` — hooks every compile site the stack already
  funnels through `CompileTracker`/`timed_first_call`. At compile time it
  re-lowers the jitted callable from `ShapeDtypeStruct` abstractions of the
  real arguments (captured BEFORE the donating first call invalidates them;
  AOT lowering does not touch jax's dispatch cache, so the zero-recompile
  invariants hold) and records flops, bytes accessed, and buffer sizes,
  normalized per-sample/per-token, classified into `roofline_binding` /
  `roofline_util` gauges on the stack's MetricsRegistry.
- A cheap sampled per-dispatch wall-time histogram (`dispatch_ms`, every Nth
  dispatch, one lock + int increment off the sampled path) makes
  achieved-vs-roofline live: `roofline_util` is re-estimated from each
  sampled dispatch.
- A "bytes regression at deploy time" plane: when a deploy/hot-swap
  re-captures an executable family at a new version, the registry sets
  `deploy_hbm_bytes_per_sample_ratio{family}` (and an unlabeled max) to
  new/old bytes-per-sample — the gauge a default AlertEngine rule watches so
  a quantized→f32 fallback trips an alarm instead of silently doubling HBM
  traffic.
- `install_donation_watch()` — donation failures observable at runtime: a
  chained `warnings.showwarning` hook counts XLA "donated buffers were not
  usable" warnings into `donation_warnings_total{site}` with a
  trace-correlated structured log record, instead of bench-stderr scraping.
- `capture_trace(steps)` — the bounded on-demand capture behind
  `GET /profile/trace?steps=N`: flips the in-process Tracer on, waits (hard
  iteration bound, never a jax.profiler session) for N fresh spans, restores
  the tracer's prior state, and returns a Chrome-trace dict of just the
  captured window.
"""
from __future__ import annotations

import sys
import threading
import time
import warnings as _pywarnings

from .registry import get_registry
from .trace import get_tracer

# Same nominal v5e numbers bench.py anchors its roofline on: the matmul leg
# is meant to be overridden with the measured MXU ceiling (bench probes it);
# the HBM leg stays nominal because cost_analysis byte counts are an upper
# bound (see bench.py's roofline_note).
V5E_PEAK_FLOPS = 197e12          # bf16 dense nominal, TPU v5e (FLOP/s)
V5E_PEAK_HBM = 820e9             # bytes/s nominal, TPU v5e

_COST_KEYS = (("flops", "flops"), ("bytes accessed", "hbm_bytes"))
_MEM_KEYS = (("temp_size_in_bytes", "temp_bytes"),
             ("argument_size_in_bytes", "argument_bytes"),
             ("output_size_in_bytes", "output_bytes"),
             ("generated_code_size_in_bytes", "code_bytes"))


def abstractify(tree):
    """Map a pytree of concrete arrays to `jax.ShapeDtypeStruct` leaves so an
    executable can be re-lowered WITHOUT live buffers — donated arguments are
    invalidated by the first real call, so capture this before it."""
    import jax

    def leaf(a):
        if hasattr(a, "shape") and hasattr(a, "dtype"):
            return jax.ShapeDtypeStruct(a.shape, a.dtype)
        return a

    return jax.tree_util.tree_map(leaf, tree)


def compiled_costs(compiled):
    """Normalize `Compiled.cost_analysis()` + `memory_analysis()` into one
    flat dict: {flops, hbm_bytes, temp_bytes, argument_bytes, output_bytes,
    code_bytes}. cost_analysis returns a dict on some jax versions and a
    list-of-dict (one per partition) on others; missing keys and backends
    that report nothing degrade to 0.0, never raise."""
    out = {name: 0.0 for _, name in _COST_KEYS}
    out.update({name: 0.0 for _, name in _MEM_KEYS})
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        for key, name in _COST_KEYS:
            v = ca.get(key)
            if v is not None:
                out[name] = float(v)
    except Exception:
        pass
    try:
        ma = compiled.memory_analysis()
        for attr, name in _MEM_KEYS:
            v = getattr(ma, attr, None)
            if v is not None:
                out[name] = float(v)
    except Exception:
        pass
    return out


def classify(flops, hbm_bytes, tflops_ceiling=None, hbm_bps_ceiling=None,
             measured_ms=None):
    """The roofline arithmetic bench.py's headline block uses, shared:
    compute leg = flops / matmul ceiling, HBM leg = bytes / bandwidth
    ceiling; binding is whichever leg is longer; util (when a measured wall
    time is supplied) is the longer leg over the measured time — util ≈ 1.0
    means the executable already runs as fast as its binding wall allows.
    Ceilings are FLOP/s and bytes/s; default to the v5e nominals."""
    tf = float(tflops_ceiling or V5E_PEAK_FLOPS)
    bw = float(hbm_bps_ceiling or V5E_PEAK_HBM)
    t_mm_ms = float(flops) / tf * 1e3
    t_bw_ms = float(hbm_bytes) / bw * 1e3
    out = {"roofline_compute_ms": t_mm_ms,
           "roofline_hbm_ms": t_bw_ms,
           "roofline_binding": "hbm" if t_bw_ms > t_mm_ms else "matmul"}
    if measured_ms and measured_ms > 0:
        out["roofline_util"] = max(t_mm_ms, t_bw_ms) / float(measured_ms)
    else:
        out["roofline_util"] = None
    return out


class ExecutableCostRegistry:
    """Per-executable cost table + live roofline gauges for one stack.

    One instance per serving/training stack (CompileTracker-style), sharing
    the stack's MetricsRegistry. Call sites:

    - `capture(label, fn, args, ...)` at each first-call/compile seam, with
      the ABSTRACT argument snapshot (see `abstractify`); the jitted fn is
      re-lowered AOT (dispatch cache untouched) and its XLA-reported costs
      recorded.
    - `record_dispatch(label, ms)` on EVERY dispatch: pays one lock + int
      increment; every `sample_every`th dispatch lands in the `dispatch_ms`
      histogram and refreshes that executable's `roofline_util` gauge.
    """

    def __init__(self, registry=None, matmul_tflops_ceiling=None,
                 hbm_gbps_ceiling=None, sample_every=16):
        self.registry = registry if registry is not None else get_registry()
        # Ceilings arrive in the bench-report units (TFLOP/s, GB/s) and are
        # held in base units (FLOP/s, bytes/s) like bench's internals.
        self.tf_ceiling = (float(matmul_tflops_ceiling) * 1e12
                           if matmul_tflops_ceiling else V5E_PEAK_FLOPS)
        self.bw_ceiling = (float(hbm_gbps_ceiling) * 1e9
                           if hbm_gbps_ceiling else V5E_PEAK_HBM)
        self.sample_every = max(1, int(sample_every))
        self._lock = threading.Lock()
        self._records = {}            # label -> row dict
        self._dispatch_n = {}         # label -> total dispatch count
        self._ratio = {}              # (family, label) -> last deploy ratio
        r = self.registry
        self.captures = r.counter(
            "cost_captures_total",
            "Executable cost captures (XLA cost_analysis at compile time)")
        self.capture_errors = r.counter(
            "cost_capture_errors_total",
            "Executable cost captures that failed (backend reported nothing)")
        self.captures.inc(0)
        self.capture_errors.inc(0)
        self.flops_gauge = r.gauge(
            "executable_flops_per_sample",
            "XLA-reported FLOPs per sample/token, labeled by executable")
        self.bytes_gauge = r.gauge(
            "executable_hbm_bytes_per_sample",
            "XLA-reported HBM bytes accessed per sample/token, "
            "labeled by executable")
        self.binding_gauge = r.gauge(
            "roofline_binding",
            "Roofline binding per executable: 1 = hbm-bound, 0 = matmul-bound")
        self.util_gauge = r.gauge(
            "roofline_util",
            "Live roofline utilization estimate per executable "
            "(binding leg / sampled dispatch wall time)")
        self.dispatch_hist = r.histogram(
            "dispatch_ms",
            "Sampled per-dispatch wall milliseconds, labeled by executable")
        self.ratio_gauge = r.gauge(
            "deploy_hbm_bytes_per_sample_ratio",
            "hbm_bytes_per_sample of the newest captured version over the "
            "previous version, per executable family (unlabeled = worst); "
            ">1.2 means a deploy regressed the byte diet")
        self.ratio_gauge.set(1.0)

    # ---- capture ----------------------------------------------------------
    def capture(self, label, fn, args=(), kwargs=None, family=None,
                samples=1, version=None):
        """Lower `fn` (a jitted callable, possibly timed_first_call-wrapped)
        for the given ABSTRACT args and record its XLA costs under `label`.
        `samples` is the batch/token count one execution serves (the padded
        bucket, decode slots, verify window...) — the per-sample normalizer.
        Never raises: capture is observability, not control flow."""
        try:
            # Unwrap timed_first_call-style wrappers, but stop at the first
            # object that can lower: jax.jit functions set __wrapped__ to the
            # RAW python function, so unwrapping past them loses .lower.
            target = fn
            while not hasattr(target, "lower"):
                inner = getattr(target, "__wrapped__", None)
                if inner is None:
                    break
                target = inner
            # This is a SHADOW compile for accounting only: abstract args
            # carry no sharding/placement, so XLA may re-emit warnings
            # (donation-unusable on sharded caches) that the real compile
            # did not — silence them here so the diagnostic lower never
            # pollutes donation watches or test warning nets.
            with _pywarnings.catch_warnings():
                _pywarnings.simplefilter("ignore")
                comp = target.lower(*args, **(kwargs or {})).compile()
        except Exception:
            self.capture_errors.inc(1, executable=str(label))
            return None
        return self.capture_compiled(label, comp, family=family,
                                     samples=samples, version=version)

    def capture_compiled(self, label, compiled, family=None, samples=1,
                         version=None):
        """Record costs for an already-compiled executable (bench.py's AOT
        path). Returns the stored row (also the live-vs-offline agreement
        surface bench asserts against)."""
        label = str(label)
        family = str(family) if family else label.split(":", 1)[0]
        samples = max(1, int(samples))
        costs = compiled_costs(compiled)
        cls = classify(costs["flops"], costs["hbm_bytes"],
                       self.tf_ceiling, self.bw_ceiling)
        row = dict(costs)
        row.update(executable=label, family=family, samples=samples,
                   version=None if version is None else str(version),
                   flops_per_sample=costs["flops"] / samples,
                   hbm_bytes_per_sample=costs["hbm_bytes"] / samples,
                   roofline_compute_ms=cls["roofline_compute_ms"],
                   roofline_hbm_ms=cls["roofline_hbm_ms"],
                   roofline_binding=cls["roofline_binding"],
                   roofline_util=None, dispatch_ms_p50=None, dispatches=0)
        with self._lock:
            prev = self._records.get(label)
            self._records[label] = row
            row["dispatches"] = self._dispatch_n.get(label, 0)
            self._update_deploy_ratio_locked(family, label, row, prev)
        self.captures.inc(1, executable=label, family=family)
        self.flops_gauge.set(row["flops_per_sample"], executable=label)
        self.bytes_gauge.set(row["hbm_bytes_per_sample"], executable=label)
        self.binding_gauge.set(
            1.0 if row["roofline_binding"] == "hbm" else 0.0,
            executable=label)
        return row

    def _update_deploy_ratio_locked(self, family, label, row, prev):
        """A re-capture of a known label at a DIFFERENT version is a
        deploy/hot-swap: record new/old bytes-per-sample for the label, and
        publish per-family (max over its labels' latest transitions) plus an
        unlabeled worst-family series — `Gauge.get()` with no labels reads
        only the unlabeled series, and that is what the default alert rule
        watches."""
        if (prev is None or prev.get("version") == row.get("version")
                or not prev.get("hbm_bytes_per_sample")):
            return
        self._ratio[(family, label)] = (row["hbm_bytes_per_sample"]
                                        / prev["hbm_bytes_per_sample"])
        fams = {}
        for (fam, _), r in self._ratio.items():
            fams[fam] = max(fams.get(fam, 0.0), r)
        for fam, r in fams.items():
            self.ratio_gauge.set(r, family=fam)
        self.ratio_gauge.set(max(fams.values()))

    # ---- dispatch sampling ------------------------------------------------
    def dispatch_due(self, label):
        """Count one dispatch of `label`; True when THIS dispatch should be
        timed (every `sample_every`th, starting with the first). Call sites
        whose wall time is not already measured (decode's async step) use
        this to pay the device sync only on sampled dispatches."""
        with self._lock:
            n = self._dispatch_n.get(label, 0) + 1
            self._dispatch_n[label] = n
            row = self._records.get(label)
            if row is not None:
                row["dispatches"] = n
        return n % self.sample_every == 1 or self.sample_every == 1

    def observe_dispatch(self, label, ms):
        """Record one SAMPLED dispatch wall time: lands in the dispatch_ms
        histogram and refreshes the label's live roofline_util estimate
        (binding leg over measured time)."""
        label = str(label)
        self.dispatch_hist.observe(float(ms), executable=label)
        with self._lock:
            row = self._records.get(label)
        if row is not None and ms and ms > 0:
            util = max(row["roofline_compute_ms"],
                       row["roofline_hbm_ms"]) / float(ms)
            row["roofline_util"] = util
            row["dispatch_ms_p50"] = self.dispatch_hist.percentile(
                0.50, executable=label)
            self.util_gauge.set(util, executable=label)

    def record_dispatch(self, label, ms):
        """Called on EVERY dispatch where the wall time is already measured
        (the batcher times each dispatch anyway); off the sampled path it
        costs one lock acquire and an int increment."""
        label = str(label)
        if self.dispatch_due(label):
            self.observe_dispatch(label, ms)

    def dispatches(self, label):
        with self._lock:
            return self._dispatch_n.get(str(label), 0)

    # ---- reading ----------------------------------------------------------
    def get(self, label):
        with self._lock:
            row = self._records.get(str(label))
            return dict(row) if row else None

    def labels(self):
        with self._lock:
            return sorted(self._records)

    def table(self, sort="hbm_bytes_per_sample", family=None):
        """Sortable per-executable rows (the `/profile/cost` payload).
        Unknown sort keys fall back to bytes-per-sample — a scrape never
        500s over a typo'd query param on the UI side."""
        with self._lock:
            rows = [dict(r) for r in self._records.values()
                    if family is None or r["family"] == family]
        keyed = sort if rows and sort in rows[0] else "hbm_bytes_per_sample"
        rows.sort(key=lambda r: ((r.get(keyed) is not None, r.get(keyed))
                                 if not isinstance(r.get(keyed), str)
                                 else (True, r.get(keyed))), reverse=True)
        return rows

    def to_dict(self, sort="hbm_bytes_per_sample", family=None):
        return {"ceilings": {"matmul_tflops_ceiling": self.tf_ceiling / 1e12,
                             "hbm_gbps_ceiling": self.bw_ceiling / 1e9},
                "sample_every": self.sample_every,
                "executables": self.table(sort=sort, family=family)}


# ---- process-default registry ----------------------------------------------
# None until a stack opts in (bench, smoke tools, ServingServer): the
# training jit-cache seam (`timed_first_call`) consults this and pays a
# single None-check per first call when nobody is attributing costs, so unit
# tests that merely train never pay the AOT re-lower.

_default_cost = None
_default_cost_lock = threading.Lock()


def get_cost_registry():
    return _default_cost


def set_cost_registry(reg):
    global _default_cost
    with _default_cost_lock:
        _default_cost = reg
    return reg


# ---- donation watch ---------------------------------------------------------

DONATION_MARKER = "donated buffers were not usable"

_donation_lock = threading.Lock()
_donation_subscribers = []       # (counter, logger) pairs
_donation_installed = False


def _donation_site():
    """First stack frame outside jax/warnings machinery — the code that
    triggered the donating compile, which is the label that makes the
    counter actionable (`mlir.py` would not be)."""
    f = sys._getframe(2)
    while f is not None:
        fn = f.f_code.co_filename.replace("\\", "/")
        if ("/jax/" not in fn and "/warnings" not in fn
                and not fn.endswith("telemetry/cost.py")):
            parts = fn.rsplit("/", 2)
            return "/".join(parts[-2:]) + f":{f.f_lineno}"
        f = f.f_back
    return "unknown"


def _on_donation_warning(message):
    site = _donation_site()
    with _donation_lock:
        subs = list(_donation_subscribers)
    for counter, logger in subs:
        try:
            counter.inc(1, site=site)
            if logger is not None:
                logger.warning("xla_donation_unusable", site=site,
                               detail=str(message))
        except Exception:   # graftlint: disable=GL005 this IS the error
            pass            # reporter; a raise here would mask the warning


def install_donation_watch(registry=None, logger=None):
    """Make XLA donation failures a live metric instead of stderr noise:
    chain-wrap `warnings.showwarning` so every "donated buffers were not
    usable" warning increments `donation_warnings_total{site}` and emits a
    trace-correlated structured log record. The previous showwarning still
    runs (stderr visibility is kept). Returns an uninstall callable removing
    THIS subscriber (the chain itself stays; it is a no-op with no
    subscribers). Note: `warnings.catch_warnings` blocks that swap
    showwarning (bench's recording net) bypass the chain while active."""
    global _donation_installed
    reg = registry if registry is not None else get_registry()
    counter = reg.counter(
        "donation_warnings_total",
        "XLA donated-buffer-unusable warnings at runtime, labeled by the "
        "triggering call site")
    counter.inc(0)
    sub = (counter, logger)
    with _donation_lock:
        _donation_subscribers.append(sub)
        # (Re-)install whenever the current showwarning is not ours: test
        # harnesses (pytest's warning plugin) and catch_warnings blocks swap
        # showwarning wholesale, silently dropping an earlier chain. Checking
        # the marker instead of a one-shot flag re-chains on top of whatever
        # handler is live now.
        if not hasattr(_pywarnings.showwarning, "_donation_prev"):
            _donation_installed = True
            # Donation warnings repeat per compile; without an "always"
            # filter the warnings registry dedupes after the first and the
            # counter undercounts every later regression.
            _pywarnings.filterwarnings(
                "always", message=".*" + DONATION_MARKER + ".*")
            prev = _pywarnings.showwarning

            def showwarning(message, category, filename, lineno,
                            file=None, line=None):
                if DONATION_MARKER in str(message):
                    _on_donation_warning(message)
                return prev(message, category, filename, lineno,
                            file=file, line=line)

            showwarning._donation_prev = prev
            _pywarnings.showwarning = showwarning

    def uninstall():
        with _donation_lock:
            if sub in _donation_subscribers:
                _donation_subscribers.remove(sub)

    return uninstall


# ---- bounded trace capture --------------------------------------------------

MAX_TRACE_STEPS = 2048


def capture_trace(steps, tracer=None, timeout_s=2.0, poll_s=0.01):
    """Bounded on-demand span capture (the `/profile/trace?steps=N` body):
    enable the in-process Tracer (never a `jax.profiler` session), wait for
    `steps` NEW spans with a hard iteration bound, restore the tracer's
    previous enabled state, and return a Chrome-trace dict of the captured
    window (falling back to the newest ring-buffer spans if traffic is
    idle). Raises ValueError for a non-positive or oversized `steps` — the
    HTTP layer maps that to 400."""
    steps = int(steps)
    if steps <= 0 or steps > MAX_TRACE_STEPS:
        raise ValueError(f"steps must be in [1, {MAX_TRACE_STEPS}]")
    tr = tracer if tracer is not None else get_tracer()
    was_enabled = tr.enabled
    tr.enabled = True
    try:
        have = len(tr.finished_spans())
        # Hard bound: ceil(timeout/poll) real-sleep polls, independent of any
        # ManualClock (which freezes monotonic_s, not time.sleep) — the
        # capture ALWAYS stops.
        for _ in range(max(1, int(float(timeout_s) / max(poll_s, 1e-3)))):
            if len(tr.finished_spans()) - have >= steps:
                break
            time.sleep(poll_s)
    finally:
        tr.enabled = was_enabled
    spans = tr.finished_spans()
    window = spans[have:] if len(spans) > have else spans
    window = window[-steps:]
    keep = {s.span_id for s in window}
    chrome = tr.to_chrome_trace()
    events = [e for e in chrome["traceEvents"]
              if e.get("args", {}).get("span_id") in keep]
    chrome["traceEvents"] = events
    chrome["otherData"]["captured_spans"] = len(window)
    chrome["otherData"]["requested_steps"] = steps
    return chrome
