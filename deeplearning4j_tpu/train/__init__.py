"""Fault-tolerant training driver: periodic checkpoint + preemption resume.

SURVEY.md §5 names this a TPU must-add with no reference counterpart ("no
elastic worker membership, no preemption handling"); the closest reference
analogs are Spark's RDD-lineage task retry and the download retry loop at
deeplearning4j-core/.../base/MnistFetcher.java:103-107. TPUs are preemptible,
so the driver must assume the process can die at any step and training must
continue from the last checkpoint — including mid-epoch iterator position.
"""
from .fault_tolerance import CheckpointConfig, FaultTolerantTrainer

__all__ = ["CheckpointConfig", "FaultTolerantTrainer"]
