"""Checkpoint-restart fault tolerance with durable, verified checkpoints.

Design (TPU-native, no reference counterpart — SURVEY.md §5 gap):
- durable checkpoints: every file fsync'd and the directory published via
  `util.fs.atomic_publish_dir` (fsync before AND after the `os.replace`),
  so a preemption or power loss mid-write never corrupts — or half-
  publishes — the latest checkpoint;
- verified format: each checkpoint dir carries a `MANIFEST.json` written
  LAST (per-file sha256 + byte sizes, step, wall time, topology). A
  checkpoint without a valid manifest is by definition incomplete. The
  digests are computed from the in-memory bytes the writer intended, so
  restore-time verification catches torn writes and bit rot that write-time
  read-back (served from the page cache) never could;
- fallback restore: `_try_restore` walks `ckpt-*` newest -> oldest,
  verifies manifests, QUARANTINES failures under `corrupt-<name>`
  (mirroring the `halt-*` forensics idiom — kept, never auto-restored),
  and resumes from the first checkpoint that verifies AND loads. Fallbacks
  surface as `ckpt_restore_fallbacks_total` / `ckpt_verify_failures_total`
  and as a degraded health-probe detail until the next good publish;
- async writes: `checkpoint()` snapshots params/opt-state/rng to host in
  ONE blocking device-get, then serializes+verifies+publishes on a
  background writer thread — at most one write in flight (the next
  checkpoint joins), writer errors re-raised exactly once at the next
  `checkpoint()`/fit-end (the ETL error-propagation idiom), except
  ENOSPC/EDQUOT (disk full is retryable capacity debt: counted, logged,
  degraded-probe-visible, and training keeps running — the previously
  published checkpoint stays intact). `ckpt_blocking_ms` vs `ckpt_write_ms`
  histograms make the async win measurable;
- training state beyond weights: epoch, batch index within the epoch, total
  iteration count, and the model's PRNG key all persist, so the resumed loss
  curve continues where the dead process stopped (mid-epoch included);
- the model file is the standard ModelSerializer zip (configuration.json +
  coefficients + updater state — util/model_serializer.py), so any checkpoint
  doubles as a normal saved model;
- `FaultTolerantTrainer.fit` skips already-consumed batches when resuming
  mid-epoch by fast-forwarding the iterator.

Chaos: `resilience.chaos.FaultPlan` disk rules (`torn_write` / `bitflip` /
`enospc` / `slow_disk`) inject through the `util.fs` write seam the async
writer uses; `tools/ckpt_doctor.py` is the operator CLI over the same
verify/quarantine primitives.

Reference analogs for the retry/resume idea: Spark task retry (RDD lineage),
MnistFetcher.java:103-107 download retry.
"""
from __future__ import annotations

import errno
import io
import json
import os
import shutil
import threading

import numpy as np

from ..telemetry.registry import get_registry
from ..telemetry.trace import get_tracer
from ..util import fs
from ..util.model_serializer import ModelSerializer
from ..util.time_source import monotonic_s, now_s


class CheckpointConfig:
    def __init__(self, directory, frequency=50, keep_last=2, format="zip",
                 keep_every=None, async_write=True):
        """format: "zip" (ModelSerializer contract, host-gathered) or
        "sharded" (orbax tensor store — mesh-sharded params checkpoint
        without host gathering, util/sharded_checkpoint.py).

        `keep_every=K`: checkpoints whose iteration is a multiple of K are
        ANCHORS — never garbage-collected, however far outside the
        `keep_last` window they fall (the long-run forensics ladder).

        `async_write`: serialize+verify+publish on the background writer
        thread (the training thread pays only the host snapshot). Forced
        off for the sharded format — orbax streams device shards itself,
        and host-gathering them first would defeat that format's point."""
        assert format in ("zip", "sharded")
        self.directory = str(directory)
        self.frequency = int(frequency)
        self.keep_last = int(keep_last)
        self.format = format
        self.keep_every = None if keep_every is None else int(keep_every)
        self.async_write = bool(async_write) and format == "zip"


def _is_disk_full(exc) -> bool:
    """ENOSPC/EDQUOT: capacity debt, retryable at the next interval — the
    one writer-error class that must not kill a training run."""
    return isinstance(exc, OSError) and \
        exc.errno in (errno.ENOSPC, getattr(errno, "EDQUOT", errno.ENOSPC))


class _ModelSnapshot:
    """Host-side copy of the serializable network state, detached from the
    live model so the background writer never races training (or reads a
    donated buffer). `model_class` stands in for the isinstance checks
    ModelSerializer.write_model would do on the live network; `_zero` is
    None because the updater state was already converted to its canonical
    layout during the blocking snapshot."""

    def __init__(self, conf, model_class, params, states, opt_state):
        self.conf = conf
        self.model_class = model_class
        self.params = params
        self.states = states
        self.opt_state = opt_state
        self._zero = None


class _CheckpointWriter:
    """At most one checkpoint write in flight. The trainer thread is the
    only caller: it `join()`s the in-flight write, then `claim_error()`s —
    the parked exception surfaces exactly ONCE (the ETL error-propagation
    idiom) — before submitting the next job."""

    def __init__(self):
        self._thread = None
        self._error = None

    def submit(self, job):
        if self._thread is not None:
            raise RuntimeError("join() the in-flight checkpoint write first")

        def run():
            try:
                job()
            except BaseException as e:   # parked; claimed on the next join
                self._error = e

        t = threading.Thread(target=run, name="ckpt-writer", daemon=True)
        self._thread = t
        t.start()

    def join(self):
        t = self._thread
        if t is not None:
            t.join()
            self._thread = None

    def claim_error(self):
        err, self._error = self._error, None
        return err


class FaultTolerantTrainer:
    """Drives `model.fit`-style training with periodic durable checkpoints
    and preemption resume.

    Usage:
        trainer = FaultTolerantTrainer(model_factory, CheckpointConfig(dir))
        trainer.fit(iterator, epochs=N)   # auto-resumes if checkpoints exist
    `model_factory()` builds the (un-initialized) model when no checkpoint
    exists; on resume the model is restored from the newest checkpoint that
    VERIFIES (manifest hashes) — corrupt newer ones are quarantined under
    `corrupt-<name>` and the restore falls back down the chain.
    """

    STATE_FILE = "train_state.json"
    MODEL_FILE = "model.zip"
    SHARDED_DIR = "model_sharded"

    def __init__(self, model_or_factory, checkpoint: CheckpointConfig,
                 health=None, monitor=None):
        """`health`: a TrainingHealthListener (optimize.listeners) — the
        trainer attaches it to the model and, when a fatal condition trips
        (NaN loss/gradients, divergence), writes one final QUARANTINED
        checkpoint (`halt-<iter>`, kept for forensics but never auto-
        restored — its params are the corrupted/diverged state) and raises
        TrainingHalted instead of burning accelerator hours on a dead run.
        Restarting resumes from the newest periodic `ckpt-*` checkpoint,
        which predates the blow-up.

        `monitor`: the telemetry.health.HealthMonitor this trainer's
        liveness probe registers into (default: the process monitor, the
        one UIServer /healthz — and so /fleet/healthz — aggregates). The
        probe carries iteration/heartbeat state and is re-registered on the
        restore path too, so a RESUMED run is immediately visible to the
        fleet plane instead of silently losing its membership entry; pass
        monitor=False to opt out entirely. A restore that fell back past a
        corrupt checkpoint — or a swallowed disk-full write failure —
        reports DEGRADED with the debt in the detail until the next
        verified publish clears it."""
        self.ckpt = checkpoint
        os.makedirs(self.ckpt.directory, exist_ok=True)
        self._factory = (model_or_factory if callable(model_or_factory)
                         else (lambda: model_or_factory))
        self.model = None
        self.health = health
        if monitor is None:
            from ..telemetry.health import get_monitor
            monitor = get_monitor()
        self.monitor = monitor or None     # False -> None (no probe)
        self.health_key = None
        self._last_beat = None
        self._writer = _CheckpointWriter()
        self._ckpt_debt = None    # restore-fallback / write-failure detail
        self._last_good = None    # newest checkpoint name known verified
        self.state = {"epoch": 0, "batch": 0, "iteration": 0, "rng": None}
        self._restored = self._try_restore()
        self._register_probe()

    def _net(self):
        """The serializable network under self.model. A trainer wrapper
        (ShardedTrainer — incl. ZeRO mode — exposes the wrapped network as
        `.model` and drives it via fit_batch) checkpoints its INNER network;
        a bare network is itself. Wrapper checkpoints therefore stay plain
        ModelSerializer zips / orbax stores, loadable anywhere."""
        m = self.model
        inner = getattr(m, "model", None)
        if inner is not None and hasattr(inner, "conf") \
                and callable(getattr(m, "fit_batch", None)):
            return inner
        return m

    # ------------------------------------------------------------ checkpoint
    def _ckpt_dirs(self):
        out = []
        for name in os.listdir(self.ckpt.directory):
            if name.startswith("ckpt-") and os.path.isfile(
                    os.path.join(self.ckpt.directory, name, self.STATE_FILE)):
                out.append(name)
        return sorted(out, key=lambda n: int(n.split("-")[1]))

    def _gc_orphans(self):
        for name in os.listdir(self.ckpt.directory):
            if name.startswith("tmp-"):
                shutil.rmtree(os.path.join(self.ckpt.directory, name),
                              ignore_errors=True)

    def checkpoint(self, prefix="ckpt"):
        """Write a durable checkpoint of model + training state. The
        blocking cost to the training thread (one device-get snapshot on
        the async path; the whole serialize+fsync+publish otherwise) is
        `ckpt_blocking_ms`; the writer's cost is `ckpt_write_ms`, both
        under a `checkpoint` span next to the iteration timings.

        Joins any in-flight write first (at most one in flight) and
        surfaces a previous writer error exactly once — disk-full errors
        are absorbed as checkpoint debt (counter + degraded probe) so the
        run keeps training and retries at the next interval.

        `prefix` other than "ckpt" (the watchdog's "halt") is invisible to
        _try_restore/_gc: quarantined, kept, never auto-resumed."""
        t0 = monotonic_s()   # before the join: a checkpoint interval shorter
        #                      than the write time stalls the training thread
        #                      HERE, and the histogram must see that stall
        self._writer.join()
        self._surface_writer_error()
        it = self.state["iteration"]
        final = os.path.join(self.ckpt.directory, f"{prefix}-{it:09d}")
        if os.path.isdir(final):
            return final  # this iteration is already durably checkpointed
        with get_tracer().span("checkpoint", iteration=it,
                               mode=("async" if self.ckpt.async_write
                                     else "sync")):
            if self.ckpt.format == "sharded":
                job = self._sharded_job(final, it)
            else:
                job = self._snapshot_zip_job(final, it)
            if self.ckpt.async_write:
                self._writer.submit(job)
            else:
                try:
                    job()
                except BaseException as e:
                    if not self._absorb_write_error(e):
                        raise
        get_registry().histogram(
            "ckpt_blocking_ms",
            "Wall ms the training thread spends inside checkpoint()"
        ).observe((monotonic_s() - t0) * 1000.0)
        return final

    def drain_checkpoints(self, raise_errors=True):
        """Join the in-flight background write (if any) and surface its
        error exactly once. fit() calls this at fit-end; drivers shutting a
        run down (or a preemption handler with grace seconds) call it so
        the last submitted checkpoint is durably on disk before exit.

        `raise_errors=False` still COUNTS and logs a parked writer error
        (the absorb path) — it only suppresses the raise, for callers about
        to propagate a more important exception."""
        self._writer.join()
        if raise_errors:
            self._surface_writer_error()
        else:
            err = self._writer.claim_error()
            if err is not None:
                self._absorb_write_error(err)

    def _surface_writer_error(self):
        err = self._writer.claim_error()
        if err is None:
            return
        if not self._absorb_write_error(err):
            raise err

    def _absorb_write_error(self, err):
        """Count+log a checkpoint write failure; True when it is absorbable
        (disk full -> checkpoint debt, training continues), False when the
        caller must re-raise."""
        from ..telemetry.logging import get_logger
        disk_full = _is_disk_full(err)
        reason = "enospc" if disk_full else type(err).__name__
        get_registry().counter(
            "ckpt_write_failures_total",
            "Checkpoint writes that failed before publish").inc(
                1, reason=reason)
        log = get_logger()
        (log.warning if disk_full else log.error)(
            "checkpoint_write_failed", reason=reason,
            error=f"{type(err).__name__}: {err}",
            iteration=self.state["iteration"])
        if disk_full:
            self._ckpt_debt = {"write_failed": reason,
                               "iteration": self.state["iteration"]}
            return True
        return False

    # -- write jobs (run on the writer thread on the async path) -------------
    def _snapshot_zip_job(self, final, it):
        """BLOCKING phase: capture training state + ONE jax.device_get of
        params/opt-state/rng to host numpy (canonical ZeRO layout first, so
        the zip stays topology-independent). Returns the closure that
        serializes, writes through the util.fs seam, manifests, verifies,
        and durably publishes — safe to run concurrently with training.
        The zip is host-gathered, so in a multi-process job process 0
        alone writes and publishes (non-zero processes would race the
        shared tmp dir and the os.replace)."""
        import jax
        if jax.process_index() != 0:
            return lambda: None
        net = self._net()
        st = dict(self.state)
        # wrapper-ness persists so a restore only pays a factory build
        # (and adopt) when the checkpointed run actually used one; plain
        # networks restore without ever constructing a throwaway model
        st["wrapper"] = self.model is not self._net()
        opt_state = net.opt_state
        zero = getattr(net, "_zero", None)
        if zero is not None and opt_state is not None:
            opt_state = zero.to_canonical(opt_state, net.params)
        snap = jax.device_get({"params": net.params, "states": net.states,
                               "opt_state": opt_state,
                               "rng": getattr(net, "_rng", None)})
        st["rng"] = (None if snap["rng"] is None
                     else np.asarray(snap["rng"]).tolist())
        proxy = _ModelSnapshot(conf=net.conf, model_class=type(net).__name__,
                               params=snap["params"], states=snap["states"],
                               opt_state=snap["opt_state"])

        def job():
            t0 = monotonic_s()
            with get_tracer().span("ckpt_write", iteration=it):
                tmp = os.path.join(self.ckpt.directory, f"tmp-{it:09d}")
                os.makedirs(tmp, exist_ok=True)
                try:
                    buf = io.BytesIO()
                    ModelSerializer.write_model(proxy, buf)
                    files = {}
                    for name, data in ((self.MODEL_FILE, buf.getvalue()),
                                       (self.STATE_FILE,
                                        json.dumps(st).encode())):
                        fs.write_bytes(os.path.join(tmp, name), data)
                        files[name] = (fs.sha256_bytes(data), len(data))
                    self._manifest_and_publish(tmp, final, it, files=files,
                                               format="zip")
                except BaseException:
                    shutil.rmtree(tmp, ignore_errors=True)
                    raise
            self._published(final, t0)

        return job

    def _sharded_job(self, final, it):
        """Sharded (orbax) writes stay on the calling thread: orbax streams
        each process's device shards itself, which is the format's whole
        point — a host-gathered snapshot would defeat it. Manifest digests
        come from read-back (orbax owns the files), which still catches
        later bit rot at restore time."""
        # deterministic tmp name so multi-process jobs agree on the orbax
        # write path; process 0 alone publishes/GCs below
        def job():
            import jax
            t0 = monotonic_s()
            tmp = os.path.join(self.ckpt.directory, f"tmp-{it:09d}")
            os.makedirs(tmp, exist_ok=True)
            try:
                from ..util.sharded_checkpoint import save_sharded
                net = self._net()
                save_sharded(net, os.path.join(tmp, self.SHARDED_DIR))
                if jax.process_index() != 0:
                    return  # process 0 publishes the checkpoint dir
                st = dict(self.state)
                st["wrapper"] = self.model is not self._net()
                rng = getattr(net, "_rng", None)
                st["rng"] = None if rng is None else np.asarray(rng).tolist()
                fs.write_bytes(os.path.join(tmp, self.STATE_FILE),
                               json.dumps(st).encode())
                self._manifest_and_publish(tmp, final, it, files=None,
                                           format="sharded")
            except BaseException:
                shutil.rmtree(tmp, ignore_errors=True)
                raise
            self._published(final, t0)

        return job

    def _manifest_and_publish(self, tmp, final, it, files, format):
        """Manifest LAST, verify completeness, durable publish. The verify
        step re-reads the manifest from disk and checks every listed file
        EXISTS — what a crash-free writer can honestly check. Sizes and
        hashes are deliberately NOT re-checked here: a write-time read-back
        (or stat) is served from the page cache, which reports the bytes
        the writer just handed the kernel — the bytes a power loss will
        never persist. Torn writes and bit rot are real only on the
        platters, so content verification belongs to the restore path
        (and to tools/ckpt_doctor.py), where it can actually see them."""
        import jax
        fs.write_manifest(
            tmp, files=files, step=it, wall_time_s=now_s(), format=format,
            topology={"process_index": jax.process_index(),
                      "process_count": jax.process_count(),
                      "device_count": jax.device_count()})
        doc = fs.read_manifest(tmp)
        missing = [rel for rel in sorted(doc.get("files", {}))
                   if not os.path.isfile(os.path.join(tmp, rel))]
        if missing:
            raise IOError(f"checkpoint incomplete before publish: "
                          f"missing {missing}")
        fs.atomic_publish_dir(tmp, final)

    def _published(self, final, t0):
        reg = get_registry()
        reg.counter("checkpoints_total",
                    "Durable training checkpoints written").inc(1)
        dur_ms = (monotonic_s() - t0) * 1000.0
        reg.counter("checkpoint_ms_total",
                    "Wall ms spent writing checkpoints").inc(dur_ms)
        reg.histogram(
            "ckpt_write_ms",
            "Wall ms serializing+publishing one checkpoint (writer side)"
        ).observe(dur_ms)
        name = os.path.basename(final)
        if name.startswith("ckpt-"):
            self._last_good = name
            self._ckpt_debt = None     # a fresh verified publish clears debt
        self._gc()

    def _gc(self):
        dirs = self._ckpt_dirs()
        # keep_last=0 retains everything (slicing parity with the original
        # dirs[:-0] -> delete-nothing semantics)
        keep = set(dirs[-self.ckpt.keep_last:] if self.ckpt.keep_last
                   else dirs)
        # the last checkpoint KNOWN to verify survives any retention window:
        # if everything newer turns out corrupt, it is the restore of record
        if self._last_good is not None:
            keep.add(self._last_good)
        K = self.ckpt.keep_every
        if K:
            keep.update(n for n in dirs if int(n.split("-")[1]) % K == 0)
        for name in dirs:
            if name not in keep:
                shutil.rmtree(os.path.join(self.ckpt.directory, name),
                              ignore_errors=True)
        # orphaned tmp-* dirs are half-written checkpoints from a process
        # that was preempted mid-write; this (single-writer) driver owns the
        # directory, so any tmp-* present outside checkpoint() is garbage
        self._gc_orphans()

    # ------------------------------------------------------------ restore
    def _try_restore(self):
        """Walk `ckpt-*` newest -> oldest: verify the manifest (hashes
        included), then load; any failure quarantines the dir under
        `corrupt-<name>` and falls back to the next. Restoring anything
        but the newest counts a fallback and leaves the probe degraded
        until the next good publish."""
        self._gc_orphans()
        dirs = self._ckpt_dirs()
        newest = dirs[-1] if dirs else None
        for fell_back, name in enumerate(reversed(dirs)):
            path = os.path.join(self.ckpt.directory, name)
            ok, errors = fs.verify_manifest(path)
            if not ok:
                self._quarantine(name, errors)
                continue
            try:
                self._restore_from(path)
            except Exception as e:
                self._quarantine(name, [f"restore raised "
                                        f"{type(e).__name__}: {e}"])
                continue
            self._last_good = name
            if fell_back:
                get_registry().counter(
                    "ckpt_restore_fallbacks_total",
                    "Restores that fell back past corrupt checkpoints"
                ).inc(1)
                self._ckpt_debt = {"restore_fallback": True,
                                   "quarantined": fell_back,
                                   "newest_was": newest, "restored": name}
                from ..telemetry.logging import get_logger
                get_logger().warning(
                    "checkpoint_restore_fell_back", restored=name,
                    newest_was=newest, quarantined=fell_back)
            return True
        self.model = self._factory()
        if getattr(self._net(), "params", None) is None:
            self._net().init()
        return False

    def _restore_from(self, latest):
        """Load one verified checkpoint dir; only commits to self.state /
        self.model when the whole load succeeded, so a fallback after a
        partial failure never leaks half-restored state."""
        sharded_dir = os.path.join(latest, self.SHARDED_DIR)
        with open(os.path.join(latest, self.STATE_FILE)) as f:
            state = json.load(f)
        if os.path.isdir(sharded_dir):
            from ..util.sharded_checkpoint import restore_sharded
            restored = restore_sharded(sharded_dir)
        else:
            restored = ModelSerializer.restore(
                os.path.join(latest, self.MODEL_FILE))
        model = restored
        if state.get("wrapper"):
            # the checkpointed run drove a trainer wrapper (ShardedTrainer):
            # rebuild it via the factory — its mesh/ZeRO config reflects
            # THIS process's topology — and adopt the restored network state
            # (canonical updater state re-shards for the current replica
            # count). Plain-network checkpoints never pay this factory build.
            candidate = self._factory()
            if getattr(candidate, "model", None) is not None \
                    and callable(getattr(candidate, "adopt", None)):
                candidate.adopt(restored)
                model = candidate
        self.state = state
        self.model = model
        net = self._net()
        rng = self.state.get("rng")
        if rng is not None:
            import jax.numpy as jnp
            net._rng = jnp.asarray(np.asarray(rng, dtype=np.uint32))
        net.iteration_count = self.state["iteration"]
        net.epoch_count = self.state["epoch"]

    def _quarantine(self, name, errors):
        """Move a failed checkpoint aside as `corrupt-<name>` — invisible to
        _ckpt_dirs/_gc (same forensics idiom as `halt-*`), recoverable by an
        operator via tools/ckpt_doctor.py."""
        dst = fs.quarantine_dir(self.ckpt.directory, name)
        get_registry().counter(
            "ckpt_verify_failures_total",
            "Checkpoints that failed manifest verification or load").inc(1)
        from ..telemetry.logging import get_logger
        get_logger().error("checkpoint_quarantined", checkpoint=name,
                           quarantined_as=dst, errors=list(errors)[:4])

    @property
    def resumed(self):
        return self._restored

    # ------------------------------------------------------------ liveness
    def _register_probe(self):
        """(Re-)register the trainer's health probe + heartbeat state. Runs
        at construction — AFTER _try_restore, so the restore path (which
        rebuilds self.model via adopt and previously surfaced nowhere)
        re-registers too and a resumed run shows up on /healthz //fleet
        immediately, at its restored iteration. A restore primes the
        heartbeat so the probe reports a live (not never-beaten) trainer."""
        if self.monitor is None:
            return
        if self._restored:
            self._touch_beat()
        if self.health_key is not None:
            self.monitor.unregister(self.health_key)
        self.health_key = self.monitor.register_unique("trainer", self._probe)
        return self.health_key

    def unregister_probe(self):
        """Withdraw the liveness probe (a driver shutting the run down)."""
        if self.monitor is not None and self.health_key is not None:
            self.monitor.unregister(self.health_key)
            self.health_key = None

    def _touch_beat(self):
        self._last_beat = monotonic_s()

    def _probe_detail(self):
        """Extra probe fields; subclasses (ElasticTrainer) extend."""
        return {}

    def _probe(self):
        halted = self.health is not None and \
            getattr(self.health, "should_halt", False)
        # one read: the writer thread clears the debt on a good publish,
        # and the probe runs on the health monitor's thread
        debt = self._ckpt_debt
        status = "unhealthy" if halted else \
            ("degraded" if debt else "healthy")
        beat_age = None if self._last_beat is None \
            else monotonic_s() - self._last_beat
        detail = {"iteration": self.state["iteration"],
                  "epoch": self.state["epoch"],
                  "resumed": self._restored,
                  "last_step_age_s": beat_age,
                  **self._probe_detail()}
        if debt:
            detail["checkpoint_debt"] = dict(debt)
        if halted:
            detail["reason"] = getattr(self.health, "trip_reason", "halted")
        return status, detail

    # ------------------------------------------------------------ training
    def _before_batch(self):
        """Hook run between batches (before each fit_batch). The elastic
        policy (elastic.ElasticTrainer) overrides this with its membership
        poll/re-shard; the base trainer does nothing — keeping ONE fit
        loop so resume/checkpoint/halt fixes apply to every policy."""

    def fit(self, iterator, epochs=1):
        """Train with checkpoints every `frequency` iterations; on resume,
        fast-forwards past the batches the dead process already consumed.
        With a health listener attached, a fatal watchdog condition
        checkpoints once more and raises TrainingHalted. Returns only
        after the final checkpoint is durably published (drains the
        background writer, surfacing its errors per the idiom above)."""
        from ..datasets.iterator.base import as_iterator
        it = as_iterator(iterator)
        listeners = getattr(self._net(), "listeners", None)
        if self.health is not None and listeners is not None \
                and self.health not in listeners:
            listeners.append(self.health)
        freq = self.ckpt.frequency
        start_epoch = self.state["epoch"]
        for epoch in range(start_epoch, epochs):
            it.reset()
            skip = self.state["batch"] if epoch == self.state["epoch"] else 0
            b = 0
            for ds in it:
                if b < skip:
                    b += 1
                    continue
                self._before_batch()
                self.model.fit_batch(ds)
                self._touch_beat()
                b += 1
                self.state.update(epoch=epoch, batch=b,
                                  iteration=self.state["iteration"] + 1)
                self._halt_if_unhealthy()
                if freq and self.state["iteration"] % freq == 0:
                    self.checkpoint()
            self.state.update(epoch=epoch + 1, batch=0)
        self.checkpoint()
        self.drain_checkpoints()
        return self.model

    def _halt_if_unhealthy(self):
        if self.health is None or not self.health.should_halt:
            return
        from ..optimize.listeners.health import TrainingHalted
        # the fatal update is already applied to the params, so this state
        # is forensics, not a resume point: quarantine it under halt-* and
        # leave the ckpt-* chain ending at the last pre-blow-up checkpoint.
        # Drain without raising: TrainingHalted is the primary signal, and a
        # failed halt-write is already counted/logged by the absorb path.
        path = self.checkpoint(prefix="halt")
        self.drain_checkpoints(raise_errors=False)
        raise TrainingHalted(self.health.trip_reason,
                             self.state["iteration"], checkpoint_path=path)
