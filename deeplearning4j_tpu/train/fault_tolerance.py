"""Checkpoint-restart fault tolerance.

Design (TPU-native, no reference counterpart — SURVEY.md §5 gap):
- atomic checkpoints: write to `<dir>/tmp-*` then os.replace into place, so a
  preemption mid-write never corrupts the latest checkpoint;
- training state beyond weights: epoch, batch index within the epoch, total
  iteration count, and the model's PRNG key all persist, so the resumed loss
  curve continues where the dead process stopped (mid-epoch included);
- the model file is the standard ModelSerializer zip (configuration.json +
  coefficients + updater state — util/model_serializer.py), so any checkpoint
  doubles as a normal saved model;
- `FaultTolerantTrainer.fit` skips already-consumed batches when resuming
  mid-epoch by fast-forwarding the iterator.

Reference analogs for the retry/resume idea: Spark task retry (RDD lineage),
MnistFetcher.java:103-107 download retry.
"""
from __future__ import annotations

import json
import os

import numpy as np

from ..telemetry.registry import get_registry
from ..telemetry.trace import get_tracer
from ..util.model_serializer import ModelSerializer
from ..util.time_source import monotonic_s


class CheckpointConfig:
    def __init__(self, directory, frequency=50, keep_last=2, format="zip"):
        """format: "zip" (ModelSerializer contract, host-gathered) or
        "sharded" (orbax tensor store — mesh-sharded params checkpoint
        without host gathering, util/sharded_checkpoint.py)."""
        assert format in ("zip", "sharded")
        self.directory = str(directory)
        self.frequency = int(frequency)
        self.keep_last = int(keep_last)
        self.format = format


class FaultTolerantTrainer:
    """Drives `model.fit`-style training with periodic atomic checkpoints and
    preemption resume.

    Usage:
        trainer = FaultTolerantTrainer(model_factory, CheckpointConfig(dir))
        trainer.fit(iterator, epochs=N)   # auto-resumes if checkpoints exist
    `model_factory()` builds the (un-initialized) model when no checkpoint
    exists; on resume the model is restored from the newest checkpoint.
    """

    STATE_FILE = "train_state.json"
    MODEL_FILE = "model.zip"
    SHARDED_DIR = "model_sharded"

    def __init__(self, model_or_factory, checkpoint: CheckpointConfig,
                 health=None, monitor=None):
        """`health`: a TrainingHealthListener (optimize.listeners) — the
        trainer attaches it to the model and, when a fatal condition trips
        (NaN loss/gradients, divergence), writes one final QUARANTINED
        checkpoint (`halt-<iter>`, kept for forensics but never auto-
        restored — its params are the corrupted/diverged state) and raises
        TrainingHalted instead of burning accelerator hours on a dead run.
        Restarting resumes from the newest periodic `ckpt-*` checkpoint,
        which predates the blow-up.

        `monitor`: the telemetry.health.HealthMonitor this trainer's
        liveness probe registers into (default: the process monitor, the
        one UIServer /healthz — and so /fleet/healthz — aggregates). The
        probe carries iteration/heartbeat state and is re-registered on the
        restore path too, so a RESUMED run is immediately visible to the
        fleet plane instead of silently losing its membership entry; pass
        monitor=False to opt out entirely."""
        self.ckpt = checkpoint
        os.makedirs(self.ckpt.directory, exist_ok=True)
        self._factory = (model_or_factory if callable(model_or_factory)
                         else (lambda: model_or_factory))
        self.model = None
        self.health = health
        if monitor is None:
            from ..telemetry.health import get_monitor
            monitor = get_monitor()
        self.monitor = monitor or None     # False -> None (no probe)
        self.health_key = None
        self._last_beat = None
        self.state = {"epoch": 0, "batch": 0, "iteration": 0, "rng": None}
        self._restored = self._try_restore()
        self._register_probe()

    def _net(self):
        """The serializable network under self.model. A trainer wrapper
        (ShardedTrainer — incl. ZeRO mode — exposes the wrapped network as
        `.model` and drives it via fit_batch) checkpoints its INNER network;
        a bare network is itself. Wrapper checkpoints therefore stay plain
        ModelSerializer zips / orbax stores, loadable anywhere."""
        m = self.model
        inner = getattr(m, "model", None)
        if inner is not None and hasattr(inner, "conf") \
                and callable(getattr(m, "fit_batch", None)):
            return inner
        return m

    # ------------------------------------------------------------ checkpoint
    def _ckpt_dirs(self):
        out = []
        for name in os.listdir(self.ckpt.directory):
            if name.startswith("ckpt-") and os.path.isfile(
                    os.path.join(self.ckpt.directory, name, self.STATE_FILE)):
                out.append(name)
        return sorted(out, key=lambda n: int(n.split("-")[1]))

    def _gc_orphans(self):
        import shutil
        for name in os.listdir(self.ckpt.directory):
            if name.startswith("tmp-"):
                shutil.rmtree(os.path.join(self.ckpt.directory, name),
                              ignore_errors=True)

    def checkpoint(self, prefix="ckpt"):
        """Write an atomic checkpoint of model + training state. Cost is
        accounted in the telemetry registry (checkpoints_total /
        checkpoint_ms_total) and as a span — checkpoint stalls are a real
        training-throughput tax worth seeing next to iteration times.
        `prefix` other than "ckpt" (the watchdog's "halt") is invisible to
        _try_restore/_gc: quarantined, kept, never auto-resumed."""
        it = self.state["iteration"]
        final = os.path.join(self.ckpt.directory, f"{prefix}-{it:09d}")
        if os.path.isdir(final):
            return final  # this iteration is already durably checkpointed
        with get_tracer().span("checkpoint", iteration=it):
            t0 = monotonic_s()
            out = self._checkpoint_write(final, it)
        reg = get_registry()
        reg.counter("checkpoints_total",
                    "Durable training checkpoints written").inc(1)
        reg.counter("checkpoint_ms_total",
                    "Wall ms spent writing checkpoints").inc(
                        (monotonic_s() - t0) * 1000.0)
        return out

    def _checkpoint_write(self, final, it):
        # deterministic tmp name so multi-process jobs (sharded format) agree
        # on the orbax write path; process 0 alone publishes/GCs below
        import jax
        tmp = os.path.join(self.ckpt.directory, f"tmp-{it:09d}")
        os.makedirs(tmp, exist_ok=True)
        try:
            net = self._net()
            if self.ckpt.format == "sharded":
                from ..util.sharded_checkpoint import save_sharded
                save_sharded(net, os.path.join(tmp, self.SHARDED_DIR))
            else:
                ModelSerializer.write_model(net,
                                            os.path.join(tmp, self.MODEL_FILE))
            if jax.process_index() != 0:
                return final  # process 0 publishes the checkpoint dir
            st = dict(self.state)
            # wrapper-ness persists so a restore only pays a factory build
            # (and adopt) when the checkpointed run actually used one; plain
            # networks restore without ever constructing a throwaway model
            st["wrapper"] = self.model is not self._net()
            rng = getattr(net, "_rng", None)
            st["rng"] = None if rng is None else np.asarray(rng).tolist()
            with open(os.path.join(tmp, self.STATE_FILE), "w") as f:
                json.dump(st, f)
            os.replace(tmp, final)  # atomic publish
        except Exception:
            import shutil
            shutil.rmtree(tmp, ignore_errors=True)
            raise
        self._gc()
        return final

    def _gc(self):
        import shutil
        dirs = self._ckpt_dirs()
        for name in dirs[:-self.ckpt.keep_last]:
            shutil.rmtree(os.path.join(self.ckpt.directory, name),
                          ignore_errors=True)
        # orphaned tmp-* dirs are half-written checkpoints from a process
        # that was preempted mid-write; this (single-writer) driver owns the
        # directory, so any tmp-* present outside checkpoint() is garbage
        self._gc_orphans()

    def _try_restore(self):
        self._gc_orphans()
        dirs = self._ckpt_dirs()
        if not dirs:
            self.model = self._factory()
            if getattr(self._net(), "params", None) is None:
                self._net().init()
            return False
        latest = os.path.join(self.ckpt.directory, dirs[-1])
        sharded_dir = os.path.join(latest, self.SHARDED_DIR)
        with open(os.path.join(latest, self.STATE_FILE)) as f:
            self.state = json.load(f)
        if os.path.isdir(sharded_dir):
            from ..util.sharded_checkpoint import restore_sharded
            restored = restore_sharded(sharded_dir)
        else:
            restored = ModelSerializer.restore(
                os.path.join(latest, self.MODEL_FILE))
        self.model = restored
        if self.state.get("wrapper"):
            # the checkpointed run drove a trainer wrapper (ShardedTrainer):
            # rebuild it via the factory — its mesh/ZeRO config reflects
            # THIS process's topology — and adopt the restored network state
            # (canonical updater state re-shards for the current replica
            # count). Plain-network checkpoints never pay this factory build.
            candidate = self._factory()
            if getattr(candidate, "model", None) is not None \
                    and callable(getattr(candidate, "adopt", None)):
                candidate.adopt(restored)
                self.model = candidate
        net = self._net()
        rng = self.state.get("rng")
        if rng is not None:
            import jax.numpy as jnp
            net._rng = jnp.asarray(np.asarray(rng, dtype=np.uint32))
        net.iteration_count = self.state["iteration"]
        net.epoch_count = self.state["epoch"]
        return True

    @property
    def resumed(self):
        return self._restored

    # ------------------------------------------------------------ liveness
    def _register_probe(self):
        """(Re-)register the trainer's health probe + heartbeat state. Runs
        at construction — AFTER _try_restore, so the restore path (which
        rebuilds self.model via adopt and previously surfaced nowhere)
        re-registers too and a resumed run shows up on /healthz //fleet
        immediately, at its restored iteration. A restore primes the
        heartbeat so the probe reports a live (not never-beaten) trainer."""
        if self.monitor is None:
            return
        if self._restored:
            self._touch_beat()
        if self.health_key is not None:
            self.monitor.unregister(self.health_key)
        self.health_key = self.monitor.register_unique("trainer", self._probe)
        return self.health_key

    def unregister_probe(self):
        """Withdraw the liveness probe (a driver shutting the run down)."""
        if self.monitor is not None and self.health_key is not None:
            self.monitor.unregister(self.health_key)
            self.health_key = None

    def _touch_beat(self):
        self._last_beat = monotonic_s()

    def _probe_detail(self):
        """Extra probe fields; subclasses (ElasticTrainer) extend."""
        return {}

    def _probe(self):
        halted = self.health is not None and \
            getattr(self.health, "should_halt", False)
        status = "unhealthy" if halted else "healthy"
        beat_age = None if self._last_beat is None \
            else monotonic_s() - self._last_beat
        detail = {"iteration": self.state["iteration"],
                  "epoch": self.state["epoch"],
                  "resumed": self._restored,
                  "last_step_age_s": beat_age,
                  **self._probe_detail()}
        if halted:
            detail["reason"] = getattr(self.health, "trip_reason", "halted")
        return status, detail

    # ------------------------------------------------------------ training
    def _before_batch(self):
        """Hook run between batches (before each fit_batch). The elastic
        policy (elastic.ElasticTrainer) overrides this with its membership
        poll/re-shard; the base trainer does nothing — keeping ONE fit
        loop so resume/checkpoint/halt fixes apply to every policy."""

    def fit(self, iterator, epochs=1):
        """Train with checkpoints every `frequency` iterations; on resume,
        fast-forwards past the batches the dead process already consumed.
        With a health listener attached, a fatal watchdog condition
        checkpoints once more and raises TrainingHalted."""
        from ..datasets.iterator.base import as_iterator
        it = as_iterator(iterator)
        listeners = getattr(self._net(), "listeners", None)
        if self.health is not None and listeners is not None \
                and self.health not in listeners:
            listeners.append(self.health)
        freq = self.ckpt.frequency
        start_epoch = self.state["epoch"]
        for epoch in range(start_epoch, epochs):
            it.reset()
            skip = self.state["batch"] if epoch == self.state["epoch"] else 0
            b = 0
            for ds in it:
                if b < skip:
                    b += 1
                    continue
                self._before_batch()
                self.model.fit_batch(ds)
                self._touch_beat()
                b += 1
                self.state.update(epoch=epoch, batch=b,
                                  iteration=self.state["iteration"] + 1)
                self._halt_if_unhealthy()
                if freq and self.state["iteration"] % freq == 0:
                    self.checkpoint()
            self.state.update(epoch=epoch + 1, batch=0)
        self.checkpoint()
        return self.model

    def _halt_if_unhealthy(self):
        if self.health is None or not self.health.should_halt:
            return
        from ..optimize.listeners.health import TrainingHalted
        # the fatal update is already applied to the params, so this state
        # is forensics, not a resume point: quarantine it under halt-* and
        # leave the ckpt-* chain ending at the last pre-blow-up checkpoint
        path = self.checkpoint(prefix="halt")
        raise TrainingHalted(self.health.trip_reason,
                             self.state["iteration"], checkpoint_path=path)
