"""Model zoo: the BASELINE.json configs + the reference's embryonic zoo.

Reference: trainedmodels/TrainedModels.java (VGG16); BASELINE configs:
LeNet/MNIST MultiLayerNetwork, ResNet-50 ComputationGraph, GravesLSTM char-RNN.
All built through the public config DSL — these dual as integration tests of
the builder.
"""
from __future__ import annotations

from ..nn.conf.configuration import NeuralNetConfiguration
from ..nn.conf.inputs import InputType
from ..nn.conf.layers import (DenseLayer, OutputLayer, RnnOutputLayer,
                              ConvolutionLayer, SubsamplingLayer,
                              BatchNormalization, ActivationLayer, GravesLSTM,
                              GlobalPoolingLayer)
from ..nn.conf.graph_configuration import ElementWiseVertex
from ..nn.updaters import Adam, Nesterovs
from ..nn.multilayer.network import MultiLayerNetwork
from ..nn.graph.graph import ComputationGraph


def lenet_mnist(seed=12345, updater=None):
    """LeNet-style CNN for MNIST (BASELINE config #1; mirrors the classic DL4J
    LeNet example built on the reference's ConvolutionLayer/SubsamplingLayer)."""
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Nesterovs(learning_rate=0.01, momentum=0.9))
            .weight_init("xavier")
            .list()
            .layer(ConvolutionLayer(kernel_size=(5, 5), stride=(1, 1), n_out=20,
                                    activation="identity"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(kernel_size=(5, 5), stride=(1, 1), n_out=50,
                                    activation="identity"))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=500, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="MCXENT"))
            .input_type(InputType.convolutional(28, 28, 1))
            .build())
    return MultiLayerNetwork(conf)


def cifar_convnet(seed=12345, num_classes=10, updater=None):
    """Small conv net for 32x32x3 CIFAR-format data (mirrors the reference's
    Cifar example scale: two conv/pool blocks + dense head). Gated on the
    committed real-photo fixture (tests/fixtures/cifar_real) in bench.py as
    `real32_test_acc`."""
    conf = (NeuralNetConfiguration.builder()
            .seed(seed)
            .updater(updater or Adam(1e-3))
            .weight_init("relu")
            .list()
            .layer(ConvolutionLayer(kernel_size=(3, 3), stride=(1, 1),
                                    n_out=32, activation="relu",
                                    padding=(1, 1)))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(ConvolutionLayer(kernel_size=(3, 3), stride=(1, 1),
                                    n_out=64, activation="relu",
                                    padding=(1, 1)))
            .layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                    stride=(2, 2)))
            .layer(DenseLayer(n_out=256, activation="relu"))
            .layer(OutputLayer(n_out=num_classes, activation="softmax",
                               loss="MCXENT"))
            .input_type(InputType.convolutional(32, 32, 3))
            .build())
    return MultiLayerNetwork(conf)


def mlp_mnist(seed=12345, hidden=512):
    conf = (NeuralNetConfiguration.builder()
            .seed(seed).updater(Adam(1e-3)).weight_init("relu")
            .list()
            .layer(DenseLayer(n_out=hidden, activation="relu"))
            .layer(DenseLayer(n_out=hidden // 2, activation="relu"))
            .layer(OutputLayer(n_out=10, activation="softmax", loss="MCXENT"))
            .input_type(InputType.feed_forward(784))
            .build())
    return MultiLayerNetwork(conf)


def char_rnn_lstm(vocab_size=80, hidden=256, layers=2, seed=12345, tbptt=50,
                  compute_dtype=None):
    """GravesLSTM char-RNN (BASELINE config #3). compute_dtype="bfloat16"
    runs the gemms on the MXU in bf16 while the LSTM carry and gate math
    accumulate in f32 (nn/layers/recurrent.py:_lstm_scan)."""
    from ..nn.conf.configuration import BackpropType
    b = (NeuralNetConfiguration.builder()
         .seed(seed).updater(Adam(2e-3)).weight_init("xavier")
         .compute_dtype(compute_dtype)
         .list())
    for _ in range(layers):
        b.layer(GravesLSTM(n_out=hidden, activation="tanh"))
    b.layer(RnnOutputLayer(n_out=vocab_size, activation="softmax", loss="MCXENT"))
    b.set_input_type(InputType.recurrent(vocab_size))
    b.backprop_type(BackpropType.TRUNCATED_BPTT)
    b.tbptt_fwd_length(tbptt).tbptt_back_length(tbptt)
    return MultiLayerNetwork(b.build())


def _resnet_conv_block(gb, name, n_in_name, filters, stride, bottleneck=True,
                       project=True):
    """One ResNet v1 bottleneck block: conv1x1 -> conv3x3 -> conv1x1 + skip."""
    f1, f2, f3 = filters
    gb.add_layer(f"{name}_c1", ConvolutionLayer(kernel_size=(1, 1), stride=(stride, stride),
                                                n_out=f1, activation="identity",
                                                convolution_mode="same", has_bias=False),
                 n_in_name)
    gb.add_layer(f"{name}_bn1", BatchNormalization(activation="relu"), f"{name}_c1")
    gb.add_layer(f"{name}_c2", ConvolutionLayer(kernel_size=(3, 3), stride=(1, 1),
                                                n_out=f2, activation="identity",
                                                convolution_mode="same", has_bias=False),
                 f"{name}_bn1")
    gb.add_layer(f"{name}_bn2", BatchNormalization(activation="relu"), f"{name}_c2")
    gb.add_layer(f"{name}_c3", ConvolutionLayer(kernel_size=(1, 1), stride=(1, 1),
                                                n_out=f3, activation="identity",
                                                convolution_mode="same", has_bias=False),
                 f"{name}_bn2")
    gb.add_layer(f"{name}_bn3", BatchNormalization(activation="identity"), f"{name}_c3")
    if project:
        gb.add_layer(f"{name}_proj", ConvolutionLayer(kernel_size=(1, 1),
                                                      stride=(stride, stride), n_out=f3,
                                                      activation="identity",
                                                      convolution_mode="same",
                                                      has_bias=False),
                     n_in_name)
        gb.add_layer(f"{name}_projbn", BatchNormalization(activation="identity"),
                     f"{name}_proj")
        skip = f"{name}_projbn"
    else:
        skip = n_in_name
    gb.add_vertex(f"{name}_add", ElementWiseVertex("add"), f"{name}_bn3", skip)
    gb.add_layer(f"{name}_relu", ActivationLayer(activation="relu"), f"{name}_add")
    return f"{name}_relu"


def resnet50(num_classes=1000, image_size=224, seed=12345, updater=None,
             compute_dtype=None, remat=None):
    """ResNet-50 as a ComputationGraph (BASELINE config #2). Structure follows
    the standard [3,4,6,3] bottleneck stacking; built from the same layer/vertex
    vocabulary the reference exposes (ConvolutionLayer, BatchNormalization,
    ElementWiseVertex add = residual). compute_dtype="bfloat16" enables
    TPU mixed precision (f32 params/BN stats/loss, bf16 conv+matmul);
    remat="convs_and_dots" recomputes the BN/ReLU/residual chains in the
    backward instead of storing them (nn/remat.py)."""
    gb = (NeuralNetConfiguration.builder()
          .seed(seed).updater(updater or Nesterovs(learning_rate=0.1, momentum=0.9))
          .weight_init("relu")
          .compute_dtype(compute_dtype)
          .remat(remat)
          .graph_builder()
          .add_inputs("in"))
    gb.add_layer("stem_conv", ConvolutionLayer(kernel_size=(7, 7), stride=(2, 2),
                                               n_out=64, activation="identity",
                                               convolution_mode="same", has_bias=False),
                 "in")
    gb.add_layer("stem_bn", BatchNormalization(activation="relu"), "stem_conv")
    gb.add_layer("stem_pool", SubsamplingLayer(pooling_type="max", kernel_size=(3, 3),
                                               stride=(2, 2), convolution_mode="same"),
                 "stem_bn")
    prev = "stem_pool"
    stages = [
        ("s2", (64, 64, 256), 3, 1),
        ("s3", (128, 128, 512), 4, 2),
        ("s4", (256, 256, 1024), 6, 2),
        ("s5", (512, 512, 2048), 3, 2),
    ]
    for sname, filters, blocks, stride in stages:
        prev = _resnet_conv_block(gb, f"{sname}b1", prev, filters, stride, project=True)
        for i in range(1, blocks):
            prev = _resnet_conv_block(gb, f"{sname}b{i+1}", prev, filters, 1,
                                      project=False)
    gb.add_layer("avgpool", GlobalPoolingLayer(pooling_type="avg"), prev)
    gb.add_layer("out", OutputLayer(n_out=num_classes, activation="softmax",
                                    loss="MCXENT"), "avgpool")
    gb.set_outputs("out")
    gb.set_input_types(InputType.convolutional(image_size, image_size, 3))
    return ComputationGraph(gb.build())


def transformer_lm(vocab_size=256, d_model=256, n_layers=4, n_heads=4,
                   ffn_mult=4, seed=12345, causal=True, use_pallas=False,
                   compute_dtype=None, updater=None, remat=None):
    """Decoder-only transformer language model — NEW model family beyond the
    reference's 2017 zoo (no attention exists in DL4J v0.7.3; SURVEY.md §5
    names long-context attention as this framework's new capability). Built
    from the same DSL vocabulary as everything else: SelfAttentionLayer
    (optionally the Pallas flash kernel), LayerNormalization (post-norm),
    per-timestep Dense FFN, ElementWiseVertex residuals. Input: one-hot
    [b, t, vocab]; output: next-token softmax per position.
    remat="dots" is the long-context memory dial: saved activations scale
    with n_layers*T*d_model, and recomputing the LN/residual/softmax chains
    in the backward trades idle MXU time for that memory (nn/remat.py)."""
    from ..nn.conf.layers import LayerNormalization, SelfAttentionLayer
    gb = (NeuralNetConfiguration.builder()
          .seed(seed).updater(updater or Adam(3e-4)).weight_init("xavier")
          .compute_dtype(compute_dtype)
          .remat(remat)
          .graph_builder()
          .add_inputs("tokens"))
    gb.add_layer("embed", DenseLayer(n_out=d_model, activation="identity"),
                 "tokens")
    prev = "embed"
    for i in range(n_layers):
        gb.add_layer(f"b{i}_attn",
                     SelfAttentionLayer(n_out=d_model, n_heads=n_heads,
                                        causal=causal, use_pallas=use_pallas,
                                        activation="identity"), prev)
        gb.add_vertex(f"b{i}_res1", ElementWiseVertex("add"), prev, f"b{i}_attn")
        gb.add_layer(f"b{i}_ln1", LayerNormalization(), f"b{i}_res1")
        gb.add_layer(f"b{i}_ffn1", DenseLayer(n_out=d_model * ffn_mult,
                                              activation="relu"), f"b{i}_ln1")
        gb.add_layer(f"b{i}_ffn2", DenseLayer(n_out=d_model,
                                              activation="identity"),
                     f"b{i}_ffn1")
        gb.add_vertex(f"b{i}_res2", ElementWiseVertex("add"), f"b{i}_ln1",
                      f"b{i}_ffn2")
        gb.add_layer(f"b{i}_ln2", LayerNormalization(), f"b{i}_res2")
        prev = f"b{i}_ln2"
    gb.add_layer("out", RnnOutputLayer(n_out=vocab_size, activation="softmax",
                                       loss="MCXENT"), prev)
    gb.set_outputs("out")
    gb.set_input_types(InputType.recurrent(vocab_size))
    return ComputationGraph(gb.build())


def vgg16(num_classes=1000, image_size=224, seed=12345):
    """VGG16 (reference: trainedmodels/TrainedModels.java VGG16)."""
    b = (NeuralNetConfiguration.builder()
         .seed(seed).updater(Nesterovs(learning_rate=0.01, momentum=0.9))
         .weight_init("relu")
         .list())
    cfg = [64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
           512, 512, 512, "M"]
    for v in cfg:
        if v == "M":
            b.layer(SubsamplingLayer(pooling_type="max", kernel_size=(2, 2),
                                     stride=(2, 2)))
        else:
            b.layer(ConvolutionLayer(kernel_size=(3, 3), stride=(1, 1), n_out=v,
                                     activation="relu", convolution_mode="same"))
    b.layer(DenseLayer(n_out=4096, activation="relu"))
    b.layer(DenseLayer(n_out=4096, activation="relu"))
    b.layer(OutputLayer(n_out=num_classes, activation="softmax", loss="MCXENT"))
    b.set_input_type(InputType.convolutional(image_size, image_size, 3))
    return MultiLayerNetwork(b.build())
