"""Pretrained zoo weights + label decoding.

Reference: modelimport trainedmodels/TrainedModelHelper.java:1 (downloads a
zoo architecture's pretrained HDF5 weights, builds the model, returns it
ready for inference) and Utils/ImageNetLabels.java:1 (class-index -> label
names, decodePredictions top-5 table).

TPU build: the same machinery against committed weight fixtures — this
environment has no egress, so ImageNet-scale VGG16 weights cannot be
fetched; what ships is the full pretrained path exercised end to end on a
committed LeNet trained on the real-digit MNIST fixture
(tests/fixtures/pretrained/, built by tools/make_pretrained_fixture.py).
`load_pretrained()` resolves name -> weights file (PRETRAINED_DIR env
overrides, so real downloaded weight archives drop in without code
changes), restores the checkpoint, and `decode_predictions` maps output
distributions through the model's label table like ImageNetLabels does."""
from __future__ import annotations

import json
import os

import numpy as np

_FIXTURE_DIR = os.path.join(os.path.dirname(__file__), os.pardir, os.pardir,
                            "tests", "fixtures", "pretrained")


class Labels:
    """Class-index -> name table (reference: Utils/ImageNetLabels.java)."""

    def __init__(self, names):
        self.names = list(names)

    @staticmethod
    def load(path):
        with open(path) as f:
            return Labels(json.load(f))

    def decode_predictions(self, probs, top=5):
        """[batch, n_classes] -> per-row list of (label, probability),
        descending (ImageNetLabels.decodePredictions)."""
        probs = np.asarray(probs)
        if probs.ndim == 1:
            probs = probs[None]
        out = []
        for row in probs:
            idx = np.argsort(row)[::-1][:top]
            out.append([(self.names[i], float(row[i])) for i in idx])
        return out


def _search_dirs():
    d = os.environ.get("PRETRAINED_DIR")
    return [p for p in (d, _FIXTURE_DIR) if p]


def available_pretrained():
    """Names with a weights archive on this machine (a label table is
    optional — load_pretrained returns labels=None when absent, so callers
    that decode labels must check before using it)."""
    names = set()
    for d in _search_dirs():
        if os.path.isdir(d):
            for f in os.listdir(d):
                if f.endswith(".zip"):
                    names.add(f[:-4])
    return sorted(names)


def load_pretrained(name="lenet_mnist_real", load_updater=False):
    """Restore a ready-for-inference pretrained model + its Labels
    (TrainedModelHelper.loadModel analog). Returns (model, labels) where
    labels is None if no <name>.labels.json sits next to the weights.
    Raises FileNotFoundError with the searched locations when the weights
    are absent."""
    from ..util.model_serializer import ModelSerializer
    searched = []
    for d in _search_dirs():
        zp = os.path.join(d, name + ".zip")
        lp = os.path.join(d, name + ".labels.json")
        searched.append(zp)
        if os.path.exists(zp):
            model = ModelSerializer.restore(zp, load_updater=load_updater)
            labels = Labels.load(lp) if os.path.exists(lp) else None
            return model, labels
    raise FileNotFoundError(
        f"no pretrained weights for {name!r}; searched {searched} "
        f"(set PRETRAINED_DIR to a directory of <name>.zip weight archives)")
