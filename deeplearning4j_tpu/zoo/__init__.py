"""Model zoo: reference architectures + pretrained weight loading.

Reference: deeplearning4j-modelimport trainedmodels/ (TrainedModels.java
architectures, TrainedModelHelper.java weight fetch+restore,
Utils/ImageNetLabels.java label decoding).
"""
from .models import (lenet_mnist, mlp_mnist, char_rnn_lstm, resnet50,
                     transformer_lm, vgg16)
from .pretrained import (Labels, available_pretrained, load_pretrained)

__all__ = ["lenet_mnist", "mlp_mnist", "char_rnn_lstm", "resnet50",
           "transformer_lm", "vgg16", "Labels", "available_pretrained",
           "load_pretrained"]
