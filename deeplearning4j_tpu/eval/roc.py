"""ROC / AUC evaluation (binary and multiclass, thresholded).

Reference: eval/ROC.java, eval/ROCMultiClass.java — threshold-stepped ROC
curve: `thresholdSteps` evenly spaced thresholds in [0,1]; at each threshold
count TP/FP/TN/FN, giving (fpr, tpr) points; AUC by trapezoidal integration.
Same contract here, vectorized over thresholds with numpy.
"""
from __future__ import annotations

import numpy as np


class ROC:
    """Binary ROC. Labels may be single-column {0,1} or two-column one-hot
    (probability of class 1 taken from the last column), matching the
    reference's ROC.eval handling."""

    def __init__(self, threshold_steps=100):
        self.threshold_steps = int(threshold_steps)
        self._scores = []   # P(class=1)
        self._labels = []   # {0,1}

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:  # time series: flatten [b,t,c] -> [b*t,c]
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                m = np.asarray(mask).reshape(-1) > 0
                labels, predictions = labels[m], predictions[m]
        elif mask is not None:
            m = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[m], predictions[m]
        if labels.ndim == 1:
            labels = labels[:, None]
        if predictions.ndim == 1:
            predictions = predictions[:, None]
        # column selection is per-array: a 2-column array is one-hot/softmax
        # (class-1 prob in the last column); a 1-column array is already the
        # {0,1} indicator / P(class 1)
        lab = labels[:, 1] if labels.shape[-1] == 2 else labels[:, 0]
        prob = predictions[:, 1] if predictions.shape[-1] == 2 else predictions[:, 0]
        self._labels.append(lab)
        self._scores.append(prob)

    eval_time_series = eval

    def _collected(self):
        if not self._labels:
            return np.zeros(0), np.zeros(0)
        return np.concatenate(self._labels), np.concatenate(self._scores)

    def get_roc_curve(self):
        """[(threshold, fpr, tpr)] over threshold_steps+1 thresholds."""
        lab, prob = self._collected()
        pos = lab > 0.5
        n_pos, n_neg = pos.sum(), (~pos).sum()
        out = []
        for k in range(self.threshold_steps + 1):
            t = k / self.threshold_steps
            pred_pos = prob >= t
            tp = np.sum(pred_pos & pos)
            fp = np.sum(pred_pos & ~pos)
            tpr = tp / n_pos if n_pos else 0.0
            fpr = fp / n_neg if n_neg else 0.0
            out.append((t, float(fpr), float(tpr)))
        return out

    def get_precision_recall_curve(self):
        lab, prob = self._collected()
        pos = lab > 0.5
        n_pos = pos.sum()
        out = []
        for k in range(self.threshold_steps + 1):
            t = k / self.threshold_steps
            pred_pos = prob >= t
            tp = np.sum(pred_pos & pos)
            fp = np.sum(pred_pos & ~pos)
            prec = tp / (tp + fp) if (tp + fp) else 1.0
            rec = tp / n_pos if n_pos else 0.0
            out.append((t, float(prec), float(rec)))
        return out

    def calculate_auc(self):
        """Trapezoidal AUC over the threshold-stepped curve (reference:
        ROC.calculateAUC)."""
        curve = self.get_roc_curve()
        pts = sorted((fpr, tpr) for _, fpr, tpr in curve)
        auc = 0.0
        for (x0, y0), (x1, y1) in zip(pts[:-1], pts[1:]):
            auc += (x1 - x0) * (y0 + y1) / 2.0
        return float(auc)

    def merge(self, other):
        self._labels.extend(other._labels)
        self._scores.extend(other._scores)
        return self


class ROCMultiClass:
    """One-vs-all ROC per class (reference: eval/ROCMultiClass.java)."""

    def __init__(self, threshold_steps=100):
        self.threshold_steps = int(threshold_steps)
        self._per_class = {}

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
        if mask is not None:
            m = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[m], predictions[m]
        n = labels.shape[-1]
        for c in range(n):
            roc = self._per_class.setdefault(c, ROC(self.threshold_steps))
            roc.eval(labels[:, c], predictions[:, c])

    eval_time_series = eval

    def calculate_auc(self, class_idx):
        return self._per_class[class_idx].calculate_auc()

    def calculate_average_auc(self):
        if not self._per_class:
            return 0.0
        return float(np.mean([r.calculate_auc() for r in self._per_class.values()]))

    def get_roc_curve(self, class_idx):
        return self._per_class[class_idx].get_roc_curve()

    def merge(self, other):
        for c, r in other._per_class.items():
            if c in self._per_class:
                self._per_class[c].merge(r)
            else:
                self._per_class[c] = r
        return self


class RegressionEvaluation:
    """Per-column regression metrics: MSE, MAE, RMSE, RSE, R^2, correlation
    (reference: eval/RegressionEvaluation.java)."""

    def __init__(self, n_columns=None, column_names=None):
        self.column_names = column_names
        self.n_columns = n_columns or (len(column_names) if column_names else None)
        self._labels = []
        self._preds = []

    def eval(self, labels, predictions, mask=None):
        labels = np.asarray(labels, np.float64)
        predictions = np.asarray(predictions, np.float64)
        if labels.ndim == 3:
            labels = labels.reshape(-1, labels.shape[-1])
            predictions = predictions.reshape(-1, predictions.shape[-1])
            if mask is not None:
                m = np.asarray(mask).reshape(-1) > 0
                labels, predictions = labels[m], predictions[m]
        elif mask is not None:
            m = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[m], predictions[m]
        if labels.ndim == 1:
            labels = labels[:, None]
            predictions = predictions[:, None]
        self.n_columns = self.n_columns or labels.shape[-1]
        self._labels.append(labels)
        self._preds.append(predictions)

    eval_time_series = eval

    def _col(self):
        return np.concatenate(self._labels), np.concatenate(self._preds)

    def mean_squared_error(self, col):
        y, p = self._col()
        return float(np.mean((y[:, col] - p[:, col]) ** 2))

    def mean_absolute_error(self, col):
        y, p = self._col()
        return float(np.mean(np.abs(y[:, col] - p[:, col])))

    def root_mean_squared_error(self, col):
        return float(np.sqrt(self.mean_squared_error(col)))

    def relative_squared_error(self, col):
        y, p = self._col()
        num = np.sum((y[:, col] - p[:, col]) ** 2)
        den = np.sum((y[:, col] - y[:, col].mean()) ** 2)
        return float(num / den) if den else float("inf")

    def r_squared(self, col):
        return 1.0 - self.relative_squared_error(col)

    def pearson_correlation(self, col):
        y, p = self._col()
        sy, sp = y[:, col].std(), p[:, col].std()
        if sy == 0 or sp == 0:
            return 0.0
        return float(np.corrcoef(y[:, col], p[:, col])[0, 1])

    def average_mean_squared_error(self):
        return float(np.mean([self.mean_squared_error(c) for c in range(self.n_columns)]))

    def average_mean_absolute_error(self):
        return float(np.mean([self.mean_absolute_error(c) for c in range(self.n_columns)]))

    def average_r_squared(self):
        return float(np.mean([self.r_squared(c) for c in range(self.n_columns)]))

    def stats(self):
        names = self.column_names or [f"col_{i}" for i in range(self.n_columns)]
        lines = ["column | MSE | MAE | RMSE | RSE | R^2 | corr"]
        for c, name in enumerate(names):
            lines.append(
                f"{name} | {self.mean_squared_error(c):.6g} | "
                f"{self.mean_absolute_error(c):.6g} | "
                f"{self.root_mean_squared_error(c):.6g} | "
                f"{self.relative_squared_error(c):.6g} | "
                f"{self.r_squared(c):.6g} | {self.pearson_correlation(c):.6g}")
        return "\n".join(lines)

    def merge(self, other):
        self._labels.extend(other._labels)
        self._preds.extend(other._preds)
        self.n_columns = self.n_columns or other.n_columns
        return self
