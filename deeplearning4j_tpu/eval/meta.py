"""Prediction-error introspection metadata.

Reference: eval/meta/ (RecordMetaData plumbing) + Evaluation.java's
getPredictionErrors()/getPredictionsByActualClass()/getPredictionByPredictedClass
— after evaluation, pull out WHICH examples were misclassified and as what,
for debugging datasets rather than just scoring them.
"""
from __future__ import annotations


class Prediction:
    """One recorded prediction (reference: eval/meta/Prediction.java)."""

    __slots__ = ("actual", "predicted", "record_meta")

    def __init__(self, actual, predicted, record_meta=None):
        self.actual = int(actual)
        self.predicted = int(predicted)
        self.record_meta = record_meta

    def __repr__(self):
        return (f"Prediction(actual={self.actual}, predicted={self.predicted}"
                f", meta={self.record_meta!r})")

    def __eq__(self, other):
        return (isinstance(other, Prediction)
                and self.actual == other.actual
                and self.predicted == other.predicted
                and self.record_meta == other.record_meta)
