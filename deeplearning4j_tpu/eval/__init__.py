"""Evaluation suite (reference: deeplearning4j-nn eval/ package —
Evaluation.java, ConfusionMatrix.java, ROC.java, ROCMultiClass.java,
RegressionEvaluation.java, IEvaluation.java)."""
from .evaluation import Evaluation, ConfusionMatrix
from .roc import ROC, ROCMultiClass, RegressionEvaluation

__all__ = ["Evaluation", "ConfusionMatrix", "ROC", "ROCMultiClass",
           "RegressionEvaluation"]
