"""Classification evaluation: accuracy/precision/recall/F1 + confusion matrix.

Reference: eval/Evaluation.java, eval/ConfusionMatrix.java. Supports masked
time-series evaluation (evalTimeSeries) like the reference.
"""
from __future__ import annotations

import numpy as np


class ConfusionMatrix:
    def __init__(self, n_classes):
        self.matrix = np.zeros((n_classes, n_classes), dtype=np.int64)

    def add(self, actual, predicted, count=1):
        self.matrix[actual, predicted] += count

    def get_count(self, actual, predicted):
        return int(self.matrix[actual, predicted])

    def __str__(self):
        return str(self.matrix)


class Evaluation:
    def __init__(self, n_classes=None, labels=None, top_n=1):
        """top_n > 1 also tracks top-N accuracy (reference: Evaluation.java
        topN constructor + topNAccuracy())."""
        self.n_classes = n_classes
        self.label_names = labels
        self.confusion = None
        self.top_n = int(top_n)
        self._top_n_correct = 0
        self._top_n_total = 0
        self._predictions = []  # Prediction meta (reference: eval/meta/)

    def _ensure(self, n):
        if self.confusion is None:
            self.n_classes = self.n_classes or n
            self.confusion = ConfusionMatrix(self.n_classes)

    def eval(self, labels, predictions, mask=None, record_meta_data=None):
        """labels/predictions: [batch, n_classes] probabilities/one-hot, or
        [batch, time, n_classes] with mask [batch, time]. record_meta_data:
        optional per-example metadata recorded onto Prediction objects for
        error introspection (reference: Evaluation.java eval(...,
        List<RecordMetaData>) + eval/meta/Prediction.java)."""
        labels = np.asarray(labels)
        predictions = np.asarray(predictions)
        if labels.ndim == 3:
            b, t, c = labels.shape
            labels = labels.reshape(b * t, c)
            predictions = predictions.reshape(b * t, c)
            if mask is not None:
                m = np.asarray(mask).reshape(b * t) > 0
                labels, predictions = labels[m], predictions[m]
            record_meta_data = None  # per-example meta is 2-D only
        elif mask is not None:
            m = np.asarray(mask).reshape(-1) > 0
            labels, predictions = labels[m], predictions[m]
            if record_meta_data is not None:
                record_meta_data = [r for r, keep in zip(record_meta_data, m)
                                    if keep]
        self._ensure(labels.shape[-1])
        actual = np.argmax(labels, axis=-1)
        pred = np.argmax(predictions, axis=-1)
        np.add.at(self.confusion.matrix, (actual, pred), 1)
        if self.top_n > 1:
            k = min(self.top_n, predictions.shape[-1])
            topk = np.argpartition(-predictions, k - 1, axis=-1)[:, :k]
            self._top_n_correct += int(np.sum(topk == actual[:, None]))
            self._top_n_total += len(actual)
        if record_meta_data is not None:
            from .meta import Prediction
            for a, pr, meta in zip(actual, pred, record_meta_data):
                self._predictions.append(Prediction(a, pr, meta))

    def eval_time_series(self, labels, predictions, mask=None):
        self.eval(labels, predictions, mask)

    # ---- metrics ----------------------------------------------------------
    def _tp(self, i):
        return self.confusion.matrix[i, i]

    def _fp(self, i):
        return self.confusion.matrix[:, i].sum() - self._tp(i)

    def _fn(self, i):
        return self.confusion.matrix[i, :].sum() - self._tp(i)

    def accuracy(self):
        m = self.confusion.matrix
        total = m.sum()
        return float(np.trace(m) / total) if total else 0.0

    def precision(self, i=None):
        if i is not None:
            d = self._tp(i) + self._fp(i)
            return float(self._tp(i) / d) if d else 0.0
        vals = [self.precision(c) for c in range(self.n_classes)
                if (self.confusion.matrix[c, :].sum() + self.confusion.matrix[:, c].sum()) > 0]
        return float(np.mean(vals)) if vals else 0.0

    def recall(self, i=None):
        if i is not None:
            d = self._tp(i) + self._fn(i)
            return float(self._tp(i) / d) if d else 0.0
        vals = [self.recall(c) for c in range(self.n_classes)
                if self.confusion.matrix[c, :].sum() > 0]
        return float(np.mean(vals)) if vals else 0.0

    def f1(self, i=None):
        p, r = self.precision(i), self.recall(i)
        return 2 * p * r / (p + r) if (p + r) else 0.0

    def top_n_accuracy(self):
        """Fraction of examples whose true class is in the top-N predictions
        (reference: Evaluation.java topNAccuracy())."""
        if self.top_n <= 1:
            return self.accuracy()
        return (self._top_n_correct / self._top_n_total
                if self._top_n_total else 0.0)

    # ---- prediction-error introspection (reference: eval/meta/) -----------
    def get_prediction_errors(self):
        return [p for p in self._predictions if p.actual != p.predicted]

    def get_predictions_by_actual_class(self, i):
        return [p for p in self._predictions if p.actual == int(i)]

    def get_predictions_by_predicted_class(self, i):
        return [p for p in self._predictions if p.predicted == int(i)]

    def false_positive_rate(self, i):
        tn = self.confusion.matrix.sum() - self._tp(i) - self._fp(i) - self._fn(i)
        d = self._fp(i) + tn
        return float(self._fp(i) / d) if d else 0.0

    def stats(self):
        lines = [
            "========================= Evaluation =========================",
            f" Examples:  {int(self.confusion.matrix.sum())}",
            f" Accuracy:  {self.accuracy():.4f}",
            f" Precision: {self.precision():.4f}",
            f" Recall:    {self.recall():.4f}",
            f" F1 Score:  {self.f1():.4f}",
            "Confusion matrix (rows=actual, cols=predicted):",
            str(self.confusion),
        ]
        return "\n".join(lines)

    def merge(self, other):
        if other.confusion is not None:
            self._ensure(other.n_classes)
            self.confusion.matrix += other.confusion.matrix
        self._top_n_correct += other._top_n_correct
        self._top_n_total += other._top_n_total
        self._predictions.extend(other._predictions)
        return self
