"""TrainingHealthListener: the training-run watchdog.

A NaN loss used to train silently to completion — every iteration after the
first non-finite update is wasted accelerator time, and the checkpoint
driver would happily persist the corpse. This listener watches each
iteration for:

- **NaN/Inf loss** (always) and, with `check_gradients=True`, NaN/Inf in
  the gradient pytree (costs a device sync per iteration — opt-in);
- **loss divergence**: loss > `divergence_factor` x rolling best (+ a small
  absolute margin so near-zero losses don't flap), held for
  `divergence_patience` consecutive iterations;
- **step-time regression**: the recent median iteration wall time exceeds
  `step_time_factor` x the baseline median established over the first
  window (a quiet way to notice thermal throttling, host contention, or an
  accidentally-recompiling step).

Each detection increments a registry counter (`training_nan_total`,
`training_divergence_total`, `training_step_time_regressions_total`) — the
series AlertEngine's `default_training_rules()` fire on — logs a structured
record inside the current iteration span (so /logs correlates with /trace),
and reports through a HealthMonitor as the `trainer` component. Fatal
conditions (per `halt_on`) additionally arm `should_halt`, which
FaultTolerantTrainer checks every batch to checkpoint-and-halt instead of
burning TPU hours on a dead run.
"""
from __future__ import annotations

import collections
import math
import statistics

from . import IterationListener
from ...util.time_source import monotonic_s


class TrainingHalted(RuntimeError):
    """Raised by FaultTolerantTrainer when its health listener trips a
    fatal condition; carries the reason and the final checkpoint path."""

    def __init__(self, reason, iteration, checkpoint_path=None):
        super().__init__(
            f"training halted at iteration {iteration}: {reason}"
            + (f" (checkpoint: {checkpoint_path})" if checkpoint_path else ""))
        self.reason = reason
        self.iteration = iteration
        self.checkpoint_path = checkpoint_path


class TrainingHealthListener(IterationListener):
    FATAL = ("nan_loss", "nan_gradient", "divergence")

    def __init__(self, *, health=None, registry=None, logger=None,
                 component="trainer", check_gradients=False,
                 divergence_factor=10.0, divergence_margin=1.0,
                 divergence_patience=3, step_time_factor=3.0,
                 step_time_window=20, halt_on=FATAL):
        if registry is None:
            from ...telemetry.registry import get_registry
            registry = get_registry()
        if logger is None:
            from ...telemetry.logging import get_logger
            logger = get_logger()
        self.health = health
        self.logger = logger
        self.component = str(component)
        self.check_gradients = bool(check_gradients)
        self.wants_gradients = self.check_gradients  # keep grads on device
        self.divergence_factor = float(divergence_factor)
        self.divergence_margin = float(divergence_margin)
        self.divergence_patience = max(1, int(divergence_patience))
        self.step_time_factor = float(step_time_factor)
        self.step_time_window = max(2, int(step_time_window))
        self.halt_on = tuple(halt_on)
        self._nan = registry.counter(
            "training_nan_total", "Non-finite loss/gradient detections")
        self._div = registry.counter(
            "training_divergence_total", "Loss-divergence detections")
        self._regress = registry.counter(
            "training_step_time_regressions_total",
            "Step-time regression detections")
        # run state
        self.best_loss = None
        self.last_loss = None
        self.last_iteration = 0
        self._diverged_streak = 0
        self._last_mono = None
        self._baseline_times = []          # first window of step times
        self._recent_times = collections.deque(maxlen=self.step_time_window)
        self.step_time_regressed = False
        self.trip_reason = None            # first fatal condition seen
        if self.health is not None:
            self.health.register(self.component, self._probe)

    # ---- watchdog ----------------------------------------------------------
    @property
    def should_halt(self):
        return self.trip_reason is not None and self.trip_reason in self.halt_on

    def _trip(self, reason, iteration, **fields):
        """First fatal detection only: a persistent NaN must not log one
        error per subsequent iteration (evicting the /logs ring of the
        context around the blow-up) — returns whether this call tripped."""
        if self.trip_reason is not None:
            return False
        self.trip_reason = reason
        self.logger.error(f"training_{reason}", component=self.component,
                          iteration=iteration, **fields)
        return True

    def iteration_done(self, model, iteration):
        self.last_iteration = iteration
        self._observe_step_time(iteration)
        try:
            loss = float(model.score_value)
        except (TypeError, ValueError):
            loss = None
        if loss is not None:
            self.last_loss = loss
            if not math.isfinite(loss):
                if self._trip("nan_loss", iteration, loss=loss):
                    self._nan.inc(1)    # one detection, not one per step
            else:
                self._check_divergence(loss, iteration)
        if self.check_gradients and self.trip_reason is None:
            self._check_gradients(model, iteration)

    def _check_divergence(self, loss, iteration):
        if self.best_loss is None or loss < self.best_loss:
            self.best_loss = loss
            self._diverged_streak = 0
            return
        bound = self.best_loss * self.divergence_factor \
            if self.best_loss > 0 else 0.0
        if loss > bound + self.divergence_margin:
            self._diverged_streak += 1
            if self._diverged_streak >= self.divergence_patience:
                if self._trip("divergence", iteration, loss=loss,
                              best=self.best_loss):
                    self._div.inc(1)
        else:
            self._diverged_streak = 0

    def _check_gradients(self, model, iteration):
        grads = getattr(model, "last_gradients", None)
        if grads is None:
            return
        import jax
        import numpy as np
        for leaf in jax.tree_util.tree_leaves(grads):
            if not bool(np.all(np.isfinite(np.asarray(leaf)))):
                if self._trip("nan_gradient", iteration):
                    self._nan.inc(1)
                return

    def _observe_step_time(self, iteration):
        now = monotonic_s()
        if self._last_mono is None:
            self._last_mono = now
            return
        dt_ms = (now - self._last_mono) * 1000.0
        self._last_mono = now
        if len(self._baseline_times) < self.step_time_window:
            self._baseline_times.append(dt_ms)
            return
        self._recent_times.append(dt_ms)
        if len(self._recent_times) < self._recent_times.maxlen:
            return
        baseline = statistics.median(self._baseline_times)
        recent = statistics.median(self._recent_times)
        regressed = baseline > 0 and recent > self.step_time_factor * baseline
        if regressed and not self.step_time_regressed:
            self._regress.inc(1)
            self.logger.warning("training_step_time_regression",
                                component=self.component,
                                iteration=iteration,
                                baseline_ms=baseline, recent_ms=recent)
        self.step_time_regressed = regressed

    # ---- health probe ------------------------------------------------------
    def _probe(self):
        detail = {"iteration": self.last_iteration,
                  "last_loss": self.last_loss, "best_loss": self.best_loss}
        if self.trip_reason is not None:
            return "unhealthy", {**detail, "reason": self.trip_reason}
        if self.step_time_regressed or self._diverged_streak:
            return "degraded", {**detail,
                                "reason": ("step_time_regression"
                                           if self.step_time_regressed
                                           else "loss_rising")}
        return "healthy", detail
