"""Training listener SPI + stock listeners.

Reference: optimize/api/IterationListener.java, TrainingListener.java (epoch &
pass hooks), impls in optimize/listeners/: ScoreIterationListener,
PerformanceListener (samples/sec :99-102), CollectScoresIterationListener,
ParamAndGradientIterationListener, ComposableIterationListener.
"""
from __future__ import annotations

from ...util.time_source import monotonic_s


class IterationListener:
    """Hook called after every parameter update (reference:
    optimize/api/IterationListener.java)."""

    def iteration_done(self, model, iteration):
        pass

    def on_epoch_start(self, model):
        pass

    def on_epoch_end(self, model):
        pass


TrainingListener = IterationListener  # epoch hooks included above


class ScoreIterationListener(IterationListener):
    """(reference: optimize/listeners/ScoreIterationListener.java)"""

    def __init__(self, print_iterations=10, log_fn=print):
        self.print_iterations = max(1, int(print_iterations))
        self.log_fn = log_fn

    def iteration_done(self, model, iteration):
        if iteration % self.print_iterations == 0:
            self.log_fn(f"Score at iteration {iteration} is {model.score_value}")


class PerformanceListener(IterationListener):
    """Throughput reporting (reference:
    optimize/listeners/PerformanceListener.java:99-102 — samples/sec,
    batches/sec, iteration time)."""

    def __init__(self, frequency=1, report_batch=True, report_sample=True,
                 log_fn=print, registry=None):
        self.frequency = max(1, int(frequency))
        self.report_batch = report_batch
        self.report_sample = report_sample
        self.log_fn = log_fn
        self._last_time = None
        self._last_iter = 0
        self._samples_since = 0
        # None (not NaN) until the first measured interval: a snapshot
        # serialized before any measurement must emit null, never a bare
        # NaN token that JSON.parse rejects
        self.last_samples_per_sec = None
        self.last_batches_per_sec = None
        self.last_iteration_ms = None
        # central-registry mirror (telemetry.MetricsRegistry): the same
        # throughput numbers this listener logs become scrapeable gauges and
        # a latency histogram instead of private fields only
        self.registry = registry
        if registry is not None:
            self._reg_samples = registry.counter(
                "training_samples_total", "Example rows trained on")
            self._reg_iter_ms = registry.histogram(
                "training_iteration_ms", "Wall ms per training iteration")
            self._reg_sps = registry.gauge(
                "training_samples_per_sec", "Recent training throughput")

    def record_batch_size(self, n):
        self._samples_since += int(n)
        if self.registry is not None:
            self._reg_samples.inc(int(n))

    def iteration_done(self, model, iteration):
        now = monotonic_s()
        if self._last_time is None:
            self._last_time = now
            self._last_iter = iteration
            return
        if (iteration - self._last_iter) % self.frequency == 0:
            dt = now - self._last_time
            iters = iteration - self._last_iter
            if dt > 0 and iters > 0:
                self.last_batches_per_sec = iters / dt
                self.last_iteration_ms = 1000.0 * dt / iters
                if self._samples_since:
                    self.last_samples_per_sec = self._samples_since / dt
                if self.registry is not None:
                    self._reg_iter_ms.observe(self.last_iteration_ms)
                    if self._samples_since:
                        self._reg_sps.set(self.last_samples_per_sec)
                msg = (f"iteration {iteration}: {self.last_iteration_ms:.2f} ms/iter, "
                       f"{self.last_batches_per_sec:.2f} batches/sec")
                if self._samples_since:
                    msg += f", {self.last_samples_per_sec:.1f} samples/sec"
                self.log_fn(msg)
            self._last_time = now
            self._last_iter = iteration
            self._samples_since = 0


class CollectScoresIterationListener(IterationListener):
    """(reference: optimize/listeners/CollectScoresIterationListener.java)"""

    def __init__(self, frequency=1):
        self.frequency = max(1, int(frequency))
        self.scores = []  # list of (iteration, score)

    def iteration_done(self, model, iteration):
        if iteration % self.frequency == 0:
            self.scores.append((iteration, model.score_value))


class ParamAndGradientIterationListener(IterationListener):
    """Collects parameter norm stats per iteration (reference:
    optimize/listeners/ParamAndGradientIterationListener.java)."""

    def __init__(self, frequency=1):
        import numpy as np
        self._np = np
        self.frequency = max(1, int(frequency))
        self.records = []

    def iteration_done(self, model, iteration):
        if iteration % self.frequency != 0:
            return
        np = self._np
        rec = {"iteration": iteration, "score": model.score_value}
        for name, p in model.param_table().items():
            a = np.asarray(p)
            rec[f"{name}.mean_mag"] = float(np.mean(np.abs(a)))
        self.records.append(rec)


class ComposableIterationListener(IterationListener):
    """(reference: optimize/listeners/ComposableIterationListener.java)"""

    def __init__(self, *listeners):
        self.listeners = list(listeners)

    def iteration_done(self, model, iteration):
        for l in self.listeners:
            l.iteration_done(model, iteration)

    def on_epoch_start(self, model):
        for l in self.listeners:
            l.on_epoch_start(model)

    def on_epoch_end(self, model):
        for l in self.listeners:
            l.on_epoch_end(model)


from .health import TrainingHalted, TrainingHealthListener  # noqa: E402


def resolve_listeners(listeners):
    out = []
    for l in listeners:
        if isinstance(l, (list, tuple)):
            out.extend(resolve_listeners(l))
        elif l is not None:
            out.append(l)
    return out
